"""Runtime thread-sanitizer harness: instrumented locks, live
lock-order graph, hold budgets, Perfetto export.

The runtime twin of the JX120 static lock-order checker
(``tools/jaxlint/concurrency.py``): static analysis sees the locks the
AST names; this harness sees the locks the PROCESS actually takes, in
the order it actually takes them, across the tier-1 suite and the
smoke drills. :func:`install` patches ``threading.Lock``/``RLock``
with :class:`SanitizedLock` factories, so every lock created AFTER the
patch (engines, routers, registries, spools, stdlib queues) records:

- **acquisition-order edges** — acquiring B while holding A adds edge
  A→B to a process-wide digraph. Lock identity is lockdep-style: the
  CREATION SITE (``file:line``), so every ``Histogram._lock`` instance
  is one node and a cross-instance ABBA still closes a cycle.
  :meth:`ThreadCheck.check_acyclic` raises :class:`LockOrderError`
  naming the cycle path — the teardown assertion of the
  ``DVTPU_THREADCHECK=1`` pytest fixture and the ``--smoke`` CLI.
- **hold-budget violations** — a lock held longer than ``budget_s``
  (default 1.0, ``DVTPU_THREADCHECK_BUDGET_S``) almost certainly sat
  across a blocking syscall (I/O, subprocess, compile) — JX119's
  runtime shadow. Violations are recorded and exported, not fatal:
  some long holds are sanctioned (the compile-cache build lock,
  documented in ``serve/compile_cache.py``).
- **hold timeline** — completed holds land in a bounded ring and
  export as Chrome-trace ``"X"`` events (one row per thread), so the
  graph JSON loads in Perfetto beside the PR 11 span spools
  (``tools/trace_merge.py`` artifacts) and the lock story lines up
  with the span story on one timeline. The edge list + violations
  ride in the export's ``metadata.lockGraph`` block.

Partial instrumentation is inherent and fine: locks created before
:func:`install` (interpreter/jax import time) are invisible; the tiers
this harness exists for (serve/resilience/obs/data) construct their
locks per object, after the patch.

Surfaces:

- ``DVTPU_THREADCHECK=1 pytest ...`` — tests/conftest.py installs the
  sanitizer for the whole session, asserts acyclicity at teardown, and
  exports the graph (``DVTPU_THREADCHECK_EXPORT`` or
  ``logs/lockgraph-<pid>.json``; a ``DVTPU_TRACE_SPOOL`` dir wins so
  the graph lands beside the spools).
- ``python -m tools.jaxlint.threadcheck --smoke`` — the `make check`
  gate: a real engine+router lifecycle (toy models, CPU) under the
  sanitizer, acyclic graph asserted, export written.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from pathlib import Path

__all__ = [
    "LockOrderError",
    "SanitizedLock",
    "ThreadCheck",
    "get_active",
    "install",
    "uninstall",
]

# the REAL factories, bound at import time: the sanitizer's own state
# must never run through its own instrumentation (recursion), and
# uninstall() must restore exactly these
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_TLS = threading.local()  # per-thread held-lock stack


class LockOrderError(AssertionError):
    """A cycle in the observed lock-acquisition graph — two threads
    can interleave into a deadlock along the recorded edges."""


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _thread_name() -> str:
    """Current thread's name WITHOUT threading.current_thread(): for a
    foreign (C-born, e.g. XLA pool) thread that call mints a
    _DummyThread, whose Event->Condition->Lock() construction re-enters
    the patched factory and recurses to death. Read the registry
    directly instead; unregistered threads get an ident-based name."""
    ident = threading.get_ident()
    t = threading._active.get(ident)  # noqa: SLF001 (read-only peek)
    return t.name if t is not None else f"thread-{ident}"


def _creation_site() -> str:
    """``file:line`` of the frame that called the lock factory,
    skipping this module and threading internals — the lockdep-style
    lock-class identity."""
    skip = (__file__, threading.__file__)
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename in skip:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fname = f.f_code.co_filename
    try:
        fname = str(Path(fname).resolve().relative_to(Path.cwd()))
    except ValueError:
        fname = Path(fname).name
    return f"{fname}:{f.f_lineno}"


class ThreadCheck:
    """Process-wide lock-order graph + hold accounting."""

    def __init__(self, budget_s: float = 1.0,
                 hold_capacity: int = 4096):
        self._mu = _ORIG_LOCK()
        self.budget_s = float(budget_s)
        self.nodes: dict[str, str] = {}          # name -> kind
        # (src, dst) -> {count, threads, first_site}
        self.edges: dict[tuple[str, str], dict] = {}
        self.violations: list[dict] = []
        self._holds: deque[dict] = deque(maxlen=hold_capacity)
        self.dropped_holds = 0
        self._epoch = time.perf_counter()
        self.epoch_wall = time.time()

    # -- recording (called from SanitizedLock) ---------------------------
    def _on_create(self, name: str, kind: str) -> None:
        with self._mu:
            self.nodes.setdefault(name, kind)

    def _on_acquired(self, lock: "SanitizedLock", site: str) -> None:
        stack = _held_stack()
        thread = _thread_name()
        with self._mu:
            for held, _t0 in stack:
                if held.name == lock.name:
                    continue  # same lock class re-entered via RLock
                e = self.edges.get((held.name, lock.name))
                if e is None:
                    e = self.edges[(held.name, lock.name)] = {
                        "count": 0, "threads": set(),
                        "first_site": site}
                e["count"] += 1
                e["threads"].add(thread)
        stack.append((lock, time.perf_counter()))

    def _on_released(self, lock: "SanitizedLock") -> None:
        stack = _held_stack()
        t1 = time.perf_counter()
        entry = None
        for i in range(len(stack) - 1, -1, -1):  # non-LIFO tolerated
            if stack[i][0] is lock:
                entry = stack.pop(i)
                break
        if entry is None:
            # cross-thread release (threading.Lock permits the
            # hand-off pattern): pop the ACQUIRER's recorded entry —
            # left in place it would seed a bogus order edge from this
            # lock to everything that thread acquires afterwards, and
            # eventually a spurious cycle. List ops are GIL-atomic, so
            # mutating the other thread's stack here is safe.
            other = lock._hold_stack
            if other is not None and other is not stack:
                for i in range(len(other) - 1, -1, -1):
                    if other[i][0] is lock:
                        entry = other.pop(i)
                        break
        if entry is None:
            return  # released by a thread that never acquired: ignore
        t0 = entry[1]
        dur = t1 - t0
        tid = threading.get_ident()
        tname = _thread_name()
        rec = {"name": lock.name, "ts": t0 - self._epoch, "dur": dur,
               "tid": tid, "tname": tname}
        with self._mu:
            if len(self._holds) >= self._holds.maxlen:
                self.dropped_holds += 1
            self._holds.append(rec)
            if dur > self.budget_s:
                self.violations.append({
                    "lock": lock.name, "held_s": round(dur, 4),
                    "budget_s": self.budget_s,
                    "thread": tname,
                    "note": "held across a blocking call "
                            "(I/O / subprocess / compile)"})

    # -- analysis --------------------------------------------------------
    def graph(self) -> dict:
        """JSON-able view: nodes, edges (with counts/threads/sites),
        violations — the shape the tests pin."""
        with self._mu:
            return {
                "nodes": [{"name": n, "kind": k}
                          for n, k in sorted(self.nodes.items())],
                "edges": [{"src": a, "dst": b,
                           "count": e["count"],
                           "threads": sorted(e["threads"]),
                           "first_site": e["first_site"]}
                          for (a, b), e in sorted(self.edges.items())],
                "violations": list(self.violations),
                "budget_s": self.budget_s,
            }

    def find_cycle(self) -> list[str] | None:
        """One cycle path [a, b, ..., a] in the edge digraph, or
        None."""
        with self._mu:
            adj: dict[str, list[str]] = {}
            for (a, b) in self.edges:
                adj.setdefault(a, []).append(b)
                adj.setdefault(b, [])
        WHITE, GREY, BLACK = 0, 1, 2
        color = {v: WHITE for v in adj}
        parent: dict[str, str] = {}
        for root in sorted(adj):
            if color[root] != WHITE:
                continue
            stack = [(root, iter(sorted(adj[root])))]
            color[root] = GREY
            while stack:
                v, it = stack[-1]
                advanced = False
                for w in it:
                    if color[w] == WHITE:
                        color[w] = GREY
                        parent[w] = v
                        stack.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if color[w] == GREY:  # back edge: cycle
                        path = [w, v]
                        cur = v
                        while cur != w:
                            cur = parent[cur]
                            path.append(cur)
                        path.reverse()
                        return path
                if not advanced:
                    color[v] = BLACK
                    stack.pop()
        return None

    def check_acyclic(self) -> None:
        cycle = self.find_cycle()
        if cycle is not None:
            raise LockOrderError(
                "lock-order cycle observed at runtime: "
                + " -> ".join(cycle)
                + " — these locks were acquired in inconsistent order "
                "by live threads (potential deadlock); see the "
                "exported lock graph for sites")

    # -- export ----------------------------------------------------------
    def export(self, path: str | Path) -> Path:
        """Perfetto-loadable Chrome-trace JSON: completed lock holds as
        per-thread ``"X"`` events, the acquisition graph + violations
        in ``metadata.lockGraph`` — written beside the PR 11 span
        spools so one Perfetto session holds both stories."""
        with self._mu:
            holds = list(self._holds)
            dropped = self.dropped_holds
        pid = os.getpid()
        events: list[dict] = []
        threads: dict[int, str] = {}
        for h in holds:
            threads.setdefault(h["tid"], h["tname"])
            events.append({
                "name": h["name"], "cat": "lock", "ph": "X",
                "ts": round(h["ts"] * 1e6, 3),
                "dur": round(h["dur"] * 1e6, 3),
                "pid": pid, "tid": h["tid"],
            })
        for tid, tname in threads.items():
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": "threadcheck locks"}})
        body = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "threadcheck": 1,
                "pid": pid,
                "epoch_wall": self.epoch_wall,
                "dropped_holds": dropped,
                "complete": dropped == 0,
                "lockGraph": self.graph(),
            },
        }
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(path.name + f".tmp.{pid}")
        tmp.write_text(json.dumps(body))
        os.replace(tmp, path)
        return path


class SanitizedLock:
    """Drop-in ``threading.Lock``/``RLock`` stand-in recording
    acquisition order + hold durations into a :class:`ThreadCheck`.
    ``kind="RLock"`` tracks owner/count so reentrant re-acquires
    neither self-edge nor double-push."""

    def __init__(self, state: ThreadCheck, kind: str = "Lock",
                 name: str | None = None):
        self._state = state
        self.kind = kind
        self.name = name if name is not None else _creation_site()
        self._inner = _ORIG_LOCK() if kind == "Lock" else _ORIG_RLOCK()
        self._owner: int | None = None
        self._count = 0
        self._hold_stack: list | None = None  # acquirer's TLS stack
        state._on_create(self.name, kind)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if self.kind == "RLock" and self._owner == me:
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                self._count += 1
            return ok
        # the acquire site only matters when this acquisition creates
        # an order edge, i.e. when the thread already holds another
        # lock — skip the frame walk on the (overwhelmingly common)
        # bare acquisition so instrumentation doesn't inflate the very
        # hold durations the budget measures
        site = _acquire_site() if _held_stack() else ""
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._count = 1
            self._state._on_acquired(self, site)
            # remember whose stack holds the entry: a cross-thread
            # release (legal on a plain Lock) must pop it from THERE
            self._hold_stack = _held_stack()
        return ok

    def release(self):
        me = threading.get_ident()
        if self.kind == "RLock":
            if self._owner != me:
                # not the owner: let the real RLock raise WITHOUT
                # touching _owner/_count — clobbering them first would
                # corrupt the actual owner's reentrancy bookkeeping
                self._inner.release()  # raises RuntimeError
                return
            if self._count > 1:
                self._count -= 1
                self._inner.release()
                return
        self._owner = None
        self._count = 0
        self._state._on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib os.register_at_fork hooks (concurrent.futures, logging)
        # reinit their module locks in the child; delegate + reset
        self._inner._at_fork_reinit()
        self._owner = None
        self._count = 0
        self._hold_stack = None

    # threading.Condition binds these when present. They MUST be
    # correct for the RLock kind: Condition's fallback ownership probe
    # is `acquire(False)` — which SUCCEEDS on a reentrant lock the
    # caller already owns, making Condition.wait refuse with "cannot
    # wait on un-acquired lock" (concurrent.futures.Future uses
    # Condition() over an RLock, so every Future.result() hits this).
    def _is_owned(self) -> bool:
        if self.kind == "RLock":
            return self._owner == threading.get_ident()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        if self.kind == "Lock":
            self.release()
            return None
        state = self._inner._release_save()
        owner, count = self._owner, self._count
        self._owner = None
        self._count = 0
        self._state._on_released(self)
        return (state, owner, count)

    def _acquire_restore(self, state):
        if self.kind == "Lock" or state is None:
            self.acquire()
            return
        inner_state, owner, count = state
        site = _acquire_site() if _held_stack() else ""
        self._inner._acquire_restore(inner_state)
        self._owner, self._count = owner, count
        self._state._on_acquired(self, site)
        self._hold_stack = _held_stack()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"SanitizedLock({self.kind}, {self.name!r})"


def _acquire_site() -> str:
    f = sys._getframe(2)
    skip = (__file__, threading.__file__)
    while f is not None and f.f_code.co_filename in skip:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{Path(f.f_code.co_filename).name}:{f.f_lineno}"


_ACTIVE: ThreadCheck | None = None


def install(budget_s: float | None = None) -> ThreadCheck:
    """Patch ``threading.Lock``/``RLock`` with sanitized factories;
    idempotent (returns the active state). ``budget_s`` default comes
    from ``DVTPU_THREADCHECK_BUDGET_S`` (1.0s)."""
    global _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    if budget_s is None:
        budget_s = float(os.environ.get(
            "DVTPU_THREADCHECK_BUDGET_S", "1.0"))
    state = ThreadCheck(budget_s=budget_s)
    threading.Lock = lambda: SanitizedLock(state, "Lock")
    threading.RLock = lambda: SanitizedLock(state, "RLock")
    _ACTIVE = state
    return state


def uninstall() -> None:
    """Restore the real factories (existing sanitized locks keep
    working — they wrap real primitives)."""
    global _ACTIVE
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _ACTIVE = None


def get_active() -> ThreadCheck | None:
    return _ACTIVE


def default_export_path() -> Path:
    """Where the graph lands: ``DVTPU_THREADCHECK_EXPORT`` wins; else a
    ``DVTPU_TRACE_SPOOL`` dir (beside the span spools, one Perfetto
    session for both); else ``logs/lockgraph-<pid>.json``."""
    explicit = os.environ.get("DVTPU_THREADCHECK_EXPORT")
    if explicit:
        return Path(explicit)
    spool = os.environ.get("DVTPU_TRACE_SPOOL")
    base = Path(spool) if spool else Path("logs")
    return base / f"lockgraph-{os.getpid()}.json"


# ----------------------------------------------------------- CLI smoke


def _smoke(export: Path, budget_s: float | None) -> int:
    """A real engine+router lifecycle under the sanitizer: the
    `make check` gate proving the locks the serving tier actually
    takes form an acyclic order. Returns a process exit code."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    state = install(budget_s)

    import numpy as np

    def toy_model(name: str):
        import jax.numpy as jnp

        from deepvision_tpu.serve import ServedModel

        def forward(variables, x):
            return {"y": x * variables["w"] + jnp.float32(0.5)}

        def post(host, i):
            return {"y": np.asarray(host["y"][i]).tolist()}

        return ServedModel(
            name=name, task="classify", forward=forward,
            variables={"w": np.float32(2.0)}, input_shape=(3,),
            postprocess=post)

    from deepvision_tpu.core.mesh import create_mesh
    from deepvision_tpu.obs.metrics import Registry
    from deepvision_tpu.serve import (
        EngineReplica,
        FleetRouter,
        InferenceEngine,
    )
    from deepvision_tpu.serve.telemetry import (
        RouterTelemetry,
        ServeTelemetry,
    )

    mesh = create_mesh(1, 1)
    # 1) engine lifecycle: open -> pause/queue -> resume -> results ->
    # stats/health churn -> close (the dispatcher, admission,
    # compile-cache, telemetry and obs-registry locks all live here)
    eng = InferenceEngine([toy_model("a"), toy_model("b")], mesh=mesh,
                          buckets=(1, 4))
    eng.pause()
    futs = [eng.submit(np.full(3, i, np.float32),
                       model=("a" if i % 2 else "b"))
            for i in range(8)]
    eng.resume()
    for f in futs:
        f.result(timeout=60)
    eng.stats()
    eng.health()
    eng.close()
    # 2) router lifecycle: 2 in-process replicas, routed load, probe
    # loop churn, federated metrics scrape, close
    def factory(sid: str):
        return EngineReplica(sid, lambda: [toy_model("toy")],
                             mesh=mesh, buckets=(1, 4))

    router = FleetRouter(factory, replicas=2, models=["toy"],
                         probe_interval_s=0.05,
                         telemetry=RouterTelemetry(registry=Registry()))
    try:
        futs = [router.submit(np.full(3, i, np.float32), model="toy")
                for i in range(12)]
        for f in futs:
            f.result(timeout=60)
        router.stats()
        router.health()
        router.render_metrics()
        time.sleep(0.2)  # a few probe ticks
    finally:
        router.close()
    # engine telemetry keeps a ServeTelemetry reference importable for
    # the engine above; referenced so linters see the import is used
    assert ServeTelemetry is not None

    path = state.export(export)
    g = state.graph()
    try:
        state.check_acyclic()
    except LockOrderError as e:
        print(f"threadcheck-smoke FAILED: {e}", file=sys.stderr)
        print(f"lock graph: {path}", file=sys.stderr)
        return 1
    finally:
        uninstall()
    n_viol = len(g["violations"])
    print(f"threadcheck-smoke OK ({len(g['nodes'])} lock classes, "
          f"{len(g['edges'])} order edges, acyclic, "
          f"{n_viol} hold-budget violation(s); graph: {path})")
    if n_viol:
        for v in g["violations"][:5]:
            print(f"  [hold>{v['budget_s']}s] {v['lock']} held "
                  f"{v['held_s']}s by {v['thread']}", file=sys.stderr)
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tools.jaxlint.threadcheck",
        description="runtime lock sanitizer (see tools/jaxlint/"
                    "threadcheck.py); --smoke runs an engine+router "
                    "lifecycle under instrumented locks and asserts "
                    "the observed acquisition order is acyclic",
    )
    parser.add_argument("--smoke", action="store_true",
                        help="run the engine+router lifecycle smoke")
    parser.add_argument("--export", default=None,
                        help="lock-graph JSON path (default: "
                             "DVTPU_THREADCHECK_EXPORT / spool dir / "
                             "logs/lockgraph-<pid>.json)")
    parser.add_argument("--budget-s", type=float, default=None,
                        help="hold-budget seconds (default 1.0 or "
                             "DVTPU_THREADCHECK_BUDGET_S)")
    args = parser.parse_args(argv)
    export = Path(args.export) if args.export else default_export_path()
    if args.smoke:
        return _smoke(export, args.budget_s)
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
