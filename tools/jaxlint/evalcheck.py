"""Registry-wide abstract-eval gate: ``python -m tools.jaxlint.evalcheck``.

The dynamic complement to the static pass: for EVERY model in
``deepvision_tpu.models.registry`` (all registered configs), trace
``init`` and ``apply`` (train and eval mode) under ``jax.eval_shape``
and assert:

- **zero concrete-array materialization** — inputs are
  ``jax.ShapeDtypeStruct``s, so any ``.item()``/``np.asarray``/Python
  branch on a traced value raises a ConcretizationTypeError instead of
  silently syncing (the same hazards JX101/JX102 hunt statically, here
  proven dynamically through the real module code);
- **stable output shapes** — tracing twice must produce identical
  shape/dtype pytrees (a trace that depends on ambient state is a
  recompile factory);
- **batch-shape scaling** — batch 1 and batch 2 must differ only in the
  leading dim (catches accidental batch-dim mixing, e.g. a stray
  reshape folding batch into features).

Abstract eval runs no FLOPs, so the whole zoo gates in seconds — cheap
enough for every PR (``make lint``).

Input geometry comes from ``train/configs.py`` (the production configs);
registry-only variants (``*_tf``/``*_ref``, GAN component models) carry
explicit specs below.
"""

from __future__ import annotations

import argparse
import sys
import traceback
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp


@dataclass
class ModelSpec:
    """How to build + trace one registry entry."""

    input_shape: tuple[int, ...]  # without the leading batch dim
    input_dtype: object = jnp.float32
    kwargs: dict = field(default_factory=dict)
    init_rngs: tuple[str, ...] = ("params", "dropout")
    train_rngs: tuple[str, ...] = ("dropout",)


def _config_spec(config_name: str) -> ModelSpec:
    from deepvision_tpu.train.configs import get_config

    cfg = get_config(config_name)
    size, ch = cfg["input_size"], cfg["channels"]
    kwargs = dict(cfg.get("model_kwargs", {}))
    if "num_heatmaps" in cfg:
        kwargs["num_heatmaps"] = cfg["num_heatmaps"]
    else:
        kwargs["num_classes"] = cfg["num_classes"]
    return ModelSpec(input_shape=(size, size, ch), kwargs=kwargs)


# Registry names with no training config of their own: converter-parity
# variants trace with the base model's geometry; GAN component models
# take their geometry from train/gan.py's create_*_state sample inputs.
_EXTRA_SPECS: dict[str, ModelSpec] = {
    "lenet5_tf": ModelSpec((32, 32, 1), kwargs={"num_classes": 10}),
    "alexnet2_tf": ModelSpec((224, 224, 3), kwargs={"num_classes": 1000}),
    "inception1_ref": ModelSpec((224, 224, 3),
                                kwargs={"num_classes": 1000}),
    "dcgan_generator": ModelSpec((100,), train_rngs=()),
    "dcgan_discriminator": ModelSpec((28, 28, 1)),
    "cyclegan_generator": ModelSpec((256, 256, 3), train_rngs=()),
    "cyclegan_discriminator": ModelSpec((256, 256, 3), train_rngs=()),
}

# config names that exist for the CLI but are not registry entries
# (the GAN trainers assemble their component models themselves)
_CONFIG_ALIASES = {"dcgan", "cyclegan", "gan_mnist", "gan_unpaired"}


def spec_for(name: str) -> ModelSpec:
    from deepvision_tpu.train.configs import TRAINING_CONFIG

    if name in _EXTRA_SPECS:
        return _EXTRA_SPECS[name]
    base = name[:-4] if name.endswith("_ref") else name
    if base in TRAINING_CONFIG:
        return _config_spec(base)
    raise KeyError(
        f"no evalcheck spec for registry entry {name!r}: add a "
        "ModelSpec to tools/jaxlint/evalcheck._EXTRA_SPECS (or a "
        "training config) so the shape gate covers it")


def _shapes(tree) -> list[tuple[str, tuple[int, ...], str]]:
    """Canonical, comparable (path, shape, dtype) listing of a pytree of
    ShapeDtypeStructs."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        (jax.tree_util.keystr(path), tuple(leaf.shape), str(leaf.dtype))
        for path, leaf in leaves
    ]


def _trace(module, spec: ModelSpec, batch: int):
    """One abstract init+apply pass; returns (init_shapes, eval_shapes,
    train_out_shapes, mutated_shapes). All inputs are ShapeDtypeStructs
    — nothing can materialize. Train outputs are split from the mutated
    batch_stats: outputs must SCALE with the batch dim, running stats
    must be batch-INDEPENDENT."""
    key_struct = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    x = jax.ShapeDtypeStruct((batch, *spec.input_shape), spec.input_dtype)

    def init_fn(rngs, xx):
        return module.init(rngs, xx, train=True)

    init_rngs = {r: key_struct for r in spec.init_rngs}
    variables = jax.eval_shape(init_fn, init_rngs, x)

    def apply_eval(v, xx):
        return module.apply(v, xx, train=False)

    out_eval = jax.eval_shape(apply_eval, variables, x)

    def apply_train(v, xx, rngs):
        return module.apply(v, xx, train=True,
                            mutable=["batch_stats"],
                            rngs=rngs)

    train_rngs = {r: key_struct for r in spec.train_rngs}
    out_train, mutated = jax.eval_shape(
        apply_train, variables, x, train_rngs)
    return (_shapes(variables), _shapes(out_eval), _shapes(out_train),
            _shapes(mutated))


def check_model(name: str) -> dict:
    """Gate one registry entry; returns a report dict (ok/error/...)."""
    from deepvision_tpu.models import get_model

    report = {"name": name, "ok": False}
    try:
        spec = spec_for(name)
        module = get_model(name, **spec.kwargs)
        first = _trace(module, spec, batch=1)
        again = _trace(module, spec, batch=1)
        if first != again:
            raise AssertionError(
                "unstable trace: two identical eval_shape passes "
                "produced different shape pytrees")
        init2, eval2, train2, mutated2 = _trace(module, spec, batch=2)
        for label, (b1, b2) in (
            ("eval apply", (first[1], eval2)),
            ("train apply", (first[2], train2)),
        ):
            _check_batch_scaling(name, label, b1, b2)
        if first[0] != init2:
            raise AssertionError(
                "parameter shapes depend on the batch size")
        if first[3] != mutated2:
            raise AssertionError(
                "mutated batch_stats shapes depend on the batch size — "
                "a running statistic is accumulating per-sample state")
        report.update(
            ok=True,
            params=len(first[0]),
            outputs=[s for _, s, _ in first[1]][:4],
        )
    except Exception as e:  # report, don't abort the sweep
        report["error"] = f"{type(e).__name__}: {e}"
        report["trace"] = traceback.format_exc(limit=8)
    return report


def _check_batch_scaling(name, label, b1, b2) -> None:
    if len(b1) != len(b2):
        raise AssertionError(
            f"{label}: output structure changes with batch size")
    for (p1, s1, d1), (p2, s2, d2) in zip(b1, b2):
        if p1 != p2 or d1 != d2:
            raise AssertionError(
                f"{label}: output {p1} changes structure/dtype with "
                "batch size")
        # leading dim scales with batch; everything else must not move.
        # A scalar/0-d output is the extreme form of batch mixing (the
        # whole batch reduced away), not a pass.
        if not s1 or s1[1:] != s2[1:] or s1[0] * 2 != s2[0]:
            raise AssertionError(
                f"{label}: output {p1} does not scale with the batch "
                f"dim (batch1 {s1} vs batch2 {s2}) — a reshape/reduce "
                "is mixing batch into features")


def run(names: list[str] | None = None, *, verbose: bool = False) -> int:
    import deepvision_tpu.models as models

    all_names = models.list_models()
    names = names or all_names
    unknown = sorted(set(names) - set(all_names))
    if unknown:
        print(f"unknown model(s): {', '.join(unknown)}", file=sys.stderr)
        return 2
    failures = 0
    for name in names:
        report = check_model(name)
        if report["ok"]:
            outs = " ".join("x".join(map(str, s))
                            for s in report["outputs"])
            print(f"ok   {name:24s} {report['params']:4d} param leaves; "
                  f"out {outs}")
        else:
            failures += 1
            print(f"FAIL {name:24s} {report['error']}")
            if verbose and "trace" in report:
                print(report["trace"], file=sys.stderr)
    total = len(names)
    print(f"evalcheck: {total - failures}/{total} models trace cleanly "
          "under abstract eval")
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.jaxlint.evalcheck",
        description="abstract-eval shape/trace gate over the model "
                    "registry (see tools/jaxlint/evalcheck.py)",
    )
    parser.add_argument("names", nargs="*",
                        help="registry names (default: whole registry)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print tracebacks for failures")
    args = parser.parse_args(argv)
    return run(args.names or None, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
