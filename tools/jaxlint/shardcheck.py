"""SPMD sharding & collective-traffic gate: ``python -m tools.jaxlint.shardcheck``.

The fourth tier of the static-analysis stack (AST → interprocedural →
compiled-IR → SPMD). ircheck proves per-device contracts of the compiled
train step; this gate proves the *between*-device ones — the properties
ROADMAP item 1 (partition-rule sharding engine + ZeRO-1) hinges on and
whose failure modes are silent today: a mistyped partition rule
replicates a tensor, a sharding mismatch at a pjit boundary inserts an
all-gather, and nothing ratchets collective bytes the way the
hbm/wire ledgers ratchet HBM. Four registry-wide contracts, each
riding ircheck's lower-and-compile harness (``make_cases`` — the REAL
train step of every registry model) at genuine multi-device CPU meshes
(``ensure_host_device_count`` forces them before jax loads):

- **collective-byte ledger** — every collective instruction in the
  optimized SPMD module (all-reduce / all-gather / reduce-scatter /
  all-to-all / collective-permute) is attributed its output bytes
  (per participant; async ``-start``/``-done`` pairs counted once,
  loop bodies once per trace — a relative ledger, like the wire
  ledger) and the per-(model, mesh, batch) total is gated ±5% against
  the ``[[shardcheck.comms]]`` baselines in jaxlint.toml. Interconnect
  traffic only ratchets down consciously.
- **implicit-resharding detector** — a pure data-parallel
  replicated-params step compiles to exactly the ``expected_collectives``
  set (gradient/metric all-reduce). Any OTHER collective opcode is a
  resharding transfer pjit inserted behind the program's back
  (producer/consumer sharding mismatch at a jit boundary, GSPMD
  repair, non-partitionable RNG) and fails the gate unless a reasoned
  ``[[shardcheck.reshard]]`` waiver declares it deliberate.
- **partition-rule coverage audit** — the declarative
  ``[[shardcheck.rule]]`` table (regex leaf-path → PartitionSpec DSL;
  the format item 1's engine will consume) must match EVERY
  param/opt-state leaf of every registry model. An unmatched leaf is
  replicated-by-default — exactly the silent-fallback bug class. The
  ``--zero1-ready`` mode prints the per-model replicated-residency
  worklist (f32 master + optimizer-moment bytes that
  ``core.step.weight_update_sharding`` would shard over the data
  axis), the ZeRO-1 twin of ``ircheck --bf16-ready``'s f32-surface
  worklist. ``--zero1`` goes further: it compiles every case under
  the engine's ZeRO-1 specs (``deepvision_tpu/core/sharding.py`` —
  the same interpreter the trainer runs) and PROVES conversion by
  reading the storage shardings back out of
  ``compiled.output_shardings``; the worklist is empty only when
  every prescribed opt-state leaf is stored sharded.
- **mesh-generalization gate** — each case compiles at every
  ``mesh_shapes`` entry (≥2 shapes) and the collective structure
  (opcode set AND instruction counts) must be identical across them: a
  hardcoded axis size produces a program whose collective set depends
  on the grid extents, which this catches before any TPU slice does.

Source-level companions JX124–JX126 (tools/jaxlint/checkers.py) keep
the idioms these proofs rest on out of the source: no hardcoded axis
names outside core/mesh.py, no unsharded device_put on multi-device
paths, no inline PartitionSpec in model/step code.

Cost: per case one abstract-state build and one lower+compile per mesh
shape. The ``fast_models`` subset (``[shardcheck]`` in jaxlint.toml)
is the `make lint-comms`/tier-1 slice; the registry-wide sweep rides
``make lint-ir``.
"""

from __future__ import annotations

import argparse
import fnmatch
import re
import sys
import traceback
from typing import Iterable

from tools.jaxlint.config import ShardCheckConfig, load_shardcheck_config
from tools.jaxlint.ircheck import IRCase, ensure_host_device_count, make_cases

# ------------------------------------------------------------ pure helpers
# (no jax imports: unit-testable on HLO text alone)

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "all-to-all", "collective-permute")

# `%name = <shape> <opcode>(` — the shape may be a tuple (variadic
# all-reduce); async pairs appear as <op>-start/<op>-done and must be
# charged once. Opcode must follow whitespace after the '=' side so
# instruction NAMES containing an opcode (e.g. %all-reduce.3 on the
# lhs, or calls=%all-reduce-fusion) never match.
_COLL_RE = re.compile(
    r"=\s*(?P<shape>\(?[^=\n]*?)\s+"
    r"(?P<op>" + "|".join(COLLECTIVE_OPS) + r")"
    r"(?P<suffix>-start|-done)?\(")


def parse_collective_bytes(hlo_text: str) -> dict[str, dict]:
    """opcode -> {"count", "bytes"} over (layout-stripped) HLO text:
    every collective instruction charged its OUTPUT bytes (summed over
    tuple elements for variadic ops) — per-participant bytes of the
    SPMD module, the number the comms ledger ratchets. ``-done`` halves
    of async pairs are skipped (the ``-start`` carries the shape)."""
    from tools.hbm_budget import shape_bytes

    out: dict[str, dict] = {}
    for m in _COLL_RE.finditer(hlo_text):
        if m.group("suffix") == "-done":
            continue
        op = m.group("op")
        nbytes = shape_bytes(m.group("shape"))
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += nbytes
    return out


def parse_mesh(s: str) -> tuple[int, int]:
    """'2x1' -> (2, 1) — the NxM mesh-string format the toml ledgers
    key on."""
    try:
        n, m = (int(x) for x in s.lower().split("x"))
    except ValueError:
        raise ValueError(f"mesh shape must be NxM (got {s!r})") from None
    if n < 1 or m < 1:
        raise ValueError(f"mesh extents must be >= 1 (got {s!r})")
    return n, m


def leaf_paths(tree) -> list[tuple[str, object]]:
    """('/'-joined path, leaf) pairs for a state pytree —
    ``params/Conv_0/kernel``, ``opt_state/0/mu/Dense_0/bias`` — the
    path strings the ``[[shardcheck.rule]]`` regexes match against.
    Delegates to the runtime engine so the audit and the trainer can
    never disagree on the path dialect (import stays lazy: this module
    must be importable jax-free for the HLO-text unit tests)."""
    from deepvision_tpu.core.sharding import leaf_paths as _engine_paths

    return _engine_paths(tree)


def _leaf_bytes(leaf) -> int:
    import math

    import numpy as np

    shape = getattr(leaf, "shape", ())
    return (math.prod(shape) if shape else 1) * \
        np.dtype(leaf.dtype).itemsize


def zero1_residency(state, mesh) -> dict:
    """The ZeRO-1 worklist for one model state: how many bytes sit
    replicated on every device today that
    ``core.step.weight_update_sharding`` would shard over the data
    axis. Keys: ``state_gb`` (whole train state), ``master_f32_gb``
    (f32 master params — the bf16 diet keeps masters full precision),
    ``opt_gb`` (optimizer state: Adam/RMSProp moments + counts),
    ``shardable_gb`` (opt bytes with a data-divisible dim — what
    ZeRO-1 moves), ``resid_gb`` (per-device opt residency AFTER
    sharding), ``n_data``."""
    import jax
    from jax.sharding import PartitionSpec

    from deepvision_tpu.core.mesh import axis_size
    from deepvision_tpu.core.step import weight_update_sharding

    n_data = axis_size(mesh)
    specs = weight_update_sharding(state, mesh)
    is_spec = lambda s: isinstance(s, PartitionSpec)  # noqa: E731
    opt_leaves = jax.tree.leaves(state.opt_state)
    opt_specs = jax.tree.leaves(specs.opt_state, is_leaf=is_spec)
    assert len(opt_leaves) == len(opt_specs)
    opt_b = sum(_leaf_bytes(x) for x in opt_leaves)
    shard_b = sum(_leaf_bytes(x)
                  for x, sp in zip(opt_leaves, opt_specs)
                  if tuple(sp) != ())
    master_b = sum(
        _leaf_bytes(x) for x in jax.tree.leaves(state.params)
        if str(x.dtype) == "float32")
    total_b = sum(_leaf_bytes(x) for x in jax.tree.leaves(state))
    return {
        "state_gb": round(total_b / 1e9, 3),
        "master_f32_gb": round(master_b / 1e9, 3),
        "opt_gb": round(opt_b / 1e9, 3),
        "shardable_gb": round(shard_b / 1e9, 3),
        "resid_gb": round(
            (opt_b - shard_b + shard_b / n_data) / 1e9, 3),
        "n_data": n_data,
    }


def mesh_consistency(reps: list[dict]) -> list[str]:
    """The mesh-generalization gate: collective opcode sets AND
    instruction counts must be identical across every mesh shape a
    case compiled at — a program whose collective STRUCTURE depends on
    the grid extents has an axis size baked in somewhere (per-device
    BYTES legitimately change with the mesh; the ledger rows key on
    the mesh for exactly that reason). Opcodes covered by a reshard
    waiver on any mesh are excluded: declared traffic (RNG counter
    permutes, scatter-index gathers) is partitioner-chosen and MAY
    differ per grid — that variance is exactly what the waiver's
    reason explains. Returns failure strings."""
    done = [r for r in reps if "collectives" in r]
    if len(done) < 2:
        return []
    waived = {op for r in done for op in r.get("waived_ops", ())}
    probs: list[str] = []
    ref = done[0]
    ref_struct = {op: rec["count"]
                  for op, rec in ref["collectives"].items()
                  if op not in waived}
    for r in done[1:]:
        struct = {op: rec["count"] for op, rec in r["collectives"].items()
                  if op not in waived}
        if struct != ref_struct:
            probs.append(
                f"collective structure differs across meshes: "
                f"{ref['mesh']} compiles {ref_struct or '{}'} but "
                f"{r['mesh']} compiles {struct or '{}'} — an axis "
                "size is hardcoded somewhere the mesh should "
                "parameterize")
    return probs


# ----------------------------------------------------------------- checks


def check_case(case: IRCase, scfg: ShardCheckConfig, *,
               mesh_shape: tuple[int, int],
               audit_rules: bool = True,
               zero1: bool = False,
               zero1_compile: bool = False) -> dict:
    """Lower + compile one case at one mesh shape and evaluate the
    comms ledger, the resharding detector and (once per case) the
    partition-rule coverage audit. Never raises — a broken build is
    itself a gate failure.

    ``zero1_compile`` compiles under the engine's ZeRO-1 state specs
    (``state_partition_specs(..., zero1=True)`` as the pjit
    out-shardings) and then PROVES the conversion from the compiled
    executable: every opt-state leaf the ``largest(...)`` rule
    prescribes sharded must come back non-replicated in
    ``compiled.output_shardings`` — the ``--zero1-ready`` worklist is
    empty only when the storage sharding is real, not merely asked
    for. Comms baselines are keyed separately (``zero1 = true`` rows):
    the update's reduce-scatter/all-gather is declared traffic here,
    not an implicit reshard."""
    import jax

    from deepvision_tpu.core import create_mesh
    from deepvision_tpu.core.sharding import (
        RuleError,
        parse_leaf_spec,
        state_partition_specs,
        zero1_plan as make_zero1_plan,
    )
    from deepvision_tpu.core.step import compile_train_step
    from tools.hbm_budget import strip_layouts

    mesh_str = f"{mesh_shape[0]}x{mesh_shape[1]}"
    rep: dict = {"case": case.name, "models": list(case.models),
                 "batch": case.batch, "mesh": mesh_str,
                 "platform": jax.default_backend(), "ok": False,
                 "failures": [], "notes": []}
    n_dev = len(jax.devices())
    need = mesh_shape[0] * mesh_shape[1]
    if need > n_dev:
        # no clamping here, ever: an unsharded program has no
        # collectives to audit and a passing report would be a lie
        rep["failures"].append(
            f"mesh {mesh_str} needs {need} devices, have {n_dev} — "
            "run via the CLI (it forces "
            "--xla_force_host_platform_device_count before jax loads)")
        return rep
    try:
        state, batch1, step_fn = case.build(case.batch)
        key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
        mesh = create_mesh(*mesh_shape)
        state_spec = None
        if zero1_compile:
            plan = make_zero1_plan(mesh, rules=scfg.rules)
            if plan is None:
                rep["failures"].append(
                    "--zero1 compile asked for weight-update sharding "
                    "but the [[shardcheck.rule]] opt_state row does not "
                    "prescribe a largest(...) spec — nothing to verify")
                return rep
            if hasattr(state, "zero1_plan"):
                state = state.replace(zero1_plan=plan)
            state_spec = state_partition_specs(
                state, mesh, zero1=True, rules=scfg.rules)
            rep["zero1_compile"] = True
        step = compile_train_step(step_fn, mesh, state_spec=state_spec)
        compiled = step.lower(state, batch1, key).compile()
        hlo = strip_layouts(compiled.as_text())

        # (a) collective-byte ledger
        colls = parse_collective_bytes(hlo)
        rep["collectives"] = colls
        coll_gb = round(
            sum(r["bytes"] for r in colls.values()) / 1e9, 3)
        rep["coll_gb_per_step"] = coll_gb
        base = scfg.comms_baseline(case.name, rep["platform"],
                                   mesh_str, case.batch,
                                   zero1=zero1_compile)
        if base is None:
            rep["notes"].append(
                "no comms baseline for this (platform, mesh, batch) — "
                "record with --record")
            rep["comms_unbaselined"] = True
        else:
            hi = base.coll_gb_per_step * (1 + scfg.comms_tolerance)
            lo = base.coll_gb_per_step * (1 - scfg.comms_tolerance)
            # an all-zero baseline (tiny model: KB of collectives
            # rounds to 0.0) gates on exact equality of the rounded
            # number — hi == lo == 0.0 and any growth fails, as it must
            if coll_gb > hi:
                rep["failures"].append(
                    f"coll_gb_per_step {coll_gb} exceeds baseline "
                    f"{base.coll_gb_per_step} by more than "
                    f"{scfg.comms_tolerance:.0%} — interconnect bytes "
                    "only ratchet DOWN; fix the regression or "
                    "consciously re-record the baseline")
            elif coll_gb < lo:
                rep["notes"].append(
                    f"collective bytes improved "
                    f"{base.coll_gb_per_step} -> {coll_gb}; re-record "
                    "to lock the gain in")

        # (b) implicit-resharding detector. The waiver set doubles as
        # the mesh-generalization comparator's exclusion list: a
        # waiver on an EXPECTED opcode is never needed here, but it
        # licenses cross-mesh structure variance on that opcode (the
        # partitioner re-planning a waived scatter on a 2-axis grid
        # can shift a neighboring all-reduce count by one).
        rep["waived_ops"] = []
        # under a ZeRO-1 compile the update's collective swap is the
        # declared plan, not an implicit reshard: reduce-scatter (grads
        # into local shards), all-gather (updated params back out), and
        # whatever shard shuffles the partitioner plans between them
        # (permutes/all-to-alls on 2-axis grids; the scatter half even
        # lowers as all-reduce+slice on this CPU backend). The reshard
        # DETECTOR therefore lives in the default replicated compile —
        # under --zero1 the teeth are the separately-keyed byte ledger
        # and the storage-sharding proof below.
        zero1_expected = ({"all-gather", "reduce-scatter",
                           "collective-permute", "all-to-all"}
                          if zero1_compile else set())
        for op in sorted(colls):
            if op in zero1_expected:
                rep["notes"].append(
                    f"zero1: {op} x{colls[op]['count']} "
                    f"({colls[op]['bytes'] / 1e6:.1f} MB/step) is the "
                    "declared weight-update traffic")
                continue
            waiver = scfg.reshard_waiver(case.name, mesh_str, op)
            for m in case.models:
                waiver = waiver or scfg.reshard_waiver(m, mesh_str, op)
            if waiver is not None:
                waiver.hits += 1
                rep["waived_ops"].append(op)
                rep["notes"].append(
                    f"reshard waived: {op} x{colls[op]['count']} "
                    f"({colls[op]['bytes'] / 1e6:.1f} MB/step) — "
                    f"{waiver.reason}")
            elif any(fnmatch.fnmatch(op, pat)
                     for pat in scfg.expected_collectives):
                continue
            else:
                rep["failures"].append(
                    f"implicit reshard: {op} x{colls[op]['count']} "
                    f"({colls[op]['bytes'] / 1e6:.1f} MB/step) in the "
                    "compiled module — pjit inserted a resharding "
                    "transfer the program never asked for (sharding "
                    "mismatch at a jit boundary, or non-partitionable "
                    "RNG); fix the shardings or declare it with a "
                    "reasoned [[shardcheck.reshard]] waiver")

        # (c) partition-rule coverage audit (mesh-independent — run
        # once per case, on the first mesh)
        if audit_rules:
            unmatched: list[str] = []
            bad_specs: list[str] = []
            for path, leaf in leaf_paths(state):
                rule = scfg.match_rule(path)
                if rule is None:
                    unmatched.append(path)
                    continue
                rule.hits += 1
                try:
                    # the spec must INTERPRET against the real leaf
                    # shape, not merely parse: a rule naming too many
                    # dims or an axis the mesh lacks is a coverage lie
                    # the regex match alone would hide
                    parse_leaf_spec(
                        rule.spec, tuple(getattr(leaf, "shape", ())),
                        mesh, zero1=True)
                except RuleError as e:
                    bad_specs.append(f"{path} ({rule.spec!r}): {e}")
            rep["unmatched_leaves"] = unmatched
            if unmatched:
                shown = ", ".join(unmatched[:4])
                more = (f" (+{len(unmatched) - 4} more)"
                        if len(unmatched) > 4 else "")
                rep["failures"].append(
                    f"partition-rule coverage: {len(unmatched)} state "
                    f"leaves match no [[shardcheck.rule]] row and "
                    f"would shard replicated-by-default: {shown}{more} "
                    "— add a rule (or extend one) so every leaf's "
                    "sharding is a declared decision")
            if bad_specs:
                shown = "; ".join(bad_specs[:3])
                more = (f" (+{len(bad_specs) - 3} more)"
                        if len(bad_specs) > 3 else "")
                rep["failures"].append(
                    f"partition-rule specs uninterpretable against "
                    f"{len(bad_specs)} matched leaves: {shown}{more}")

        # (d) ZeRO-1 conversion proof: read the STORAGE shardings back
        # out of the compiled executable and require every opt-state
        # leaf the engine prescribed sharded to actually be sharded —
        # the worklist-empty gate for --zero1-ready
        if zero1_compile:
            from jax.sharding import PartitionSpec

            is_spec = lambda s: isinstance(s, PartitionSpec)  # noqa: E731
            out_state = compiled.output_shardings[0]
            paths = [p for p, _ in leaf_paths(state)]
            specs_flat = jax.tree.leaves(state_spec, is_leaf=is_spec)
            out_flat = jax.tree.leaves(out_state)
            assert len(paths) == len(specs_flat) == len(out_flat)
            pending = [
                p for p, sp, osh in zip(paths, specs_flat, out_flat)
                if tuple(sp) != () and osh.is_fully_replicated]
            n_sharded = sum(1 for sp in specs_flat if tuple(sp) != ())
            rep["zero1_pending"] = pending
            rep["zero1_sharded_leaves"] = n_sharded - len(pending)
            if pending:
                shown = ", ".join(pending[:4])
                more = (f" (+{len(pending) - 4} more)"
                        if len(pending) > 4 else "")
                rep["failures"].append(
                    f"zero1 worklist NOT empty: {len(pending)} leaves "
                    f"the engine prescribed sharded came back "
                    f"replicated in the compiled output shardings: "
                    f"{shown}{more}")
            else:
                rep["notes"].append(
                    f"zero1 worklist empty: all {n_sharded} prescribed "
                    "opt-state leaves stored sharded in the compiled "
                    "executable")

        if zero1:
            rep["zero1"] = zero1_residency(state, mesh)

        rep["ok"] = not rep["failures"]
    # a broken build/lower/compile IS the gate failure being reported
    except Exception as e:  # jaxlint: disable=JX111
        rep["failures"].append(f"{type(e).__name__}: {e}")
        rep["trace"] = traceback.format_exc(limit=10)
    return rep


def record_toml(rep: dict) -> str:
    """A ready-to-paste ``[[shardcheck.comms]]`` baseline block for one
    (case, mesh) report."""
    return (
        "[[shardcheck.comms]]\n"
        f'model = "{rep["case"]}"\n'
        f'platform = "{rep["platform"]}"\n'
        f'mesh = "{rep["mesh"]}"\n'
        f"batch = {rep['batch']}\n"
        f"coll_gb_per_step = {rep['coll_gb_per_step']}\n"
        + ("zero1 = true\n" if rep.get("zero1_compile") else "")
    )


def _print_zero1_table(rows: list[tuple[str, dict]],
                       hbm_rows: dict[str, float]) -> None:
    """The ZeRO-1 worklist table: per model, the replicated residency
    weight-update sharding would move. ``hbm_rows`` maps case name ->
    the 1x1 cpu ``hbm_gb_per_step`` ledger row for reconciliation
    (state residency is the floor under that traffic number)."""
    print("\nzero1-ready: replicated residency the weight-update "
          "sharding (ZeRO-1) would shard over the data axis")
    hdr = (f"{'case':16s} {'state':>8s} {'masters':>8s} {'opt':>8s} "
           f"{'shardable':>9s} {'resid@' + str(rows[0][1]['n_data']) if rows else 'resid':>8s} "
           f"{'hbm1x1':>8s}")
    print(hdr)
    tot = {"state_gb": 0.0, "master_f32_gb": 0.0, "opt_gb": 0.0,
           "shardable_gb": 0.0, "resid_gb": 0.0}
    for name, z in rows:
        for k in tot:
            tot[k] += z[k]
        hbm = hbm_rows.get(name)
        print(f"{name:16s} {z['state_gb']:7.3f}G {z['master_f32_gb']:7.3f}G "
              f"{z['opt_gb']:7.3f}G {z['shardable_gb']:8.3f}G "
              f"{z['resid_gb']:7.3f}G "
              f"{(f'{hbm:7.3f}G' if hbm is not None else '      -')}")
    print(f"{'TOTAL':16s} {tot['state_gb']:7.3f}G "
          f"{tot['master_f32_gb']:7.3f}G {tot['opt_gb']:7.3f}G "
          f"{tot['shardable_gb']:8.3f}G {tot['resid_gb']:7.3f}G")
    if tot["opt_gb"]:
        cut = tot["shardable_gb"] * (1 - 1 / max(
            1, rows[0][1]["n_data"])) if rows else 0.0
        print(f"zero1-ready: sharding frees {cut:.3f} GB/device of "
              f"{tot['opt_gb']:.3f} GB optimizer state "
              f"({tot['shardable_gb']:.3f} GB shardable; masters stay "
              "replicated until ZeRO-3)")


def run(names: list[str] | None = None, *,
        config: str = "jaxlint.toml", fast: bool = False,
        meshes: Iterable[str] | None = None, record: bool = False,
        zero1: bool = False, zero1_compile: bool = False,
        prune_waivers: bool = False, fix: bool = False,
        verbose: bool = False) -> int:
    scfg = load_shardcheck_config(config)
    mesh_strs = list(meshes) if meshes else list(scfg.mesh_shapes)
    mesh_shapes = [parse_mesh(s) for s in mesh_strs]
    cases = make_cases()
    if names:
        unknown = sorted(set(names) - set(cases))
        if unknown:
            print(f"unknown case(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(cases))})", file=sys.stderr)
            return 2
        selected = [cases[n] for n in names]
    elif fast:
        unknown_fast = [n for n in scfg.fast_models if n not in cases]
        if unknown_fast:
            print(f"warning: [shardcheck] fast_models entr"
                  f"{'ies' if len(unknown_fast) > 1 else 'y'} "
                  f"{unknown_fast} match no case "
                  f"(known: {', '.join(sorted(cases))})", file=sys.stderr)
        selected = [cases[n] for n in scfg.fast_models if n in cases]
        if not selected:
            print("error: --fast selected ZERO cases — fix [shardcheck] "
                  "fast_models in jaxlint.toml", file=sys.stderr)
            return 2
    else:
        selected = list(cases.values())
    failures = 0
    crashed_models: set[str] = set()
    models_covered: set[str] = set()
    to_record: list[str] = []
    zero1_rows: list[tuple[str, dict]] = []
    for case in selected:
        reps: list[dict] = []
        for i, ms in enumerate(mesh_shapes):
            rep = check_case(case, scfg, mesh_shape=ms,
                             audit_rules=(i == 0),
                             zero1=(zero1 and i == 0),
                             zero1_compile=zero1_compile)
            reps.append(rep)
            models_covered.update(rep["models"])
            status = "ok  " if rep["ok"] else "FAIL"
            colls = rep.get("collectives", {})
            ops = ",".join(f"{op}x{r['count']}"
                           for op, r in sorted(colls.items())) or "-"
            print(f"{status} {case.name:16s} b{case.batch:<3d} "
                  f"mesh={rep['mesh']} "
                  f"coll={rep.get('coll_gb_per_step', '-')}GB {ops}")
            for note in rep["notes"]:
                print(f"     note: {note}")
            for f in rep["failures"]:
                print(f"     FAIL: {f}")
            if verbose and "trace" in rep:
                print(rep["trace"], file=sys.stderr)
            if record and "coll_gb_per_step" in rep:
                to_record.append(record_toml(rep))
            if "trace" in rep:
                crashed_models.update({case.name, *case.models})
            failures += 0 if rep["ok"] else 1
        # the mesh-generalization gate only holds for the replicated
        # compile: under ZeRO-1 the partitioner re-plans the update's
        # shard shuffle per grid (counts legitimately differ across
        # meshes), so cross-mesh structure is not an invariant there —
        # the per-(mesh, zero1) byte ledger gates those programs
        if not zero1_compile:
            for prob in mesh_consistency(reps):
                print(f"     FAIL: {case.name}: {prob}")
                failures += 1
        if zero1 and reps and "zero1" in reps[0]:
            zero1_rows.append((case.name, reps[0]["zero1"]))
    # stale-entry warnings: same burn-down contract as every ledger.
    # Rules are registry-wide, so only a FULL completed sweep may call
    # one stale; waivers are judged per completed case.
    sel_models = ({c.name for c in selected}
                  | {m for c in selected for m in c.models}) \
        - crashed_models
    full_sweep = not names and not fast and not crashed_models
    if full_sweep:
        for r in scfg.rules:
            if r.hits == 0:
                print(f"warning: stale shardcheck.rule {r.pattern!r} "
                      "matched no state leaf of any registry model — "
                      "delete or fix the row", file=sys.stderr)
    stale_waivers = [w for w in scfg.reshard
                     if w.hits == 0 and w.model in sel_models]
    for w in stale_waivers:
        print(f"warning: stale shardcheck.reshard waiver "
              f"{w.model!r} {w.op!r} ({w.reason}) — nothing "
              "matched; delete the entry", file=sys.stderr)
    if prune_waivers and stale_waivers:
        from tools.jaxlint.core import prune_blocks

        # only waivers proven stale by THIS run's compiles are
        # touched: staleness is judged per completed case, so a
        # targeted `shardcheck <models> --prune-waivers --fix` burns
        # down exactly what it just verified
        _, removed = prune_blocks(
            config, "shardcheck.reshard",
            {(w.model, w.op, w.mesh) for w in stale_waivers},
            lambda e: (e.get("model", ""), e.get("op", ""),
                       str(e.get("mesh", "*"))),
            fix=fix)
        print(f"{'pruned' if fix else 'would prune'} {removed} stale "
              f"[[shardcheck.reshard]] waiver"
              f"{'s' if removed != 1 else ''}"
              f"{'' if fix else ' (pass --fix to rewrite the config)'}")
    if record and to_record:
        print("\n# paste into jaxlint.toml (recorded comms baselines):")
        print("\n".join(to_record))
    if zero1 and zero1_rows:
        from tools.jaxlint.config import load_ircheck_config

        ircfg = load_ircheck_config(config)
        hbm_rows = {
            c.name: b.hbm_gb_per_step
            for c in selected
            for b in [ircfg.hbm_baseline(c.name, "cpu", "1x1", c.batch)]
            if b is not None
        }
        _print_zero1_table(zero1_rows, hbm_rows)
    n = len(selected) * len(mesh_shapes)
    print(f"shardcheck: {n - failures}/{n} case-mesh compiles pass "
          f"({len(models_covered)} registry models, "
          f"meshes {','.join(mesh_strs)})")
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.jaxlint.shardcheck",
        description="SPMD sharding & collective-traffic gate over the "
                    "model registry (comms-byte ledger / implicit-"
                    "reshard detector / partition-rule coverage / "
                    "mesh-generalization; tools/jaxlint/shardcheck.py)",
    )
    parser.add_argument("names", nargs="*",
                        help="case names (default: every registry case)")
    parser.add_argument("--config", default="jaxlint.toml")
    parser.add_argument("--fast", action="store_true",
                        help="only the [shardcheck] fast_models subset "
                             "(the `make lint-comms` slice)")
    parser.add_argument("--mesh", default=None,
                        help="comma-separated NxM mesh shapes to audit "
                             "(default: [shardcheck] mesh_shapes, "
                             "2x1,2x2); >=2 shapes arm the mesh-"
                             "generalization gate")
    parser.add_argument("--record", action="store_true",
                        help="print paste-ready [[shardcheck.comms]] "
                             "TOML for every measured (case, mesh)")
    parser.add_argument("--zero1-ready", action="store_true",
                        help="print the per-model replicated-residency "
                             "worklist ZeRO-1 would shard (ROADMAP "
                             "item-1 twin of ircheck --bf16-ready)")
    parser.add_argument("--zero1", action="store_true",
                        help="compile under the engine's ZeRO-1 state "
                             "specs and verify from the compiled "
                             "output shardings that every prescribed "
                             "opt-state leaf is stored sharded (the "
                             "worklist-empty proof); comms baselines "
                             "are keyed zero1 = true")
    parser.add_argument("--prune-waivers", action="store_true",
                        help="drop [[shardcheck.reshard]] waivers this "
                             "run proves stale (compiled cases whose "
                             "waived opcode never appeared) from the "
                             "config; dry-run unless --fix")
    parser.add_argument("--fix", action="store_true",
                        help="with --prune-waivers: rewrite the config "
                             "file in place")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.fix and not args.prune_waivers:
        parser.error("--fix only makes sense with --prune-waivers")
    meshes = ([s.strip() for s in args.mesh.split(",") if s.strip()]
              if args.mesh else None)
    try:
        shapes = [parse_mesh(s) for s in
                  (meshes or load_shardcheck_config(
                      args.config).mesh_shapes)]
    except ValueError as e:
        parser.error(str(e))
    # BEFORE any jax import (every jax import in this module and in
    # ircheck is lazy for exactly this): force enough virtual host
    # devices for the largest requested mesh
    if not ensure_host_device_count(
            max(n * m for n, m in shapes)):
        print("error: jax is already initialized with too few devices "
              "for the requested meshes — launch a fresh process (the "
              "CLI sets XLA_FLAGS only before jax loads)",
              file=sys.stderr)
        return 2
    return run(args.names or None, config=args.config, fast=args.fast,
               meshes=meshes, record=args.record,
               zero1=args.zero1_ready, zero1_compile=args.zero1,
               prune_waivers=args.prune_waivers, fix=args.fix,
               verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
