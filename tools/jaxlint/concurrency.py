"""jaxlint concurrency tier: lock discipline for the host-side runtime.

The serving fleet, the resilience tier, the input pipeline, and the obs
stack together hold ~30 locks, a dozen long-lived threads, several
signal handlers, and two multiprocessing pools — and until ISSUE 14
every hard-won rule about them (spawn-not-fork after jax/tf init, no
flock across a collective, no blocking I/O under a hot lock,
stop-event-not-sleep) lived only in CHANGES.md prose. These five
checkers ride the PR 9 :class:`~tools.jaxlint.core.ProjectContext`
interprocedural dataflow so the rules hold through helper calls and
module boundaries:

- **JX118 unguarded shared state** — an instance attribute mutated by a
  ``threading.Thread``-target method (or anything it transitively calls
  on the same class) and read/written from a public method, with either
  side outside the instance's lock. Resolved per class;
  ``with self._lock:`` scopes, lock/queue/event/future-typed attributes,
  and thread-local handoffs are recognized as safe.
- **JX119 blocking call under lock** — HTTP round-trips, subprocess
  waits, unbounded ``queue.get()``/``.join()``/``.wait()``, file I/O,
  and sleeps inside a ``with <lock>:`` body; via the project callable
  summaries, a call to a helper that *transitively* blocks is the same
  hazard routed through a function boundary. Every other thread that
  wants the lock stalls behind the I/O — the class of bug that turned
  the obs registry and the router probe loop into convoy points.
- **JX120 lock-order graph** — a project-wide lock-acquisition digraph
  from nested ``with lock:`` scopes plus calls that (transitively)
  acquire; any cycle is a potential ABBA deadlock, reported once per
  cycle. A second rule in the same checker rediscovers the PR 8 hazard
  class: ANY lock held across a cross-host collective/barrier call is
  an implicit cycle through the barrier (a peer blocked at the barrier
  may need the lock — exactly why the Trainer's cluster save is
  lock-free).
- **JX121 fork-safety** — ``multiprocessing`` ``Pool``/``Process``/
  ``Queue`` created without an explicit spawn context in a module that
  (directly or through the project import graph) reaches jax/tf: a
  forked child inherits the runtime's locked mutexes with no owner
  thread and wedges on first use — the PR 2 tier-1 deadlock, codified.
- **JX122 signal-handler safety** — functions registered via
  ``signal.signal`` that acquire locks, allocate through the metrics
  registry, or perform non-atomic I/O (directly or transitively): a
  handler can interrupt its own process MID-CRITICAL-SECTION and
  self-deadlock on the very lock it tries to take. The vetted
  flight-recorder dump path (``signal_safe_calls`` knob) is exempt —
  it is written to be best-effort and never to raise.

Knobs (``jaxlint.toml [jaxlint]``): ``lock_name_patterns``,
``lock_blocking_calls``, ``collective_calls``, ``fork_unsafe_imports``,
``signal_safe_calls``. The runtime twin of this static tier is
``tools/jaxlint/threadcheck.py`` — an instrumented-lock sanitizer that
records the LIVE acquisition graph and asserts acyclicity.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterator

from tools.jaxlint.core import (
    Checker,
    Finding,
    FunctionInfo,
    FunctionNode,
    ModuleContext,
    assign_target_names,
    call_name,
    dotted_name,
    iter_own_nodes,
    last_attr,
    register_checker,
)

# factories whose result is a mutex-like object (lock identity + kind)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
# factories whose result is a thread-safe handoff/sync object: an
# attribute of one of these types is a SANCTIONED cross-thread channel,
# not unguarded shared state (JX118)
_SAFE_FACTORIES = _LOCK_FACTORIES | {
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event",
    "Barrier", "Future", "deque", "local",
}
_MP_CLASSES = {"Pool", "Process", "Queue", "SimpleQueue",
               "JoinableQueue", "Manager"}
_SPAWN_METHODS = {"spawn", "forkserver"}
# registry get-or-create API: allocation takes the registry lock — a
# handler interrupting mid-register self-deadlocks (JX122)
_REGISTRY_ALLOC = {"counter", "gauge", "histogram", "register"}
_HANDLER_IO = {"print", "open", "write", "write_text", "write_bytes",
               "read_text", "read_bytes", "flush"}


def _lockish(name: str | None, patterns) -> bool:
    if not name:
        return False
    n = name.lower()
    return any(fnmatch.fnmatch(n, p.lower()) for p in patterns)


def _match_call(call: ast.Call, patterns) -> str | None:
    """First pattern-matching name of a call, checked against both the
    dotted call name and its final attribute (the JX115 convention)."""
    cn = call_name(call)
    la = last_attr(cn)
    method = call.func.attr if isinstance(call.func, ast.Attribute) \
        else None
    for n in (cn, la, method):
        if n and any(fnmatch.fnmatch(n, p) for p in patterns):
            return n
    return None


def _self_attr(expr: ast.AST) -> str | None:
    """'x' for a ``self.x`` attribute expression, else None."""
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id in ("self", "cls"):
        return expr.attr
    return None


def _self_attr_stores(stmt: ast.stmt) -> list[ast.Attribute]:
    """``self.X`` attribute nodes BOUND by an assignment statement."""
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    out = []
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.ctx, (ast.Store, ast.Del)) \
                    and _self_attr(sub) is not None:
                out.append(sub)
            elif isinstance(sub, ast.Subscript):
                # self.X[k] = ... mutates self.X (a Load of X on the
                # receiver, but a WRITE of the shared structure)
                recv = sub.value
                if isinstance(recv, ast.Attribute) \
                        and _self_attr(recv) is not None:
                    out.append(recv)
    return out


def lock_scoped_nodes(func: FunctionNode, is_lock):
    """Yield ``(node, held)`` for every node of ``func``'s own body
    (nested defs/lambdas excluded — they run when called, not here),
    where ``held`` is the tuple of lock tokens of the enclosing
    ``with``-lock scopes. ``is_lock(expr)`` returns a truthy token
    (identity) for lock expressions. ``With`` nodes yield with the
    OUTER held set — the acquisition itself happens under what was
    already held."""
    out: list[tuple[ast.AST, tuple]] = []

    def rec(node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        out.append((node, held))
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                rec(item.context_expr, held)
                if item.optional_vars is not None:
                    rec(item.optional_vars, held)
                tok = is_lock(item.context_expr)
                if tok:
                    acquired.append(tok)
            inner = held + tuple(acquired)
            for stmt in node.body:
                rec(stmt, inner)
            return
        for child in ast.iter_child_nodes(node):
            rec(child, held)

    for stmt in func.body:
        rec(stmt, ())
    return out


# ------------------------------------------------------- per-class model


class _ClassModel:
    """Thread/lock structure of one class: its methods, which of them
    run on a background thread (``threading.Thread(target=self._x)``
    closures, nested-def targets included), its lock attributes, and
    its thread-safe handoff attributes."""

    def __init__(self, mod: ModuleContext, name: str,
                 methods: list[FunctionInfo]):
        self.mod = mod
        self.name = name
        self.methods = {m.node.name: m for m in methods}
        patterns = mod.cfg.lock_name_patterns
        self.lock_attrs: set[str] = set()
        self.safe_attrs: set[str] = set()
        nested: dict[int, list[FunctionInfo]] = {}
        for f in mod.functions:
            if f.parent is not None:
                nested.setdefault(id(f.parent.node), []).append(f)
        # attribute typing from assignments anywhere in the class
        for info in methods:
            for node in ast.walk(info.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = getattr(node, "value", None)
                if not isinstance(value, ast.Call):
                    continue
                la = last_attr(call_name(value))
                for attr_node in _self_attr_stores(node):
                    if la in _LOCK_FACTORIES:
                        self.lock_attrs.add(attr_node.attr)
                    if la in _SAFE_FACTORIES:
                        self.safe_attrs.add(attr_node.attr)
        # thread entry points: Thread(target=self._x) / Thread(target=f)
        # where f is a nested def of the enclosing method
        entries: list[FunctionNode] = []
        self.thread_targets: list[str] = []
        for info in methods:
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                if last_attr(call_name(node)) not in ("Thread", "Timer"):
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    attr = _self_attr(kw.value)
                    if attr and attr in self.methods:
                        entries.append(self.methods[attr].node)
                        self.thread_targets.append(attr)
                    elif isinstance(kw.value, ast.Name):
                        for g in nested.get(id(info.node), []):
                            if g.node.name == kw.value.id:
                                entries.append(g.node)
                                self.thread_targets.append(kw.value.id)
        # close over same-class self-calls + nested defs
        thread_fns: set[int] = set()
        work = list(entries)
        while work:
            fn = work.pop()
            if id(fn) in thread_fns:
                continue
            thread_fns.add(id(fn))
            for g in nested.get(id(fn), []):
                work.append(g.node)
            for node in iter_own_nodes(fn):
                if isinstance(node, ast.Call):
                    attr = _self_attr(node.func)
                    if attr and attr in self.methods:
                        work.append(self.methods[attr].node)
        self.thread_fn_ids = thread_fns

    def is_instance_lock(self, expr: ast.AST) -> str | None:
        attr = _self_attr(expr)
        if attr is None:
            return None
        if attr in self.lock_attrs \
                or _lockish(attr, self.mod.cfg.lock_name_patterns):
            return attr
        return None


def _classes_of(mod: ModuleContext) -> list[_ClassModel]:
    groups: dict[str, list[FunctionInfo]] = {}
    for f in mod.functions:
        if f.parent is not None or "." not in f.qualname:
            continue
        groups.setdefault(f.qualname.rsplit(".", 1)[0], []).append(f)
    return [_ClassModel(mod, name, infos)
            for name, infos in sorted(groups.items())]


# --------------------------------------------------- project-level facts


class ConcurrencyFacts:
    """Project-wide concurrency summaries, computed once per
    ``run_paths`` invocation and cached on the ProjectContext:

    - ``lock_blocking_ids`` — functions whose own body (transitively,
      through resolvable calls) performs a lock-hostile blocking call
      (the JX119 set);
    - ``collective_ids`` — functions transitively performing a
      cross-host collective/barrier call (JX120's flock rule);
    - ``fn_acquires`` — per function, the set of lock identities it
      (transitively) acquires via ``with``;
    - ``fork_unsafe_mod_ids`` — modules reaching a jax/tf import
      through the project import graph (JX121's gate);
    - the project lock-order graph + its cycles (JX120).
    """

    def __init__(self, mods: list[ModuleContext], cfg, project):
        self.mods = mods
        self.cfg = cfg
        self.project = project
        self._attr_lock_owner: dict[str, list[tuple[ModuleContext, str,
                                                    str]]] = {}
        self._lock_kinds: dict[str, str] = {}
        self._collect_lock_owners()
        self.lock_blocking_ids = self._blocking_closure()
        self.collective_ids = self._collective_closure()
        self.fork_unsafe_mod_ids = self._fork_unsafe_mods()
        self.fn_acquires = self._acquire_closure()
        self.edges: dict[tuple[str, str], tuple[ModuleContext, ast.AST]] \
            = {}
        self.collective_holds: list[tuple[ModuleContext, ast.AST, str,
                                          str]] = []
        self._build_lock_graph()
        self.cycles = self._find_cycles()

    # -- lock identity ---------------------------------------------------
    def _collect_lock_owners(self) -> None:
        """attr name -> [(module, class, kind)] creating it as a lock,
        so ``other._lock`` resolves when exactly one class owns that
        attribute name project-wide."""
        for m in self.mods:
            for info in m.functions:
                if info.parent is not None or "." not in info.qualname:
                    continue
                cls = info.qualname.rsplit(".", 1)[0]
                for node in ast.walk(info.node):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                        continue
                    value = getattr(node, "value", None)
                    if not isinstance(value, ast.Call):
                        continue
                    kind = last_attr(call_name(value))
                    if kind not in _LOCK_FACTORIES:
                        continue
                    for attr_node in _self_attr_stores(node):
                        entry = (m, cls, kind)
                        owners = self._attr_lock_owner.setdefault(
                            attr_node.attr, [])
                        if (m.relpath, cls) not in [(o[0].relpath, o[1])
                                                    for o in owners]:
                            owners.append(entry)
                        self._lock_kinds[
                            f"{m.relpath}:{cls}.{attr_node.attr}"] = kind
            # module-level locks
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and last_attr(call_name(node.value)) \
                        in _LOCK_FACTORIES:
                    for name in assign_target_names(node):
                        self._lock_kinds[f"{m.relpath}:{name}"] = \
                            last_attr(call_name(node.value))

    def lock_kind(self, lock_id: str) -> str:
        return self._lock_kinds.get(lock_id, "Lock")

    def lock_id(self, m: ModuleContext, info: FunctionInfo | None,
                expr: ast.AST) -> str | None:
        """Project-stable identity for a lock expression, or None when
        the expression is not lock-like / not resolvable. ``self.X``
        resolves to the enclosing class; ``obj.X`` resolves when
        exactly one class creates ``X`` as a lock; bare names resolve
        module- or function-scoped."""
        patterns = m.cfg.lock_name_patterns
        attr = _self_attr(expr)
        if attr is not None:
            cls = _enclosing_class(info)
            if cls is None:
                return None
            lid = f"{m.relpath}:{cls}.{attr}"
            if lid in self._lock_kinds or _lockish(attr, patterns):
                return lid
            return None
        if isinstance(expr, ast.Attribute):
            owners = self._attr_lock_owner.get(expr.attr, [])
            if len(owners) == 1:
                om, cls, _ = owners[0]
                return f"{om.relpath}:{cls}.{expr.attr}"
            return None  # ambiguous/unknown receiver: stay silent
        if isinstance(expr, ast.Name):
            lid = f"{m.relpath}:{expr.id}"
            if lid in self._lock_kinds:
                return lid  # a known factory-created module lock
            if not _lockish(expr.id, patterns):
                return None
            if info is not None:
                return f"{m.relpath}:{info.qualname}.{expr.id}"
            return lid
        return None

    # -- callable summaries ----------------------------------------------
    def _own_blocking_call(self, node: ast.Call) -> str | None:
        reason = blocking_reason(node, self.cfg)
        return reason

    def _blocking_closure(self) -> set[int]:
        ids: set[int] = set()
        for m in self.mods:
            for info in m.functions:
                for node in iter_own_nodes(info.node):
                    if isinstance(node, ast.Call) \
                            and blocking_reason(node, self.cfg):
                        ids.add(id(info.node))
                        break
        return self._close_over_calls(ids)

    def _collective_closure(self) -> set[int]:
        ids: set[int] = set()
        patterns = self.cfg.collective_calls
        for m in self.mods:
            for info in m.functions:
                for node in iter_own_nodes(info.node):
                    if isinstance(node, ast.Call) \
                            and _match_call(node, patterns):
                        ids.add(id(info.node))
                        break
        return self._close_over_calls(ids)

    def _close_over_calls(self, ids: set[int]) -> set[int]:
        if self.project is None:
            return ids
        callees = self.project._callees
        changed = True
        while changed:
            changed = False
            for fid, fns in callees.items():
                if fid in ids:
                    continue
                if any(id(fn) in ids for fn in fns):
                    ids.add(fid)
                    changed = True
        return ids

    def resolve(self, m: ModuleContext, call: ast.Call,
                within: FunctionInfo | None = None):
        if self.project is None:
            return []
        return self.project.resolve_call(m, call, within)

    # -- fork-unsafe module closure --------------------------------------
    def _fork_unsafe_mods(self) -> set[int]:
        roots = set(self.cfg.fork_unsafe_imports)

        def direct(m: ModuleContext) -> bool:
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Import):
                    if any(a.name.split(".")[0] in roots
                           for a in node.names):
                        return True
                elif isinstance(node, ast.ImportFrom) and node.module \
                        and not node.level:
                    if node.module.split(".")[0] in roots:
                        return True
            return False

        unsafe = {id(m) for m in self.mods if direct(m)}
        if self.project is None:
            return unsafe
        by_modname = self.project.by_modname
        deps: dict[int, set[int]] = {}
        for m in self.mods:
            targets = set()
            for imp in self.project._imports[id(m)].values():
                modname = imp[1]
                tm = by_modname.get(modname)
                if tm is not None:
                    targets.add(id(tm))
                if imp[0] == "sym":
                    tm = by_modname.get(f"{imp[1]}.{imp[2]}")
                    if tm is not None:
                        targets.add(id(tm))
            deps[id(m)] = targets
        changed = True
        while changed:
            changed = False
            for m in self.mods:
                if id(m) in unsafe:
                    continue
                if deps[id(m)] & unsafe:
                    unsafe.add(id(m))
                    changed = True
        return unsafe

    # -- acquisition graph ----------------------------------------------
    def _acquire_closure(self) -> dict[int, set[str]]:
        acquires: dict[int, set[str]] = {}
        for m in self.mods:
            for info in m.functions:
                direct: set[str] = set()
                is_lock = lambda e, m=m, info=info: \
                    self.lock_id(m, info, e)  # noqa: E731
                for node, _held in lock_scoped_nodes(info.node, is_lock):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            tok = self.lock_id(m, info, item.context_expr)
                            if tok:
                                direct.add(tok)
                acquires[id(info.node)] = direct
        if self.project is not None:
            callees = self.project._callees
            changed = True
            while changed:
                changed = False
                for fid, fns in callees.items():
                    cur = acquires.get(fid, set())
                    for fn in fns:
                        extra = acquires.get(id(fn), set()) - cur
                        if extra:
                            cur |= extra
                            acquires[fid] = cur
                            changed = True
        return acquires

    def _build_lock_graph(self) -> None:
        coll_patterns = self.cfg.collective_calls
        for m in self.mods:
            for info in m.functions:
                is_lock = lambda e, m=m, info=info: \
                    self.lock_id(m, info, e)  # noqa: E731
                for node, held in lock_scoped_nodes(info.node, is_lock):
                    if not held:
                        continue
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            tok = self.lock_id(m, info, item.context_expr)
                            if tok:
                                for h in held:
                                    self._edge(h, tok, m, node)
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    la = last_attr(call_name(node))
                    if la in ("acquire", "release"):
                        continue
                    # a collective under ANY held lock: the implicit
                    # cycle through the barrier (PR 8 hazard class)
                    coll = _match_call(node, coll_patterns)
                    if coll is None:
                        for fn in self.resolve(m, node, info):
                            if id(fn) in self.collective_ids:
                                coll = fn.name
                                break
                    if coll is not None:
                        self.collective_holds.append(
                            (m, node, held[-1], coll))
                        continue
                    for fn in self.resolve(m, node, info):
                        for tok in self.fn_acquires.get(id(fn), ()):
                            for h in held:
                                self._edge(h, tok, m, node)

    def _edge(self, a: str, b: str, m: ModuleContext,
              node: ast.AST) -> None:
        if a == b and self.lock_kind(a) == "RLock":
            return  # reentrant re-acquire is the point of an RLock
        self.edges.setdefault((a, b), (m, node))

    def _find_cycles(self) -> list[tuple[list[str], ModuleContext,
                                         ast.AST]]:
        """Cycles in the acquisition digraph, one per SCC (plus
        self-loops), each attributed to its first recorded edge site."""
        graph: dict[str, set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # iterative Tarjan (lock graphs are small; recursion depth
            # is still bounded defensively)
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out = []
        for scc in sccs:
            nodes = sorted(scc)
            cyclic = len(nodes) > 1 or (
                len(nodes) == 1 and (nodes[0], nodes[0]) in self.edges)
            if not cyclic:
                continue
            sites = [(self.edges[(a, b)], (a, b))
                     for a in nodes for b in nodes
                     if (a, b) in self.edges]
            sites.sort(key=lambda s: (s[0][0].relpath,
                                      getattr(s[0][1], "lineno", 0)))
            (m, node), _ = sites[0]
            out.append((nodes, m, node))
        return out


def _enclosing_class(info: FunctionInfo | None) -> str | None:
    if info is None:
        return None
    chain = 1
    p = info.parent
    while p is not None:
        chain += 1
        p = p.parent
    parts = info.qualname.split(".")
    prefix = parts[:-chain]
    return ".".join(prefix) if prefix else None


def blocking_reason(call: ast.Call, cfg) -> str | None:
    """Why ``call`` blocks the calling thread unboundedly (the JX119
    predicate), or None. Pattern knob + structural rules: zero-arg
    ``.get()``/``.join()``/``.wait()`` are unbounded (a timeout bounds
    them; ``str.join(iterable)`` has an argument), bare ``sleep`` rides
    the time.sleep rule."""
    la = last_attr(call_name(call))
    method = call.func.attr if isinstance(call.func, ast.Attribute) \
        else None
    name = _match_call(call, cfg.lock_blocking_calls)
    if name is not None:
        return f"'{name}' ({_io_kind(name)})"
    if isinstance(call.func, ast.Name) and call.func.id == "sleep":
        return "'sleep' (time.sleep)"
    eff = la or method
    if eff in ("get", "join", "wait"):
        has_timeout = any(k.arg and "timeout" in k.arg.lower()
                          for k in call.keywords)
        blocking_kw = any(k.arg == "block" for k in call.keywords)
        if eff == "get":
            if not call.args and not call.keywords:
                return "unbounded 'queue.get()'"
            if blocking_kw and not has_timeout:
                return "unbounded 'queue.get(block=True)'"
            return None
        if call.args or call.keywords:
            return None  # join(timeout)/wait(timeout)/str.join(parts)
        return f"unbounded '.{eff}()'"
    return None


def _io_kind(name: str) -> str:
    n = name.lower()
    if "url" in n or "request" in n or "recv" in n or "accept" in n \
            or "connect" in n or "getresponse" in n:
        return "network round-trip"
    if "subprocess" in n or "communicate" in n:
        return "subprocess wait"
    if "sleep" in n:
        return "sleep"
    return "file I/O"


def _facts_for(mod: ModuleContext) -> ConcurrencyFacts:
    proj = mod.project
    if proj is None:
        return ConcurrencyFacts([mod], mod.cfg, None)
    cached = getattr(proj, "_concurrency_facts", None)
    if cached is None:
        cached = ConcurrencyFacts(proj.mods, mod.cfg, proj)
        proj._concurrency_facts = cached
    return cached


# ------------------------------------------------------------- checkers


@register_checker
class UnguardedSharedStateChecker(Checker):
    """JX118: instance state shared between a background thread and the
    public surface with no lock on at least one side. The GIL makes
    single attribute loads atomic, not CONSISTENT: a public reader can
    observe a half-updated pair of attributes, a stale list the thread
    just swapped out, or a dict mid-mutation (RuntimeError under
    iteration) — the class of bug pytest only catches when the
    interleaving loses the lottery."""

    code = "JX118"
    name = "unguarded-shared-state"
    description = ("instance attribute mutated by a Thread-target "
                   "method and accessed from a public method with "
                   "either side outside the instance lock")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for cls in _classes_of(mod):
            if not cls.thread_fn_ids:
                continue
            yield from self._check_class(mod, cls)

    def _check_class(self, mod: ModuleContext,
                     cls: _ClassModel) -> Iterator[Finding]:
        is_lock = cls.is_instance_lock
        # thread-side writes: attr -> [(node, locked)]
        writes: dict[str, list[tuple[ast.AST, bool]]] = {}
        for info in cls.methods.values():
            fns = [info.node] + [f.node for f in mod.functions
                                 if f.parent is not None
                                 and self._under(f, info)]
            for fn in fns:
                if id(fn) not in cls.thread_fn_ids:
                    continue
                for node, held in lock_scoped_nodes(fn, is_lock):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                             ast.AugAssign)):
                        continue
                    for attr_node in _self_attr_stores(node):
                        writes.setdefault(attr_node.attr, []).append(
                            (node, bool(held)))
        if not writes:
            return
        # public-surface accesses: attr -> [(node, locked, method)]
        accesses: dict[str, list[tuple[ast.AST, bool, str]]] = {}
        for name, info in cls.methods.items():
            if name.startswith("_"):
                continue
            if id(info.node) in cls.thread_fn_ids:
                continue
            for node, held in lock_scoped_nodes(info.node, is_lock):
                attr = _self_attr(node) if isinstance(
                    node, ast.Attribute) else None
                if attr is None or attr not in writes:
                    continue
                accesses.setdefault(attr, []).append(
                    (node, bool(held), name))
        for attr in sorted(accesses):
            if attr in cls.safe_attrs or attr in cls.lock_attrs:
                continue
            w = writes[attr]
            a = accesses[attr]
            unlocked = [(n, meth) for n, locked, meth in a
                        if not locked]
            thread_unlocked = any(not locked for _n, locked in w)
            if not unlocked and not thread_unlocked:
                continue  # both sides consistently locked
            node, meth = unlocked[0] if unlocked else (
                a[0][0], a[0][2])
            target = cls.thread_targets[0] if cls.thread_targets \
                else "?"
            side = ("public method" if unlocked
                    else "thread-side write in")
            yield mod.finding(
                node, self.code,
                f"'{cls.name}.{attr}' is mutated by the "
                f"'{target}' thread and accessed from public method "
                f"'{meth}' with the {side} outside the instance "
                "lock — a reader can observe torn/stale state; hold "
                "the instance's lock on both sides (or hand off "
                "through a Queue/Event)")

    @staticmethod
    def _under(f: FunctionInfo, ancestor: FunctionInfo) -> bool:
        p = f.parent
        while p is not None:
            if p is ancestor:
                return True
            p = p.parent
        return False


@register_checker
class BlockingUnderLockChecker(Checker):
    """JX119: a blocking call inside a ``with <lock>:`` body convoys
    every thread that wants the lock behind the I/O — a wedged HTTP
    peer or a slow disk turns one lock into a process-wide stall (and
    under the obs registry lock, into a frozen /metrics surface exactly
    when the incident needs it). Interprocedural: a call to a helper
    that transitively blocks (project callable summary) is the same
    hazard routed through a function boundary."""

    code = "JX119"
    name = "blocking-call-under-lock"
    description = ("HTTP/subprocess/file-I/O/sleep or unbounded "
                   "get()/join()/wait(), direct or routed through a "
                   "helper, inside a `with lock:` body")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        facts = _facts_for(mod)
        patterns = mod.cfg.lock_name_patterns
        is_lock = lambda e: _is_lock_pattern_expr(e, patterns)  # noqa: E731
        flagged: set[int] = set()
        for info in mod.functions:
            for node, held in lock_scoped_nodes(info.node, is_lock):
                if not held or not isinstance(node, ast.Call) \
                        or id(node) in flagged:
                    continue
                la = last_attr(call_name(node))
                if la in ("acquire", "release"):
                    continue  # nested acquisition is JX120's domain
                reason = blocking_reason(node, mod.cfg)
                if reason is not None:
                    flagged.add(id(node))
                    yield mod.finding(
                        node, self.code,
                        f"{reason} while holding '{held[-1]}': every "
                        "thread wanting the lock stalls behind the "
                        "blocking call; move the I/O outside the "
                        "critical section (snapshot under the lock, "
                        "act after releasing)")
                    continue
                for fn in facts.resolve(mod, node, info):
                    if id(fn) in facts.lock_blocking_ids:
                        flagged.add(id(node))
                        yield mod.finding(
                            node, self.code,
                            f"'{call_name(node) or fn.name}' "
                            f"transitively blocks (helper '{fn.name}' "
                            "performs HTTP/subprocess/file I/O or an "
                            "unbounded get/join/wait) while holding "
                            f"'{held[-1]}'; move the blocking work "
                            "outside the critical section")
                        break


def _is_lock_pattern_expr(expr: ast.AST, patterns) -> str | None:
    if isinstance(expr, ast.Attribute) and _lockish(expr.attr, patterns):
        return expr.attr
    if isinstance(expr, ast.Name) and _lockish(expr.id, patterns):
        return expr.id
    return None


@register_checker
class LockOrderChecker(Checker):
    """JX120: the project-wide lock-acquisition digraph. Nested
    ``with lock:`` scopes and calls that (transitively) acquire add
    edges held->acquired; a cycle means two call paths take the same
    locks in opposite orders — the classic ABBA deadlock that only
    fires under production interleavings. A second rule flags ANY lock
    held across a cross-host collective/barrier call: the barrier
    waits for peers, a peer may be blocked on the lock, and the
    implicit cycle through the barrier wedges the fleet — the PR 8
    flock-across-collective hazard class, now enforced."""

    code = "JX120"
    name = "lock-order-cycle"
    description = ("cycle in the project lock-acquisition graph "
                   "(potential ABBA deadlock), or a lock held across "
                   "a cross-host collective/barrier")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        facts = _facts_for(mod)
        for nodes, m, node in facts.cycles:
            if m is not mod:
                continue
            path = " -> ".join(nodes + [nodes[0]]) if len(nodes) > 1 \
                else f"{nodes[0]} -> {nodes[0]}"
            yield mod.finding(
                node, self.code,
                f"lock-order cycle: {path} — these locks are acquired "
                "in inconsistent order somewhere in the project, a "
                "potential ABBA deadlock; impose one global order (or "
                "collapse to a single lock)")
        for m, node, lock, coll in facts.collective_holds:
            if m is not mod:
                continue
            yield mod.finding(
                node, self.code,
                f"collective/barrier '{coll}' called while holding "
                f"'{lock}': peers blocked at the barrier may need the "
                "lock (the PR 8 flock-across-collective deadlock); "
                "release the lock before any cross-host rendezvous")
        # flock/acquire held positionally across a collective in the
        # same function body (no `with` scope to see through)
        facts_patterns = mod.cfg.lock_name_patterns
        for info in mod.functions:
            yield from self._flock_scan(mod, info, facts,
                                        facts_patterns)

    def _flock_scan(self, mod: ModuleContext, info: FunctionInfo,
                    facts: ConcurrencyFacts,
                    patterns) -> Iterator[Finding]:
        acquires: list[int] = []
        releases: list[int] = []
        collectives: list[tuple[int, ast.AST, str]] = []
        for node in iter_own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            la = last_attr(cn)
            if la == "flock" or (
                    la == "acquire" and isinstance(
                        node.func, ast.Attribute)
                    and _is_lock_pattern_expr(node.func.value, patterns)):
                if la == "flock" and _mentions_unlock(node):
                    releases.append(node.lineno)
                else:
                    acquires.append(node.lineno)
            elif la == "release" or (la == "flock"
                                     and _mentions_unlock(node)):
                releases.append(node.lineno)
            else:
                coll = _match_call(node, mod.cfg.collective_calls)
                if coll is None:
                    for fn in facts.resolve(mod, node, info):
                        if id(fn) in facts.collective_ids:
                            coll = fn.name
                            break
                if coll is not None:
                    collectives.append((node.lineno, node, coll))
        for line, node, coll in collectives:
            held = [a for a in acquires if a < line
                    and not any(a < r < line for r in releases)]
            if held:
                yield mod.finding(
                    node, self.code,
                    f"collective/barrier '{coll}' reached while a "
                    "file/lock acquisition at line "
                    f"{max(held)} is still held: a peer blocked at "
                    "the barrier may need the same lock (the PR 8 "
                    "flock-across-collective deadlock); release "
                    "before the rendezvous")


def _mentions_unlock(call: ast.Call) -> bool:
    return any(isinstance(a, ast.AST) and "LOCK_UN" in (
        dotted_name(a) or "") for a in call.args)


@register_checker
class ForkSafetyChecker(Checker):
    """JX121: fork-based multiprocessing after jax/tf initialization.
    Both runtimes start internal threads holding internal mutexes; a
    ``fork()`` clones the locked mutex but not its owner thread, so the
    child wedges the first time it touches the runtime — the PR 2
    deadlock that froze tier-1 at test 39 until the 870s timeout. Any
    ``Pool``/``Process``/``Queue`` in a module reaching a jax/tf import
    (directly or through the project import graph) must come from an
    explicit ``multiprocessing.get_context("spawn")``."""

    code = "JX121"
    name = "fork-after-jax-init"
    description = ("multiprocessing Pool/Process/Queue without an "
                   "explicit spawn context in a module that reaches a "
                   "jax/tf import (the fork-after-init deadlock)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        facts = _facts_for(mod)
        if id(mod) not in facts.fork_unsafe_mod_ids:
            return
        mp_aliases: set[str] = set()
        direct: dict[str, str] = {}  # bare name -> mp class
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "multiprocessing":
                        mp_aliases.add(a.asname or "multiprocessing")
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "multiprocessing":
                for a in node.names:
                    if a.name in _MP_CLASSES:
                        direct[a.asname or a.name] = a.name
        if not mp_aliases and not direct:
            return
        spawn_names: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and getattr(node, "value", None) is not None \
                    and self._is_spawn_ctx(node.value, mp_aliases):
                spawn_names.update(assign_target_names(node))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cls = None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MP_CLASSES:
                recv = node.func.value
                if isinstance(recv, ast.Name):
                    if recv.id in spawn_names:
                        continue  # ctx.Pool(...) through a spawn ctx
                    if recv.id not in mp_aliases:
                        continue  # some unrelated .Pool attribute
                    cls = node.func.attr
                elif isinstance(recv, ast.Call):
                    if self._is_spawn_ctx(recv, mp_aliases):
                        continue  # get_context("spawn").Pool(...)
                    if last_attr(call_name(recv)) == "get_context":
                        cls = node.func.attr  # fork/default context
                    else:
                        continue
                else:
                    continue
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in direct:
                cls = direct[node.func.id]
            if cls is None:
                continue
            yield mod.finding(
                node, self.code,
                f"multiprocessing.{cls} created without an explicit "
                "spawn context in a module that reaches jax/tf: a "
                "forked child inherits the runtime's locked mutexes "
                "with no owner thread and deadlocks on first use "
                "(the PR 2 tier-1 wedge); use "
                "mp.get_context(\"spawn\")")

    @staticmethod
    def _is_spawn_ctx(expr: ast.AST, mp_aliases: set[str]) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        if last_attr(call_name(expr)) != "get_context":
            return False
        if not expr.args:
            return False
        arg = expr.args[0]
        return isinstance(arg, ast.Constant) \
            and arg.value in _SPAWN_METHODS


@register_checker
class SignalHandlerSafetyChecker(Checker):
    """JX122: signal handlers run BETWEEN any two bytecodes of the
    interrupted thread. A handler that takes a lock can interrupt the
    critical section that already holds it (self-deadlock); one that
    allocates through the metrics registry takes the registry lock the
    interrupted scrape may hold; non-atomic I/O interleaves with the
    interrupted stream. Handlers must flip flags/events and return —
    the Trainer's ``request_preempt`` is the model. The vetted
    flight-recorder dump path (``signal_safe_calls``) is exempt: it is
    best-effort by construction and never raises."""

    code = "JX122"
    name = "unsafe-signal-handler"
    description = ("signal.signal handler that acquires a lock, "
                   "allocates registry metrics, or does non-atomic "
                   "I/O (directly or transitively)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        facts = _facts_for(mod)
        for info in list(mod.functions) + [None]:
            tree = info.node if info is not None else mod.tree
            nodes = iter_own_nodes(tree) if info is not None \
                else self._module_level(mod)
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                if last_attr(call_name(node)) != "signal" \
                        or len(node.args) < 2:
                    continue
                if not (call_name(node) or "").endswith(
                        "signal.signal") and call_name(node) != "signal":
                    continue
                handler = node.args[1]
                hazard = self._handler_hazard(mod, info, handler, facts)
                if hazard is None:
                    continue
                hname, desc = hazard
                yield mod.finding(
                    node, self.code,
                    f"signal handler '{hname}' {desc} — a handler "
                    "interrupts its own process mid-critical-section "
                    "and can self-deadlock or corrupt I/O; flip a "
                    "flag/Event and do the work at a safe point "
                    "(trainer.request_preempt is the model; the "
                    "flight-recorder dump path is the vetted "
                    "exception)")

    @staticmethod
    def _module_level(mod: ModuleContext):
        fn_nodes = {id(f.node) for f in mod.functions}

        def rec(node):
            for child in ast.iter_child_nodes(node):
                if id(child) in fn_nodes or isinstance(
                        child, ast.Lambda):
                    continue
                yield child
                yield from rec(child)

        yield from rec(mod.tree)

    def _handler_hazard(self, mod, info, handler, facts):
        """(handler name, hazard description) or None."""
        if isinstance(handler, ast.Lambda):
            desc = self._fn_hazard_body(mod, info, handler, facts,
                                        set(), 0)
            return ("<lambda>", desc) if desc else None
        ref = dotted_name(handler)
        if ref in ("signal.SIG_DFL", "signal.SIG_IGN", "SIG_DFL",
                   "SIG_IGN"):
            return None
        if ref is None:
            return None
        fns = []
        if mod.project is not None:
            fns = mod.project.resolve_name(mod, ref, info)
        if not fns:
            attr = last_attr(ref)
            fns = [f.node for f in mod.functions
                   if f.node.name == attr]
        for fn in fns:
            desc = self._fn_hazard(mod, fn, facts, set(), 0)
            if desc:
                return (last_attr(ref), desc)
        return None

    def _fn_hazard(self, mod, fn, facts, visited, depth):
        if id(fn) in visited or depth > 4:
            return None
        visited.add(id(fn))
        return self._fn_hazard_body(mod, None, fn, facts, visited,
                                    depth)

    def _fn_hazard_body(self, mod, info, fn, facts, visited, depth):
        patterns = mod.cfg.lock_name_patterns
        safe = mod.cfg.signal_safe_calls
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        is_lock = lambda e: _is_lock_pattern_expr(e, patterns)  # noqa: E731
        holder = ast.FunctionDef(
            name="_h", args=ast.arguments(
                posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
                defaults=[]),
            body=body, decorator_list=[]) \
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) else fn
        for node, _held in lock_scoped_nodes(holder, is_lock):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if is_lock(item.context_expr):
                        return ("acquires lock "
                                f"'{is_lock(item.context_expr)}'")
            if not isinstance(node, ast.Call):
                continue
            la = last_attr(call_name(node))
            method = node.func.attr if isinstance(
                node.func, ast.Attribute) else None
            eff = la or method
            # vetted-path match is on the FULL dotted name: a bare
            # "dump" pattern must not exempt json.dump/pickle.dump —
            # exactly the non-atomic I/O this checker exists to flag
            full = call_name(node) or eff
            if full and any(fnmatch.fnmatch(full, p) for p in safe):
                continue  # the vetted dump path
            if eff == "acquire":
                return "acquires a lock via .acquire()"
            if eff in _REGISTRY_ALLOC:
                return (f"allocates through the metrics registry "
                        f"('{eff}' takes the registry lock)")
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _HANDLER_IO) \
                    or (method in _HANDLER_IO):
                return f"performs non-atomic I/O ('{eff}')"
            # transitive: a helper that locks/allocates/does I/O
            frame = info if info is not None else None
            for g in facts.resolve(mod, node, frame):
                desc = self._fn_hazard(mod, g, facts, visited,
                                       depth + 1)
                if desc:
                    return f"calls '{g.name}', which {desc}"
        return None
