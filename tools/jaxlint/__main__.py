"""CLI entry point: ``python -m tools.jaxlint [paths...]``."""

import sys

from tools.jaxlint.core import main

if __name__ == "__main__":
    sys.exit(main())
