"""Compiled-IR contract gate: ``python -m tools.jaxlint.ircheck``.

Layer 2 of the ISSUE-10 static-analysis design. The AST pass (layer 1)
reasons about *source*; this gate lowers the REAL train step of every
registry model — the same construction bench.py / tools/hbm_budget.py
measure, abstract ``jax.eval_shape`` state so no FLOPs or RAM are spent
on init — and statically verifies contracts on the jaxpr and the
optimized HLO of the compiled executable:

- **donation coverage (JX104 enforcement)** — the step is compiled
  through ``core.step.compile_train_step`` with ``donate_argnums=(0,)``;
  here we verify XLA actually ALIASED the param + optimizer-state
  buffers input→output (the ``input_output_alias`` map of the compiled
  module). An undonated state fraction above the configured minimum
  fails the gate unless a ``[[ircheck.donation]]`` waiver with a
  ``reason`` covers the model — the per-model ledger `make lint-ir`
  burns down.
- **dtype discipline** — no ``f64`` anywhere in the optimized HLO, and
  no f32 pixel tensor on the H2D boundary (the IR-level twin of JX114:
  batches are constructed with the production wire dtype — uint8 for
  the record-reader families — so a step that regresses to requiring
  host-normalized f32 pixels fails to lower or trips the input check).
  ``--bf16-ready`` additionally reports the f32 activation surface of
  each jaxpr as the ROADMAP item-2 (bf16/HBM-diet) worklist.
- **recompile stability** — lowering at two bucket sizes must produce
  structurally identical jaxprs modulo the batch dimension (equation
  count, primitive sequence, and every aval shape equal or scaling with
  the bucket ratio). A step whose trace depends on the batch size is a
  recompile factory on the serving bucket ladder.
- **collective audit** — every named axis consumed by a collective
  (``psum``/``all_gather``/``ppermute``/``axis_index``…) or demanded by
  a sharding constraint exists on the declared mesh; ``--mesh N,M``
  audits the N×M shape the ROADMAP item-3 sharding engine will use.
- **HBM-budget regression ledger** — XLA's "bytes accessed" for the
  compiled step (``tools/hbm_budget.hbm_gb_per_step``) is compared
  against the per-(model, platform, mesh, batch) baselines recorded in
  ``jaxlint.toml`` ``[[ircheck.hbm]]`` with a ±``hbm_tolerance`` band:
  above fails (the 76 GB number can only go down), below prints a
  re-record nudge, missing prints a ready-to-paste baseline block
  (``--record`` emits TOML for all of them).

Cost: per model one abstract-state build, two ``make_jaxpr`` traces and
ONE ``jit.lower().compile()`` at a small fixed batch on a 1×1 mesh by
default — deterministic across harnesses and CPU-affordable. The
``fast_models`` subset (``[ircheck]`` in jaxlint.toml) is the
tier-1/`make check` slice; the registry-wide run is ``make lint-ir``.
"""

from __future__ import annotations

import argparse
import sys
import traceback
from dataclasses import dataclass
from typing import Callable

from tools.jaxlint.config import IRCheckConfig, load_ircheck_config


def ensure_host_device_count(n: int) -> bool:
    """Make sure at least ``n`` devices exist for a mesh audit, BEFORE
    jax initializes: appends ``--xla_force_host_platform_device_count``
    to ``XLA_FLAGS`` (a no-op on real accelerator platforms — the flag
    only multiplies the host/CPU platform) so the CLI can compile
    genuine NxM CPU meshes instead of silently clamping to 1x1.

    XLA reads the flag at backend creation, so this only works while
    ``jax`` is still unimported (every jax import in this module is
    deliberately lazy for exactly this reason). Returns False when jax
    is already initialized with fewer devices — the caller decides
    whether that is a clamp-with-flag or a failure."""
    if n <= 1:
        return True
    if "jax" in sys.modules:
        import jax

        return len(jax.devices()) >= n
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" in flags:
        # the caller already chose a count; respect it and let the
        # mesh build succeed or fail against that choice
        return True
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}").strip()
    return True


# ------------------------------------------------------------ pure helpers
# (no jax imports: unit-testable on text/structures alone)


_NP_TO_HLO = {
    "float32": "f32", "bfloat16": "bf16", "float16": "f16",
    "float64": "f64", "int8": "s8", "int16": "s16", "int32": "s32",
    "int64": "s64", "uint8": "u8", "uint16": "u16", "uint32": "u32",
    "uint64": "u64", "bool": "pred",
}


def canon_shape(dtype_name: str, shape: tuple) -> str:
    """Canonical HLO-style shape string for a numpy dtype + dims —
    comparable against :func:`entry_param_shapes` output."""
    dt = _NP_TO_HLO.get(dtype_name, dtype_name)
    return f"{dt}[{','.join(str(d) for d in shape)}]"


def entry_param_shapes(hlo_text: str) -> dict[int, str]:
    """parameter number -> shape string for the ENTRY computation of
    (layout-stripped) HLO text."""
    import re

    from tools.hbm_budget import parse_entry

    out: dict[int, str] = {}
    for _, shape, opcode, _, line in parse_entry(hlo_text):
        if opcode != "parameter":
            continue
        m = re.search(r"parameter\((\d+)\)", line)
        if m:
            out[int(m.group(1))] = shape
    return out


def parse_alias_map(hlo_text: str) -> set[int]:
    """Parameter numbers aliased to an output in the compiled module's
    ``input_output_alias={ {out}: (param, {idx}, kind), ... }`` header.
    Brace-counted (the map nests braces, regex backtracking truncates)."""
    key = "input_output_alias={"
    start = hlo_text.find(key)
    if start < 0:
        return set()
    i = start + len(key)
    depth = 1
    while i < len(hlo_text) and depth:
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
        i += 1
    body = hlo_text[start + len(key):i - 1]
    import re

    return {int(p) for p in
            re.findall(r"\}\s*:\s*\((\d+)\s*,", body)}


def compare_jaxprs(j1, j2, b1: int, b2: int,
                   path: str = "jaxpr") -> list[str]:
    """Structural diff of two jaxprs lowered at batch ``b1`` vs ``b2``:
    equation count, primitive sequence, and aval shapes must match with
    every dimension equal or scaling exactly with the bucket ratio
    (``d1 * b2 == d2 * b1``). Returns human-readable problems (empty =
    stable modulo the batch dim). Sub-jaxpr params recurse."""
    probs: list[str] = []
    e1, e2 = j1.eqns, j2.eqns
    if len(e1) != len(e2):
        return [f"{path}: equation count {len(e1)} vs {len(e2)} — the "
                "trace structure depends on the batch size"]

    def dim_ok(d1: int, d2: int) -> bool:
        return d1 == d2 or d1 * b2 == d2 * b1

    for i, (a, b) in enumerate(zip(e1, e2)):
        if a.primitive.name != b.primitive.name:
            probs.append(f"{path}[{i}]: primitive "
                         f"{a.primitive.name} vs {b.primitive.name}")
            continue
        for va, vb in zip(list(a.invars) + list(a.outvars),
                          list(b.invars) + list(b.outvars)):
            sa = getattr(getattr(va, "aval", None), "shape", None)
            sb = getattr(getattr(vb, "aval", None), "shape", None)
            if sa is None or sb is None:
                continue
            if len(sa) != len(sb) or not all(
                    dim_ok(x, y) for x, y in zip(sa, sb)):
                probs.append(
                    f"{path}[{i}] {a.primitive.name}: aval {tuple(sa)} "
                    f"vs {tuple(sb)} does not scale with the batch dim")
        for k, pa in a.params.items():
            pb = b.params.get(k)
            # sub-jaxprs hide behind three shapes: ClosedJaxpr params,
            # raw Jaxpr params, and TUPLES of them (lax.cond 'branches')
            pa_seq = pa if isinstance(pa, (tuple, list)) else (pa,)
            pb_seq = pb if isinstance(pb, (tuple, list)) else (pb,)
            for j, (ea, eb) in enumerate(zip(pa_seq, pb_seq)):
                ja = getattr(ea, "jaxpr",
                             ea if hasattr(ea, "eqns") else None)
                jb = getattr(eb, "jaxpr",
                             eb if hasattr(eb, "eqns") else None)
                if ja is not None and jb is not None:
                    probs.extend(compare_jaxprs(
                        ja, jb, b1, b2, f"{path}[{i}].{k}[{j}]"))
        if len(probs) > 20:  # one broken model floods otherwise
            probs.append(f"{path}: ... (truncated)")
            break
    return probs


# collective primitives whose params name mesh axes
_AXIS_PARAM_KEYS = ("axis_name", "axes", "axis")


def collect_axis_names(jaxpr, out: set[str] | None = None) -> set[str]:
    """Every string axis name consumed by collectives / axis queries /
    sharding constraints anywhere in ``jaxpr`` (sub-jaxprs included)."""
    out = out if out is not None else set()
    for eqn in jaxpr.eqns:
        for key in _AXIS_PARAM_KEYS:
            if key not in eqn.params:
                continue
            val = eqn.params[key]
            vals = val if isinstance(val, (tuple, list)) else (val,)
            out.update(v for v in vals if isinstance(v, str))
        sharding = eqn.params.get("sharding")
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            for entry in spec:
                entries = entry if isinstance(entry, (tuple, list)) \
                    else (entry,)
                out.update(e for e in entries if isinstance(e, str))
        for p in eqn.params.values():
            sub = getattr(p, "jaxpr", p if hasattr(p, "eqns") else None)
            if sub is not None:
                collect_axis_names(sub, out)
    return out


def f32_surface(jaxpr, min_bytes: int = 1 << 20) -> dict:
    """The f32 intermediate surface of a jaxpr — the bf16/HBM-diet
    worklist: per distinct >=min_bytes f32 result shape, how many
    equations produce it and the bytes per instance."""
    shapes: dict[str, dict] = {}

    def visit(j):
        for eqn in j.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is None or str(getattr(aval, "dtype", "")) \
                        != "float32":
                    continue
                import math

                n = math.prod(aval.shape) if aval.shape else 1
                b = n * 4
                if b < min_bytes:
                    continue
                key = f"f32[{','.join(map(str, aval.shape))}]"
                rec = shapes.setdefault(
                    key, {"count": 0, "bytes_each": b})
                rec["count"] += 1
            for p in eqn.params.values():
                sub = getattr(p, "jaxpr",
                              p if hasattr(p, "eqns") else None)
                if sub is not None:
                    visit(sub)

    visit(jaxpr)
    total = sum(r["count"] * r["bytes_each"] for r in shapes.values())
    return {"total_mb": round(total / 1e6, 1), "shapes": dict(sorted(
        shapes.items(),
        key=lambda kv: -kv[1]["count"] * kv[1]["bytes_each"]))}


def jaxpr_wire_bytes(jaxpr) -> int:
    """Logical HBM bytes of one traced step: operand + output bytes
    summed over every equation (sub-jaxprs recursed, the wrapping call
    not double-charged), with ``convert_element_type`` charged ZERO and
    read THROUGH to the source aval — XLA fuses pure dtype converts
    into producers/consumers, so charging them (or their outputs at the
    converted dtype) would hide exactly what the bf16 diet changes.

    This is the backend-neutral twin of the XLA cost-analysis ledger:
    on this dev box the CPU backend float-normalizes every convolution
    to f32 (measured: 98/98 resnet50 convs, bf16 13.84 GB vs f32
    13.63 GB — the dtype diet is invisible to cpu cost analysis), so
    the wire ledger is what proves the diet on the compiled artifact
    here; on-chip rows re-record the cost-analysis number natively.
    Loop bodies (scan/while) are charged once per trace — a relative
    ledger, not a wall-clock model."""
    import math

    def aval_bytes(aval):
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            return 0
        try:
            itemsize = dtype.itemsize
        except AttributeError:
            return 0
        return (math.prod(shape) if shape else 1) * itemsize

    def visit(j, total=0):
        # var id -> source aval through convert chains
        src: dict[int, object] = {}

        def source(v):
            aval = getattr(v, "aval", None)
            return src.get(id(v), aval)

        for eqn in j.eqns:
            subs = []
            for p in eqn.params.values():
                seq = p if isinstance(p, (tuple, list)) else (p,)
                for e in seq:
                    sj = getattr(e, "jaxpr",
                                 e if hasattr(e, "eqns") else None)
                    if sj is not None:
                        subs.append(sj)
            if eqn.primitive.name == "convert_element_type":
                a = source(eqn.invars[0])
                if a is not None:
                    src[id(eqn.outvars[0])] = a
                continue
            if subs:
                for sj in subs:
                    total = visit(sj, total)
                continue
            for v in eqn.invars:
                a = source(v)
                if a is not None:
                    total += aval_bytes(a)
            for v in eqn.outvars:
                a = getattr(v, "aval", None)
                if a is not None:
                    total += aval_bytes(a)
        return total

    return visit(jaxpr)


def pixel_f32_inputs(batch_leaves: list[tuple[str, tuple, str]]
                     ) -> list[str]:
    """Pixel-looking f32/f64 tensors among (path, shape, dtype) input
    leaves: 4-D, spatially >=16, <=4 channels — the tensors whose wire
    dtype must be uint8 under the split-pipeline contract (ISSUE 7)."""
    out = []
    for path, shape, dtype in batch_leaves:
        if (len(shape) == 4 and shape[1] >= 16 and shape[2] >= 16
                and shape[3] <= 4 and dtype in ("float32", "float64")):
            out.append(f"{path} {dtype}{list(shape)}")
    return out


# ----------------------------------------------------------- case builders


@dataclass
class IRCase:
    """One lowering case: the real train step of ``models`` (a GAN case
    covers its component registry entries) at a pinned small batch."""

    name: str
    models: tuple[str, ...]
    batch: int
    build: Callable  # (batch:int) -> (state_sds, batch_sds, step_fn)
    notes: str = ""


def _cls_build(cfg_name: str, *, registry_name: str | None = None,
               f32_wire: bool = False):
    """Classification family: the shipped config's geometry, optimizer,
    model_kwargs AND numerics policy — the config's explicit
    ``precision`` declaration decides the model dtype and loss-scale
    state, so the gate lowers the program training actually runs
    (``registry_name`` lowers a converter-parity variant under the base
    config); uint8 wire + on-device normalization unless the feed has
    no uint8 source (mnist/synthetic → ``f32_wire``)."""

    def build(batch: int, precision: str | None = None):
        from functools import partial

        import jax
        import numpy as np

        from deepvision_tpu.core.precision import get_policy
        from deepvision_tpu.models import get_model
        from deepvision_tpu.train.configs import get_config
        from deepvision_tpu.train.optimizers import make_optimizer
        from deepvision_tpu.train.state import create_train_state
        from deepvision_tpu.train.steps import classification_train_step

        cfg = get_config(cfg_name)
        policy = get_policy(precision or cfg["precision"])
        size, ch = cfg["input_size"], cfg["channels"]
        kwargs = dict(cfg.get("model_kwargs", {}))
        if registry_name is not None:
            kwargs = {}  # variants don't take the base's model_kwargs
        model = get_model(registry_name or cfg_name,
                          num_classes=cfg["num_classes"],
                          dtype=policy.compute_dtype, **kwargs)
        tx, _ = make_optimizer(cfg, steps_per_epoch=100)
        kind = "torch" if cfg.get("augment") == "pt" else "imagenet"
        wire = np.float32 if f32_wire else np.uint8
        SDS = jax.ShapeDtypeStruct
        state = jax.eval_shape(
            lambda s: create_train_state(model, tx, s, policy=policy),
            SDS((1, size, size, ch), wire))
        batch_sds = {"image": SDS((batch, size, size, ch), wire),
                     "label": SDS((batch,), np.int32)}
        return state, batch_sds, partial(
            classification_train_step, normalize_kind=kind)

    return build


def _det_build(model_name: str, size: int, num_classes: int,
               step_attr: str, opt: str):
    def build(batch: int, precision: str | None = None):
        import jax
        import numpy as np
        import optax

        import deepvision_tpu.train.steps as S
        from deepvision_tpu.core.precision import get_policy
        from deepvision_tpu.models import get_model
        from deepvision_tpu.train.configs import get_config
        from deepvision_tpu.train.state import create_train_state

        cfg = get_config(model_name)
        policy = get_policy(precision or cfg["precision"])
        model = get_model(model_name, num_classes=num_classes,
                          dtype=policy.compute_dtype,
                          **cfg.get("model_kwargs", {}))
        tx = optax.adam(1e-3) if opt == "adam" \
            else optax.sgd(1e-3, momentum=0.9)
        SDS = jax.ShapeDtypeStruct
        # detection readers ship uint8 (as_uint8); the step tanh-
        # normalizes on device — same {'image','boxes','label'} contract
        # as bench._zoo_case
        state = jax.eval_shape(
            lambda s: create_train_state(model, tx, s, policy=policy),
            SDS((1, size, size, 3), np.uint8))
        batch_sds = {
            "image": SDS((batch, size, size, 3), np.uint8),
            "boxes": SDS((batch, 16, 4), np.float32),
            "label": SDS((batch, 16), np.int32),
        }
        return state, batch_sds, getattr(S, step_attr)

    return build


def _pose_build():
    def build(batch: int, precision: str | None = None):
        import jax
        import numpy as np
        import optax

        import deepvision_tpu.train.steps as S
        from deepvision_tpu.core.precision import get_policy
        from deepvision_tpu.models import get_model
        from deepvision_tpu.train.configs import get_config
        from deepvision_tpu.train.state import create_train_state

        # the shipped config's policy: bf16_scaled since ISSUE 15 (f32
        # residual carrier + MixedBatchNorm + dynamic loss scaling —
        # the structural fix for the r4 bf16 finding) with "stack"
        # remat; the WIRE is still uint8 (pose reader as_uint8)
        cfg = get_config("hourglass104")
        policy = get_policy(precision or cfg["precision"])
        model = get_model("hourglass104", num_heatmaps=16,
                          dtype=policy.compute_dtype,
                          **cfg.get("model_kwargs", {}))
        tx = optax.rmsprop(2.5e-4)
        SDS = jax.ShapeDtypeStruct
        state = jax.eval_shape(
            lambda s: create_train_state(model, tx, s, policy=policy),
            SDS((1, 256, 256, 3), np.uint8))
        batch_sds = {
            "image": SDS((batch, 256, 256, 3), np.uint8),
            "kx": SDS((batch, 16), np.float32),
            "ky": SDS((batch, 16), np.float32),
            "v": SDS((batch, 16), np.float32),
        }
        return state, batch_sds, S.pose_train_step

    return build


def _dcgan_build():
    def build(batch: int, precision: str | None = None):
        import jax
        import numpy as np

        from deepvision_tpu.core.precision import get_policy
        from deepvision_tpu.models import get_model
        from deepvision_tpu.train.configs import get_config
        from deepvision_tpu.train.gan import (
            create_dcgan_state,
            dcgan_train_step,
        )

        policy = get_policy(precision
                            or get_config("dcgan")["precision"])
        SDS = jax.ShapeDtypeStruct
        # f32 [-1,1] reals (no record pipeline for the mnist-class GAN);
        # simultaneous G+D update is the compiled program (bench parity)
        state = jax.eval_shape(lambda _: create_dcgan_state(
            get_model("dcgan_generator", dtype=policy.compute_dtype),
            get_model("dcgan_discriminator",
                      dtype=policy.compute_dtype),
            policy=policy),
            0)
        batch_sds = {"image": SDS((batch, 28, 28, 1), np.float32)}
        return state, batch_sds, dcgan_train_step

    return build


def _cyclegan_build():
    def build(batch: int, precision: str | None = None):
        import jax
        import numpy as np

        from deepvision_tpu.core.precision import get_policy
        from deepvision_tpu.models import get_model
        from deepvision_tpu.train.configs import get_config
        from deepvision_tpu.train.gan import (
            create_cyclegan_state,
            cyclegan_train_step,
        )

        policy = get_policy(precision
                            or get_config("cyclegan")["precision"])
        SDS = jax.ShapeDtypeStruct
        state = jax.eval_shape(lambda _: create_cyclegan_state(
            get_model("cyclegan_generator", dtype=policy.compute_dtype),
            get_model("cyclegan_discriminator",
                      dtype=policy.compute_dtype),
            policy=policy),
            0)
        batch_sds = {"a": SDS((batch, 256, 256, 3), np.float32),
                     "b": SDS((batch, 256, 256, 3), np.float32)}
        return state, batch_sds, cyclegan_train_step

    return build


def make_cases() -> dict[str, IRCase]:
    """Every registry entry mapped to its real-step lowering case (the
    GAN component models share their trainer's composite case; the
    converter-parity ``*_tf``/``*_ref`` variants lower the variant model
    under the base config's geometry). Batches are CPU-affordable and
    fixed so HBM baselines are comparable run-to-run."""
    cases: dict[str, IRCase] = {}

    def cls(case_name: str, cfg_name: str, batch: int, *,
            registry_name: str | None = None, f32_wire: bool = False,
            notes: str = ""):
        cases[case_name] = IRCase(
            case_name, (registry_name or cfg_name,), batch,
            _cls_build(cfg_name, registry_name=registry_name,
                       f32_wire=f32_wire),
            notes)

    cls("lenet5", "lenet5", 64, f32_wire=True,
        notes="mnist/synthetic feed ships f32 1-channel")
    cls("alexnet1", "alexnet1", 8)
    cls("alexnet2", "alexnet2", 8)
    cls("vgg16", "vgg16", 8)
    cls("vgg19", "vgg19", 8)
    cls("inception1", "inception1", 8)
    cls("inception3", "inception3", 4)
    cls("resnet34", "resnet34", 8)
    cls("resnet50", "resnet50", 8)
    cls("resnet50v2", "resnet50v2", 8)
    cls("resnet152", "resnet152", 4)
    cls("mobilenet1", "mobilenet1", 8)
    cls("shufflenet1", "shufflenet1", 8)
    cls("darknet53", "darknet53", 4)
    # converter-parity variants: the variant MODEL under the base
    # config's geometry/step (they have no training config of their own)
    for variant, base in (("lenet5_tf", "lenet5"),
                          ("alexnet2_tf", "alexnet2"),
                          ("inception1_ref", "inception1")):
        f32 = base == "lenet5"
        cls(variant, base, 64 if f32 else 8, registry_name=variant,
            f32_wire=f32,
            notes=f"converter-parity variant of {base}")
    cases["yolov3"] = IRCase(
        "yolov3", ("yolov3",), 2,
        _det_build("yolov3", 416, 20, "yolo_train_step", "sgd"))
    cases["centernet"] = IRCase(
        "centernet", ("centernet",), 4,
        _det_build("centernet", 256, 80, "centernet_train_step", "adam"))
    cases["hourglass104"] = IRCase(
        "hourglass104", ("hourglass104",), 2, _pose_build(),
        "bf16_scaled + f32 carrier + stack remat (ISSUE 15 diet)")
    cases["dcgan"] = IRCase(
        "dcgan", ("dcgan_generator", "dcgan_discriminator"), 64,
        _dcgan_build(), "simultaneous G+D update, f32 [-1,1] reals")
    # batch 2, not 1: a size-1 batch dim is DEGENERATE for the
    # stability contract (grad-of-broadcast reduces (1,C) vs (C,) when
    # the leading dim is 1 — a jax transpose-rule artifact, not a model
    # hazard); buckets 2/4 compare clean
    cases["cyclegan"] = IRCase(
        "cyclegan", ("cyclegan_generator", "cyclegan_discriminator"), 2,
        _cyclegan_build(), "two-phase G+D update, f32 [-1,1] reals")
    return cases


# ----------------------------------------------------------------- checks


def check_case(case: IRCase, ircfg: IRCheckConfig, *,
               mesh_shape: tuple[int, int] = (1, 1),
               bf16_ready: bool = False, diet: bool = False,
               allow_mesh_clamp: bool = False) -> dict:
    """Lower + compile one case and evaluate every contract; returns a
    report dict (``ok``/``failures``/measurements). Never raises — a
    broken build is itself a gate failure."""
    import jax

    from deepvision_tpu.core import create_mesh
    from deepvision_tpu.core.step import compile_train_step
    from tools.hbm_budget import hbm_gb_per_step

    # a mesh bigger than this box can hold would fail every case in
    # create_mesh before any contract ran. This used to SILENTLY clamp
    # to 1x1 — which compiled an unsharded program and skipped the real
    # audit while printing "ok". The CLI now forces virtual host
    # devices up front (ensure_host_device_count), so a short box is an
    # explicit FAILURE unless the caller opts into the clamp
    # (--allow-mesh-clamp: the axis-NAME audit is still meaningful at
    # 1x1; nothing else about the sharded program is).
    n_dev = len(jax.devices())
    clamped = mesh_shape[0] * mesh_shape[1] > n_dev
    build_shape = (1, 1) if clamped else mesh_shape
    mesh_str = f"{build_shape[0]}x{build_shape[1]}"
    rep: dict = {"case": case.name, "models": list(case.models),
                 "batch": case.batch, "mesh": mesh_str,
                 "platform": jax.default_backend(), "ok": False,
                 "failures": [], "notes": []}
    if clamped and not allow_mesh_clamp:
        rep["failures"].append(
            f"mesh {mesh_shape[0]}x{mesh_shape[1]} needs "
            f"{mesh_shape[0] * mesh_shape[1]} devices, have {n_dev} — "
            "refusing to audit a silently-clamped 1x1 program; run the "
            "CLI (it forces XLA_FLAGS=--xla_force_host_platform_"
            "device_count before jax loads) or pass --allow-mesh-clamp "
            "to accept the axis-name-only audit")
        return rep
    if clamped:
        rep["notes"].append(
            f"mesh {mesh_shape[0]}x{mesh_shape[1]} needs "
            f"{mesh_shape[0] * mesh_shape[1]} devices, have {n_dev} — "
            "compiling at 1x1 (--allow-mesh-clamp: only the collective "
            "axis-name audit is meaningful; run on a bigger slice or "
            "under forced host devices for the sharded program)")
    try:
        b1, b2 = case.batch, case.batch * 2
        state, batch1, step_fn = case.build(b1)
        SDS = jax.ShapeDtypeStruct
        # the 2x bucket differs only in the leading (batch) dim — derive
        # it instead of paying a second model/optimizer/state build
        batch2 = jax.tree.map(
            lambda sl: SDS((sl.shape[0] * 2, *sl.shape[1:]), sl.dtype),
            batch1)
        key = SDS((), jax.random.key(0).dtype)

        # (c) recompile stability across two bucket sizes
        j1 = jax.make_jaxpr(step_fn)(state, batch1, key)
        j2 = jax.make_jaxpr(step_fn)(state, batch2, key)

        # (e2) backend-neutral wire ledger: logical HBM bytes of the
        # traced step at the avals' own dtypes (convert-fused) — the
        # number the bf16 diet provably moves on EVERY backend (the
        # cpu backend's float normalization blinds cost analysis to
        # dtype; see jaxpr_wire_bytes)
        wire_gb = round(jaxpr_wire_bytes(j1.jaxpr) / 1e9, 3)
        rep["wire_gb_per_step"] = wire_gb

        if diet:
            # the diet twin: the SAME case traced under the f32 policy;
            # the wire-byte ratio is the measured mixed-precision diet.
            # Builders without a precision override (synthetic test
            # cases) twin with themselves — an honest zero.
            import inspect

            takes_precision = "precision" in inspect.signature(
                case.build).parameters
            state32, batch32, step32 = (
                case.build(b1, precision="f32") if takes_precision
                else case.build(b1))
            j32 = jax.make_jaxpr(step32)(state32, batch32, key)
            wire32 = round(jaxpr_wire_bytes(j32.jaxpr) / 1e9, 3)
            rep["wire_f32_gb_per_step"] = wire32
            rep["diet_reduction"] = round(
                1.0 - wire_gb / wire32, 4) if wire32 > 0 else 0.0

        diffs = compare_jaxprs(j1.jaxpr, j2.jaxpr, b1, b2)
        rep["stability_diffs"] = diffs[:8]
        if diffs:
            rep["failures"].append(
                f"jaxpr unstable across buckets {b1}/{b2}: {diffs[0]}")

        # (d) collective audit: named axes vs the declared mesh
        mesh = create_mesh(*build_shape)
        axes_used = collect_axis_names(j1.jaxpr)
        bad_axes = sorted(axes_used - set(mesh.axis_names))
        rep["collective_axes"] = sorted(axes_used)
        if bad_axes:
            rep["failures"].append(
                f"collective axis name(s) {bad_axes} not on the declared "
                f"mesh {tuple(mesh.axis_names)}")

        # (b) pixel wire dtype (IR twin of JX114) on the H2D boundary
        leaves = [
            (jax.tree_util.keystr(path), tuple(leaf.shape),
             str(leaf.dtype))
            for path, leaf in
            jax.tree_util.tree_flatten_with_path(batch1)[0]
        ]
        pix = pixel_f32_inputs(leaves)
        rep["pixel_f32_inputs"] = pix
        if pix:
            waiver = None
            for m in case.models:
                waiver = waiver or ircfg.dtype_waiver(m)
            waiver = waiver or ircfg.dtype_waiver(case.name)
            if waiver is not None:
                waiver.hits += 1
                rep["notes"].append(
                    f"f32 pixel input waived: {waiver.reason}")
            else:
                rep["failures"].append(
                    "f32 pixel tensor(s) on the H2D boundary (ship "
                    f"uint8, normalize on device): {pix}")

        # compile ONCE at the primary bucket for the executable checks
        step = compile_train_step(step_fn, mesh)
        compiled = step.lower(state, batch1, key).compile()
        hlo = compiled.as_text()

        # (b) no f64 anywhere in the optimized program
        rep["f64"] = "f64[" in hlo
        if rep["f64"]:
            rep["failures"].append(
                "f64 present in the optimized HLO (double-precision is "
                "never intended on TPU; find the np.float64 promotion)")

        # (a) donation: state buffers actually aliased input->output.
        # The leaf->parameter attribution assumes state leaves are
        # parameters 0..n_state-1 in tree order. jit's default
        # keep_unused=False prunes unused inputs and renumbers — a
        # pruned KEY/batch input (an rng the model never consumes, as
        # lenet/hourglass legitimately do) sits AFTER the state prefix
        # and is harmless, but a pruned/reordered STATE leaf would
        # silently misattribute the alias map. Guard: every state
        # leaf's canonical shape must match its entry parameter.
        import math

        import numpy as np

        from tools.hbm_budget import strip_layouts

        aliased = parse_alias_map(hlo)
        state_leaves = jax.tree.leaves(state)
        n_state = len(state_leaves)
        pshapes = entry_param_shapes(strip_layouts(hlo))
        misaligned = [
            i for i, sl in enumerate(state_leaves)
            if pshapes.get(i) != canon_shape(
                np.dtype(sl.dtype).name, tuple(sl.shape))
        ]
        if misaligned:
            rep["failures"].append(
                f"{len(misaligned)}/{n_state} state leaves do not align "
                "with entry parameters 0..n-1 (first mismatch: leaf "
                f"{misaligned[0]} expects "
                f"{canon_shape(np.dtype(state_leaves[misaligned[0]].dtype).name, tuple(state_leaves[misaligned[0]].shape))}, "
                f"parameter is {pshapes.get(misaligned[0])!r}) — jit "
                "pruned or reordered a state input, so donation "
                "attribution is invalid; a state leaf the step never "
                "reads is itself a bug to fix first")

        bytes_per = [
            (math.prod(sl.shape) if sl.shape else 1)
            * np.dtype(sl.dtype).itemsize
            for sl in state_leaves
        ]
        total_b = sum(bytes_per) or 1
        undonated = [i for i in range(n_state) if i not in aliased]
        undonated_b = sum(bytes_per[i] for i in undonated)
        frac = 1.0 - undonated_b / total_b
        rep["donated_fraction"] = round(frac, 6)
        rep["undonated_leaves"] = len(undonated)
        rep["state_gb"] = round(total_b / 1e9, 3)
        if frac < ircfg.donation_min_fraction:
            # waivers may be keyed by a covered registry model OR the
            # case name (same lookup order as the dtype ledger)
            waiver = None
            for m in case.models:
                waiver = waiver or ircfg.donation_waiver(m)
            waiver = waiver or ircfg.donation_waiver(case.name)
            if waiver is not None:
                # consulted counts as a hit even when the bound is
                # exceeded — an INSUFFICIENT waiver must not be called
                # stale ("delete the entry") by the run summary
                waiver.hits += 1
            if waiver is not None and \
                    (1.0 - frac) <= waiver.max_undonated_fraction:
                rep["notes"].append(
                    f"donation waived ({1 - frac:.1%} undonated "
                    f"<= {waiver.max_undonated_fraction:.1%}): "
                    f"{waiver.reason}")
            else:
                over = ("" if waiver is None else
                        f" (waiver allows only "
                        f"{waiver.max_undonated_fraction:.1%} undonated)")
                rep["failures"].append(
                    f"only {frac:.1%} of state bytes aliased "
                    f"input->output (min {ircfg.donation_min_fraction:.0%}"
                    f"; {len(undonated)}/{n_state} leaves undonated)"
                    f"{over} — the optimizer update copies instead of "
                    "updating in place; fix the donation or add a "
                    "reasoned [[ircheck.donation]] waiver")

        # (e) HBM-budget regression ledger. 0.0 means the build's
        # cost_analysis() is unavailable (the skew cost_analysis_dict
        # absorbs) — comparing THAT against the band would read as a
        # miraculous improvement and disarm the gate, and recording it
        # would poison the ledger with 0.0 rows.
        gb = round(hbm_gb_per_step(compiled), 3)
        base = ircfg.hbm_baseline(case.name, rep["platform"],
                                  mesh_str, case.batch)
        if gb <= 0.0:
            rep["notes"].append(
                "XLA cost analysis unavailable on this build — HBM "
                "ledger not evaluated (and nothing recorded)")
        else:
            rep["hbm_gb_per_step"] = gb
            if base is None:
                rep["notes"].append(
                    "no hbm baseline for this (platform, mesh, batch) — "
                    "record with --record")
                rep["hbm_unbaselined"] = True
            else:
                hi = base.hbm_gb_per_step * (1 + ircfg.hbm_tolerance)
                lo = base.hbm_gb_per_step * (1 - ircfg.hbm_tolerance)
                if gb > hi:
                    rep["failures"].append(
                        f"hbm_gb_per_step {gb} exceeds baseline "
                        f"{base.hbm_gb_per_step} by more than "
                        f"{ircfg.hbm_tolerance:.0%} — the HBM diet only "
                        "ratchets DOWN; fix the regression or "
                        "consciously re-record the baseline")
                elif gb < lo:
                    rep["notes"].append(
                        f"hbm improved {base.hbm_gb_per_step} -> {gb}; "
                        "re-record the baseline to lock the gain in")
        # the wire ledger gates with the same band (wire baselines are
        # optional fields on the same [[ircheck.hbm]] rows)
        if base is not None and base.wire_gb_per_step is not None:
            hi = base.wire_gb_per_step * (1 + ircfg.hbm_tolerance)
            lo = base.wire_gb_per_step * (1 - ircfg.hbm_tolerance)
            if wire_gb > hi:
                rep["failures"].append(
                    f"wire_gb_per_step {wire_gb} exceeds baseline "
                    f"{base.wire_gb_per_step} by more than "
                    f"{ircfg.hbm_tolerance:.0%} — the diet's "
                    "dtype-faithful ledger only ratchets DOWN")
            elif wire_gb < lo:
                rep["notes"].append(
                    f"wire bytes improved {base.wire_gb_per_step} -> "
                    f"{wire_gb}; re-record to lock the gain in")
        elif base is not None:
            rep["notes"].append(
                "hbm baseline has no wire_gb_per_step yet — re-record "
                "to arm the dtype-faithful gate")

        # (f) the diet assertion ([[ircheck.diet]]): the measured
        # bf16-vs-f32 wire reduction must clear the model's declared
        # floor — the "≥40% for the deep models" acceptance, enforced
        # on the traced artifact, not claimed
        if diet and rep.get("diet_reduction") is not None:
            target = ircfg.diet_target(case.name) or next(
                (ircfg.diet_target(m) for m in case.models
                 if ircfg.diet_target(m) is not None), None)
            if target is not None \
                    and rep["diet_reduction"] < target.min_reduction:
                rep["failures"].append(
                    f"mixed-precision diet {rep['diet_reduction']:.1%} "
                    f"below the declared floor "
                    f"{target.min_reduction:.0%} for {target.model} "
                    f"(wire {rep['wire_f32_gb_per_step']} GB f32 -> "
                    f"{rep['wire_gb_per_step']} GB policy)")

        if bf16_ready:
            rep["bf16_ready"] = f32_surface(j1.jaxpr)
        rep["ok"] = not rep["failures"]
    # a broken build/lower/compile IS the gate failure being reported —
    # nothing is swallowed, the case fails with the traceback attached
    except Exception as e:  # jaxlint: disable=JX111
        rep["failures"].append(f"{type(e).__name__}: {e}")
        rep["trace"] = traceback.format_exc(limit=10)
    return rep


def record_toml(rep: dict) -> str:
    """A ready-to-paste ``[[ircheck.hbm]]`` baseline block for one
    case report (wire ledger row included when measured)."""
    wire = rep.get("wire_gb_per_step")
    return (
        "[[ircheck.hbm]]\n"
        f'model = "{rep["case"]}"\n'
        f'platform = "{rep["platform"]}"\n'
        f'mesh = "{rep["mesh"]}"\n'
        f"batch = {rep['batch']}\n"
        f"hbm_gb_per_step = {rep['hbm_gb_per_step']}\n"
        + (f"wire_gb_per_step = {wire}\n" if wire is not None else "")
    )


def run(names: list[str] | None = None, *, config: str = "jaxlint.toml",
        fast: bool = False, mesh: tuple[int, int] = (1, 1),
        bf16_ready: bool = False, record: bool = False,
        diet: bool = False, verbose: bool = False,
        allow_mesh_clamp: bool = False) -> int:
    ircfg = load_ircheck_config(config)
    cases = make_cases()
    if names:
        unknown = sorted(set(names) - set(cases))
        if unknown:
            print(f"unknown case(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(cases))})", file=sys.stderr)
            return 2
        selected = [cases[n] for n in names]
    elif fast:
        unknown_fast = [n for n in ircfg.fast_models if n not in cases]
        if unknown_fast:
            # a typo here would silently narrow the per-PR gate
            print(f"warning: [ircheck] fast_models entr"
                  f"{'ies' if len(unknown_fast) > 1 else 'y'} "
                  f"{unknown_fast} match no case "
                  f"(known: {', '.join(sorted(cases))})", file=sys.stderr)
        selected = [cases[n] for n in ircfg.fast_models if n in cases]
        if not selected:
            # an empty/mistyped subset must not let the per-PR gate
            # pass green having verified nothing
            print("error: --fast selected ZERO cases — fix [ircheck] "
                  "fast_models in jaxlint.toml", file=sys.stderr)
            return 2
    else:
        selected = list(cases.values())
    failures = 0
    crashed_models: set[str] = set()
    to_record: list[str] = []
    models_covered: set[str] = set()
    diet_cuts: list[float] = []
    for case in selected:
        rep = check_case(case, ircfg, mesh_shape=mesh,
                         bf16_ready=bf16_ready, diet=diet,
                         allow_mesh_clamp=allow_mesh_clamp)
        models_covered.update(rep["models"])
        status = "ok  " if rep["ok"] else "FAIL"
        gb = rep.get("hbm_gb_per_step", "-")
        wire = rep.get("wire_gb_per_step", "-")
        frac = rep.get("donated_fraction")
        frac_s = f"{frac:.3f}" if isinstance(frac, float) else "-"
        cut = rep.get("diet_reduction")
        cut_s = f" diet={cut:.1%}" if cut is not None else ""
        if cut is not None:
            diet_cuts.append(cut)
        print(f"{status} {case.name:16s} b{case.batch:<3d} "
              f"donated={frac_s} hbm={gb}GB wire={wire}GB{cut_s} "
              f"axes={','.join(rep.get('collective_axes', [])) or '-'}")
        for note in rep["notes"]:
            print(f"     note: {note}")
        for f in rep["failures"]:
            print(f"     FAIL: {f}")
        if verbose and "trace" in rep:
            print(rep["trace"], file=sys.stderr)
        if bf16_ready and "bf16_ready" in rep:
            surf = rep["bf16_ready"]
            print(f"     residual f32 surface: {surf['total_mb']} MB "
                  "(post-diet this is the POLICY FLOOR — BN statistics "
                  "accumulation, f32 heads/carriers, loss reductions; "
                  "JX123 gates new raw-f32 out of hot bodies)")
            for shape, r in list(surf["shapes"].items())[:6]:
                print(f"       x{r['count']:<4d} "
                      f"{r['bytes_each']/1e6:8.1f} MB each  {shape}")
        if record and "hbm_gb_per_step" in rep:
            # --record is the (re-)record flow: print a paste-ready
            # block for every measured case, not only missing ones —
            # the diet re-bases the whole ledger at once
            to_record.append(record_toml(rep))
        if "trace" in rep:  # crashed before the waiver checks ran
            crashed_models.update({case.name, *case.models})
        failures += 0 if rep["ok"] else 1
    # stale-waiver warnings: the ledgers burn down, they don't accrete.
    # Only waivers whose case actually RAN TO COMPLETION can be judged
    # stale — a subset run (--fast, explicit names) must not cry wolf
    # about the rest of the registry, and a case that crashed before
    # its waiver checks must not get its (still needed) waiver deleted.
    sel_cases = {c.name for c in selected} - crashed_models
    sel_models = (sel_cases | {m for c in selected for m in c.models}) \
        - crashed_models
    for w in ircfg.donation:
        if w.hits == 0 and w.model in sel_models:
            print(f"warning: stale ircheck.donation waiver "
                  f"{w.model!r} ({w.reason}) — the gate passes without "
                  "it; delete the entry", file=sys.stderr)
    for w in ircfg.dtype:
        if w.hits == 0 and w.model in sel_models:
            print(f"warning: stale ircheck.dtype waiver {w.model!r} "
                  f"({w.reason}) — nothing matched; delete the entry",
                  file=sys.stderr)
    if record and to_record:
        print("\n# paste into jaxlint.toml (recorded hbm baselines):")
        print("\n".join(to_record))
    if diet and diet_cuts:
        import statistics

        med = statistics.median(diet_cuts)
        print(f"diet: median mixed-precision wire reduction "
              f"{med:.1%} over {len(diet_cuts)} cases "
              f"(floor {ircfg.diet_median_min:.0%})")
        if len(diet_cuts) >= len(cases) and med < ircfg.diet_median_min:
            # the registry-median floor only judges FULL sweeps — a
            # subset median would cry wolf (or pass) on a biased sample
            print(f"FAIL: registry-median diet {med:.1%} below the "
                  f"{ircfg.diet_median_min:.0%} floor", file=sys.stderr)
            failures += 1
    n = len(selected)
    print(f"ircheck: {n - failures}/{n} cases pass "
          f"({len(models_covered)} registry models covered)")
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.jaxlint.ircheck",
        description="compiled-IR contract gate over the model registry "
                    "(donation / dtype / recompile stability / "
                    "collectives / HBM ledger; tools/jaxlint/ircheck.py)",
    )
    parser.add_argument("names", nargs="*",
                        help="case names (default: every registry case)")
    parser.add_argument("--config", default="jaxlint.toml")
    parser.add_argument("--fast", action="store_true",
                        help="only the [ircheck] fast_models subset "
                             "(the tier-1/`make check` slice)")
    parser.add_argument("--mesh", default="1,1",
                        help="mesh shape N,M to audit against "
                             "(default 1,1: deterministic + cheap)")
    parser.add_argument("--bf16-ready", action="store_true",
                        help="report the f32 activation surface per "
                             "model (ROADMAP item-2 worklist)")
    parser.add_argument("--record", action="store_true",
                        help="print paste-ready [[ircheck.hbm]] TOML "
                             "(hbm + wire rows) for every measured "
                             "case — the (re-)record flow")
    parser.add_argument("--diet", action="store_true",
                        help="trace each case's f32 twin and assert "
                             "the mixed-precision wire-byte reduction "
                             "against [[ircheck.diet]] floors + the "
                             "registry-median floor")
    parser.add_argument("--allow-mesh-clamp", action="store_true",
                        help="accept compiling at 1x1 when the box has "
                             "fewer devices than --mesh needs (axis-"
                             "name audit only); the default is to FAIL "
                             "such cases")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    try:
        n, m = (int(x) for x in args.mesh.split(","))
    except ValueError:
        parser.error(f"--mesh expects N,M (got {args.mesh!r})")
    # BEFORE any jax import: multiply the host platform so --mesh N,M
    # compiles a genuine NxM SPMD program on a CPU box instead of the
    # old silent 1x1 clamp (the flag is read at backend creation)
    ensure_host_device_count(n * m)
    return run(args.names or None, config=args.config, fast=args.fast,
               mesh=(n, m), bf16_ready=args.bf16_ready,
               record=args.record, diet=args.diet,
               verbose=args.verbose,
               allow_mesh_clamp=args.allow_mesh_clamp)


if __name__ == "__main__":
    sys.exit(main())
