"""The jaxlint checker set (JX101–JX116).

Each checker targets one class of TPU step-time/correctness hazard that
pytest cannot see (the program stays *correct* — it just recompiles,
syncs, or silently correlates PRNG streams). See the package docstring
for the one-line inventory and README "Static analysis" for how to add
a checker. Since ISSUE 10 the loop/wire checkers (JX109/JX114) and the
traced-reachability checkers (JX101/JX102/JX106) consume the
interprocedural ProjectContext (tools/jaxlint/core.py): hazards routed
through helper functions and module boundaries are resolved through the
project call graph — the ``*_funcs`` knobs seed the callable sets, the
dataflow closes them.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Iterator

from tools.jaxlint.core import (
    NP_MATERIALIZERS,
    Checker,
    Finding,
    FunctionNode,
    ModuleContext,
    array_names_in,
    assign_target_names,
    call_name,
    dotted_name,
    is_host_blocking_call,
    iter_own_nodes,
    last_attr,
    path_matches_dir,
    register_checker,
)

_NP_MATERIALIZERS = NP_MATERIALIZERS
_HOST_SYNC_METHODS = {"item", "tolist"}
_LAYOUT_ATTRS = {"reshape", "transpose", "swapaxes", "moveaxis"}


@register_checker
class HostSyncChecker(Checker):
    """Host↔device syncs inside traced code: every one serializes the
    dispatch queue (the device idles while the host waits on a D2H
    transfer) — the dominant silent step-time regression on TPU."""

    code = "JX101"
    name = "host-sync-in-trace"
    description = ("'.item()'/'.tolist()'/np.asarray/float() on a traced "
                   "value inside jit-reachable code")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for f in mod.traced_functions():
            tainted = mod.tainted_names(f.node)
            for node in ast.walk(f.node):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _HOST_SYNC_METHODS:
                    yield mod.finding(
                        node, self.code,
                        f"'.{node.func.attr}()' forces a device->host "
                        "sync inside traced code; keep the value on "
                        "device (or fetch it outside the step)")
                    continue
                name = call_name(node)
                if name in _NP_MATERIALIZERS:
                    yield mod.finding(
                        node, self.code,
                        f"'{name}' materializes a concrete array inside "
                        "traced code; use jnp.asarray (trace-safe) or "
                        "move the conversion to the host pipeline")
                elif name == "jax.device_get":
                    yield mod.finding(
                        node, self.code,
                        "'jax.device_get' inside traced code is a "
                        "host sync; fetch results after the step returns")
                elif name in ("float", "int", "bool") and len(node.args) == 1 \
                        and mod.expr_is_tainted(node.args[0], tainted):
                    yield mod.finding(
                        node, self.code,
                        f"'{name}()' on a traced value blocks on a "
                        "device->host transfer; keep it as a jnp scalar "
                        "(convert on the host after the step)")


@register_checker
class TracedBranchChecker(Checker):
    """Python ``if``/``while`` on a traced array value: concretizes the
    tracer (ConcretizationTypeError at best; at worst the branch is
    burned in at trace time and silently wrong for other inputs)."""

    code = "JX102"
    name = "python-branch-on-traced"
    description = ("Python if/while on a traced array value instead of "
                   "lax.cond/lax.while_loop/jnp.where")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for f in mod.traced_functions():
            tainted = mod.tainted_names(f.node)
            for node in ast.walk(f.node):
                if isinstance(node, (ast.If, ast.While)):
                    test, kind = node.test, (
                        "while" if isinstance(node, ast.While) else "if")
                elif isinstance(node, ast.IfExp):
                    test, kind = node.test, "conditional expression"
                else:
                    continue
                if _is_none_check(test):
                    continue  # 'x is None' resolves statically at trace
                if mod.expr_is_tainted(test, tainted):
                    names = sorted({n.id for n in array_names_in(test)
                                    if n.id in tainted})
                    what = f" on {', '.join(names)!s}" if names else ""
                    yield mod.finding(
                        node, self.code,
                        f"Python {kind}{what} branches on a traced "
                        "value; use jax.lax.cond/jax.lax.while_loop "
                        "(or jnp.where for elementwise selects)")


def _is_none_check(test: ast.AST) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


@register_checker
class KeyReuseChecker(Checker):
    """PRNG key reuse: the same key consumed by two ``jax.random``-style
    draws yields *correlated* streams (identical numbers), silently
    degrading augmentation/dropout/GAN noise. The blessed idioms are
    ``key, sub = jax.random.split(key)``, ``jax.random.fold_in(key, i)``
    with distinct data, and ``next(KeySeq)`` (core/prng.py)."""

    code = "JX103"
    name = "prng-key-reuse"
    description = ("a PRNG key passed to >=2 consumers without an "
                   "intervening split/fold_in")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for f in mod.traced_functions():
            yield from _KeyScan(mod, f.node).run()
        # host-side loops thread keys too (epoch loops); scan untraced
        # functions that visibly handle keys, same rules
        for info in mod.functions:
            if mod.is_traced(info.node):
                continue
            if info.parent is not None:
                continue
            yield from _KeyScan(mod, info.node).run()


class _KeyScan:
    """Flow-sensitive-enough sequential scan of one function:

    - tracks names that look like keys (``key``/``rng``-ish params and
      anything assigned from split/fold_in/key()/next()/take());
    - counts consumptions (a tracked name passed to any non-freshener
      call; indexed subkeys like ``keys[i]`` don't count the base name);
    - ``split(key)`` itself counts — *using a key after splitting it*
      is the classic reuse bug — while the canonical
      ``key, sub = split(key)`` resets the count via its reassignment;
    - ``fold_in(key, data)`` does NOT count (deriving per-step keys from
      one base with distinct fold data is the blessed pattern);
    - loop bodies are scanned twice (models re-entry: a key consumed
      per-iteration without per-iteration splitting is reuse);
    - if/else branches are scanned independently and merged by max.
    """

    def __init__(self, mod: ModuleContext, func: FunctionNode):
        self.mod = mod
        self.cfg = mod.cfg
        self.func = func
        self.counts: dict[str, int] = {}
        self.flagged: set[str] = set()
        self.findings: list[Finding] = []
        self.fresheners = set(self.cfg.key_fresheners)

    def run(self) -> Iterator[Finding]:
        args = self.func.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if any(fnmatch.fnmatch(a.arg, p)
                   for p in self.cfg.key_name_patterns) \
                    and self._param_is_jax_key(a):
                self.counts[a.arg] = 0
        self._stmts(self.func.body)
        yield from self.findings

    def _param_is_jax_key(self, arg: ast.arg) -> bool:
        """Evidence that a key-named parameter really is a jax PRNG key
        (host code passes numpy Generators and torch checkpoint-key
        STRINGS under the same names):

        - an annotation naming jax/Array/Key types confirms it; any
          other annotation (str, np.random.Generator) rules it out;
        - unannotated: yes inside traced code (numpy generators cannot
          appear there), else only if the body visibly feeds the name
          to a ``jax.random.*`` call."""
        if arg.annotation is not None:
            ann = ast.unparse(arg.annotation)
            return bool(re.search(r"jax|Array|Key|PRNG", ann))
        if self.mod.is_traced(self.func):
            return True
        for node in ast.walk(self.func):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            if not ("random." in name or name.startswith("random")):
                continue
            for a in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Name) and sub.id == arg.arg:
                        return True
        return False

    # -- statement walk -------------------------------------------------
    def _stmts(self, stmts: list[ast.stmt]) -> None:
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs run (roughly) where they're used; textual
            # order is the right approximation for closures over keys
            self._stmts(s.body)
        elif isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if s.value is not None:
                self._expr(s.value)
                self._assign(s, s.value)
        elif isinstance(s, ast.Expr):
            self._expr(s.value)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self._expr(s.value)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self._expr(s.iter)
            for _ in range(2):  # model loop re-entry
                self._reset_targets(s)
                self._stmts(s.body)
            self._stmts(s.orelse)
        elif isinstance(s, ast.While):
            for _ in range(2):
                self._expr(s.test)
                self._stmts(s.body)
            self._stmts(s.orelse)
        elif isinstance(s, ast.If):
            self._expr(s.test)
            snap = dict(self.counts)
            self._stmts(s.body)
            body_counts = self.counts
            self.counts = dict(snap)
            self._stmts(s.orelse)
            for k in set(body_counts) | set(self.counts):
                self.counts[k] = max(self.counts.get(k, 0),
                                     body_counts.get(k, 0))
        elif isinstance(s, ast.With):
            for item in s.items:
                self._expr(item.context_expr)
            self._stmts(s.body)
        elif isinstance(s, ast.Try):
            self._stmts(s.body)
            for h in s.handlers:
                self._stmts(h.body)
            self._stmts(s.orelse)
            self._stmts(s.finalbody)

    def _reset_targets(self, s: ast.stmt) -> None:
        from tools.jaxlint.core import assign_target_names

        for name in assign_target_names(s):
            if name in self.counts:
                self.counts[name] = 0

    # -- expression walk ------------------------------------------------
    def _expr(self, e: ast.AST) -> None:
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._call(node)

    def _call(self, call: ast.Call) -> None:
        la = last_attr(call_name(call))
        if la in self.fresheners and la != "split":
            return  # fold_in/key()/... derive, they don't consume
        if la == "next":
            return  # next(KeySeq) is the blessed stateful idiom
        if la in ("isinstance", "len", "type", "hasattr", "getattr",
                  "id", "repr", "str"):
            return  # static predicates don't consume entropy
        if la in ("lower", "eval_shape"):
            return  # AOT lowering/abstract eval read shapes, not entropy
        for name in self._direct_key_args(call):
            self.counts[name] = self.counts.get(name, 0) + 1
            if self.counts[name] >= 2 and name not in self.flagged:
                self.flagged.add(name)
                self.findings.append(self.mod.finding(
                    call, KeyReuseChecker.code,
                    f"PRNG key '{name}' is consumed more than once "
                    "without an intervening split/fold_in — the streams "
                    "are identical; split the key (or use "
                    "core.prng.KeySeq) before each consumer"))

    def _direct_key_args(self, call: ast.Call) -> list[str]:
        """Tracked key names used directly in this call's arguments —
        excluding subtrees owned by nested calls (attributed to the
        nested call), attribute receivers (``self.x`` uses ``x``, not a
        key named ``self``), and indexed subkeys (``keys[i]`` is a
        distinct subkey per index, not a reuse of ``keys``)."""
        out: list[str] = []
        skip: set[int] = set()
        for arg in list(call.args) + [k.value for k in call.keywords]:
            for node in ast.walk(arg):
                if id(node) in skip:
                    continue
                if isinstance(node, ast.Call):
                    for sub in ast.walk(node):
                        if sub is not node:
                            skip.add(id(sub))
                elif isinstance(node, (ast.Subscript, ast.Attribute)):
                    for sub in ast.walk(node):
                        if sub is not node:
                            skip.add(id(sub))
                elif isinstance(node, ast.Name) \
                        and node.id in self.counts \
                        and node.id not in out:
                    out.append(node.id)
        return out

    def _assign(self, stmt: ast.stmt, value: ast.AST) -> None:
        from tools.jaxlint.core import assign_target_names

        names = assign_target_names(stmt)
        if not names:
            return
        mints_keys = False
        if isinstance(value, ast.Call):
            la = last_attr(call_name(value))
            if la in self.fresheners or la in ("next", "take"):
                mints_keys = True
        elif isinstance(value, ast.Name) and value.id in self.counts:
            mints_keys = True  # alias of a tracked key
        for name in names:
            if name in self.counts or mints_keys:
                self.counts[name] = 0
                self.flagged.discard(name)
            if mints_keys:
                self.counts.setdefault(name, 0)


@register_checker
class DonateChecker(Checker):
    """A jitted step that takes the full train state without donating it
    doubles the parameter+optimizer HBM footprint: XLA must keep the
    input buffers alive while writing fresh outputs every step."""

    code = "JX104"
    name = "missing-donate"
    description = ("jitted step function taking the train state without "
                   "donate_argnums")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        by_name = {f.node.name: f for f in mod.functions}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and last_attr(call_name(node)) in ("jit", "pjit") \
                    and node.args:
                wrapped = node.args[0]
                if not isinstance(wrapped, ast.Name):
                    continue  # wrapped expression — can't resolve; skip
                if self._steplike(wrapped.id, by_name) \
                        and not self._donates(node):
                    yield mod.finding(
                        node, self.code,
                        f"jitted step function '{wrapped.id}' does not "
                        "donate its state buffers; pass "
                        "donate_argnums=(0,) so the optimizer update "
                        "reuses the parameter HBM in place")
        for f in mod.functions:
            for deco in f.node.decorator_list:
                target = deco.func if isinstance(deco, ast.Call) else deco
                is_jit = last_attr(dotted_name(target)) in ("jit", "pjit")
                # @partial(jax.jit, ...) — donate kwargs live on the
                # partial call itself
                if not is_jit and isinstance(deco, ast.Call) \
                        and last_attr(call_name(deco)) == "partial":
                    is_jit = any(
                        last_attr(dotted_name(a)) in ("jit", "pjit")
                        for a in deco.args)
                if is_jit \
                        and self._steplike(f.node.name, by_name) \
                        and not (isinstance(deco, ast.Call)
                                 and self._donates(deco)):
                    yield mod.finding(
                        deco, self.code,
                        f"@jit on step function '{f.node.name}' without "
                        "donate_argnums=(0,): state buffers are copied "
                        "every step instead of updated in place")

    @staticmethod
    def _steplike(name: str, by_name: dict) -> bool:
        if "step" in name.lower():
            return True
        f = by_name.get(name)
        if f is None:
            return False
        args = f.node.args.posonlyargs + f.node.args.args
        return bool(args) and args[0].arg == "state"

    @staticmethod
    def _donates(call: ast.Call) -> bool:
        return any(k.arg in ("donate_argnums", "donate_argnames")
                   for k in call.keywords)


@register_checker
class StaticHazardChecker(Checker):
    """Recompile hazards through ``static_argnums``/``static_argnames``:
    a float static recompiles per distinct value (schedules belong in
    traced args); an unhashable static (list/dict) is a TypeError the
    first time the call leaves the happy path."""

    code = "JX105"
    name = "static-arg-hazard"
    description = ("unhashable or float Python values flowing into "
                   "static_argnums/static_argnames")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and last_attr(call_name(node)) in ("jit", "pjit"):
                yield from self._check_jit_call(mod, node, wrapped=(
                    node.args[0] if node.args else None))
        for f in mod.functions:
            for deco in f.node.decorator_list:
                if isinstance(deco, ast.Call) and last_attr(
                        call_name(deco)) in ("jit", "pjit"):
                    yield from self._check_jit_call(
                        mod, deco, wrapped_def=f.node)
                # @partial(jax.jit, static_argnums=...) decorator form
                if isinstance(deco, ast.Call) and last_attr(
                        call_name(deco)) == "partial" and deco.args \
                        and last_attr(dotted_name(deco.args[0])) in (
                            "jit", "pjit"):
                    yield from self._check_jit_call(
                        mod, deco, wrapped_def=f.node)

    def _check_jit_call(self, mod: ModuleContext, call: ast.Call,
                        wrapped: ast.AST | None = None,
                        wrapped_def: FunctionNode | None = None
                        ) -> Iterator[Finding]:
        static_nums = _int_list_kwarg(call, "static_argnums")
        static_names = _str_list_kwarg(call, "static_argnames")
        if not static_nums and not static_names:
            return
        if wrapped_def is None and isinstance(wrapped, ast.Name):
            defs = mod.functions_named(wrapped.id)
            wrapped_def = defs[0].node if defs else None
        if wrapped_def is not None:
            yield from self._check_defaults(
                mod, wrapped_def, static_nums, static_names)
        # call sites of `F = jax.jit(g, static_argnums=...)`
        fname = _assigned_name(mod, call)
        if fname:
            for site in ast.walk(mod.tree):
                if isinstance(site, ast.Call) \
                        and isinstance(site.func, ast.Name) \
                        and site.func.id == fname:
                    yield from self._check_site(
                        mod, site, static_nums, static_names)

    def _check_defaults(self, mod, func, static_nums, static_names
                        ) -> Iterator[Finding]:
        args = func.args.posonlyargs + func.args.args
        defaults = func.args.defaults
        offset = len(args) - len(defaults)
        for i, arg in enumerate(args):
            if i in static_nums or arg.arg in static_names:
                if i >= offset:
                    yield from self._judge_value(
                        mod, defaults[i - offset], arg.arg, "default for")
        for kwarg, default in zip(func.args.kwonlyargs,
                                  func.args.kw_defaults):
            if kwarg.arg in static_names and default is not None:
                yield from self._judge_value(
                    mod, default, kwarg.arg, "default for")

    def _check_site(self, mod, site, static_nums, static_names
                    ) -> Iterator[Finding]:
        for i, arg in enumerate(site.args):
            if i in static_nums:
                yield from self._judge_value(
                    mod, arg, f"position {i}", "value passed to")
        for kw in site.keywords:
            if kw.arg in static_names:
                yield from self._judge_value(
                    mod, kw.value, kw.arg, "value passed to")

    def _judge_value(self, mod, node, label, how) -> Iterator[Finding]:
        if isinstance(node, (ast.List, ast.Dict, ast.Set)):
            yield mod.finding(
                node, self.code,
                f"unhashable {how} static arg {label}: jit static "
                "arguments must be hashable (use a tuple, or make the "
                "argument traced)")
        elif isinstance(node, ast.Constant) and isinstance(
                node.value, float):
            yield mod.finding(
                node, self.code,
                f"float {how} static arg {label}: every distinct value "
                "triggers a full recompile; pass it as a traced array "
                "argument instead")


def _int_list_kwarg(call: ast.Call, name: str) -> set[int]:
    for k in call.keywords:
        if k.arg == name:
            v = k.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)}
    return set()


def _str_list_kwarg(call: ast.Call, name: str) -> set[str]:
    for k in call.keywords:
        if k.arg == name:
            v = k.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return set()


def _assigned_name(mod: ModuleContext, call: ast.Call) -> str | None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and node.value is call:
            if len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name):
                return node.targets[0].id
    return None


@register_checker
class PrintChecker(Checker):
    """``print`` under trace runs ONCE, at trace time, with tracer
    reprs — it looks like logging but logs nothing at run time."""

    code = "JX106"
    name = "print-in-trace"
    description = "print() inside traced code (use jax.debug.print)"

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for f in mod.traced_functions():
            for node in ast.walk(f.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    yield mod.finding(
                        node, self.code,
                        "print() inside traced code executes once at "
                        "trace time with tracer values; use "
                        "jax.debug.print (or print outside the step)")


@register_checker
class DataJnpChecker(Checker):
    """``jnp`` in a host data pipeline hijacks device 0 for per-batch
    preprocessing (and blocks the dispatch queue): ``data/`` is the
    host-side domain — numpy/tf there, jnp only inside the step."""

    code = "JX107"
    name = "jnp-in-data-pipeline"
    description = "jnp/jax.numpy used inside a host data pipeline (data/)"

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        if not path_matches_dir(mod.relpath, mod.cfg.data_dirs):
            return
        aliases = {"jnp"}
        seen_lines: set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.numpy":
                        # a bare `import jax.numpy` binds root `jax` —
                        # don't alias-flag every jax.* use (device_put
                        # in data/ is legitimate host↔device plumbing);
                        # the dotted `jax.numpy` check below still
                        # catches the compute uses
                        if alias.asname:
                            aliases.add(alias.asname)
                        yield from self._flag(mod, node, seen_lines)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax" and any(
                        a.name == "numpy" for a in node.names):
                    for a in node.names:
                        if a.name == "numpy":
                            aliases.add(a.asname or "numpy")
                    yield from self._flag(mod, node, seen_lines)
        for node in ast.walk(mod.tree):
            name = dotted_name(node) if isinstance(
                node, (ast.Attribute, ast.Name)) else None
            if name and (name.split(".", 1)[0] in aliases
                         or name.startswith("jax.numpy")):
                yield from self._flag(mod, node, seen_lines)

    def _flag(self, mod, node, seen_lines) -> Iterator[Finding]:
        line = getattr(node, "lineno", 0)
        if line in seen_lines:
            return
        seen_lines.add(line)
        yield mod.finding(
            node, self.code,
            "jnp compute inside a host data pipeline runs on (and "
            "blocks) device 0 per batch; keep data/ on numpy/tf and do "
            "device math inside the compiled step")


@register_checker
class ConstraintChecker(Checker):
    """Layout changes in ``parallel/`` that aren't re-anchored with a
    sharding constraint: GSPMD propagates *a* sharding through
    reshape/transpose, but not necessarily the intended one — the
    classic source of silent all-gathers at scale."""

    code = "JX108"
    name = "unconstrained-layout-change"
    description = ("reshape/transpose in parallel/ not followed by "
                   "with_sharding_constraint/guard_thin_h")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        if not path_matches_dir(mod.relpath, mod.cfg.parallel_dirs):
            return
        constraint = set(mod.cfg.constraint_funcs)
        for info in mod.functions:
            if info.parent is not None:
                continue
            # (name, lineno) of every constraint-call argument: only a
            # constraint at-or-after the layout change re-anchors it —
            # one BEFORE the reshape is exactly the hazard
            constrained: list[tuple[str, int]] = []
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call) \
                        and last_attr(call_name(node)) in constraint:
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Name):
                                constrained.append(
                                    (sub.id, node.lineno))
            for node in ast.walk(info.node):
                if not isinstance(node, ast.stmt):
                    continue
                value = getattr(node, "value", None)
                if not (isinstance(value, ast.Call)
                        and self._is_layout_call(value)):
                    continue
                if self._directly_constrained(info.node, value,
                                              constraint):
                    continue
                names = (ast.unparse(value.func) if hasattr(
                    ast, "unparse") else "call")
                targets = [n for n in self._targets(node)]
                if targets and any(
                        t == c and line >= value.lineno
                        for t in targets for c, line in constrained):
                    continue
                yield mod.finding(
                    value, self.code,
                    f"'{names}' changes layout in parallel code without "
                    "a following with_sharding_constraint/guard_thin_h; "
                    "re-anchor the sharding or GSPMD may silently "
                    "all-gather")

    @staticmethod
    def _is_layout_call(call: ast.Call) -> bool:
        la = last_attr(call_name(call))
        return la in _LAYOUT_ATTRS

    @staticmethod
    def _targets(stmt: ast.stmt) -> list[str]:
        from tools.jaxlint.core import assign_target_names

        return assign_target_names(stmt)

    @staticmethod
    def _directly_constrained(func: FunctionNode, call: ast.Call,
                              constraint: set[str]) -> bool:
        for node in ast.walk(func):
            if isinstance(node, ast.Call) \
                    and last_attr(call_name(node)) in constraint:
                for sub in ast.walk(node):
                    if sub is call:
                        return True
        return False


@register_checker
class PrefetchLoopSyncChecker(Checker):
    """Blocking host syncs inside a loop consuming a prefetched iterator
    (``device_prefetch``/``DevicePrefetcher`` — data/prefetch.py): every
    ``np.asarray``/``block_until_ready``/``jax.device_get`` in the body
    parks the host until the device drains, so the producer thread's
    queued H2D transfers stop overlapping anything and the async feed
    degrades back to the synchronous pipeline it replaced. Fetch metrics
    after the loop, or batch them through the pending/drain pattern
    (train/trainer.py).

    Interprocedural (ISSUE 10): a call to a HELPER whose body
    transitively blocks the host (the ProjectContext blocking-callable
    summary) is the same hazard routed through a function boundary and
    is flagged too, and a wrapper that *returns* a prefetcher counts as
    a prefetch factory — the ``prefetch_funcs`` knob seeds the set, the
    dataflow is the mechanism."""

    code = "JX109"
    name = "sync-in-prefetch-loop"
    description = ("blocking host sync (np.asarray / .block_until_ready "
                   "/ jax.device_get), direct or routed through a "
                   "helper call, inside a loop consuming a prefetched "
                   "iterator")

    # the blocking-call set is core.is_host_blocking_call (shared with
    # the ProjectContext blocking-callable summary so direct and
    # helper-routed syncs can never diverge); float()/`.item()` on
    # metrics is JX101's territory (traced code) — here the loop is
    # host code, and the matched calls block unconditionally rather
    # than per-element

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        # names bound to a prefetch-factory result (`feed =
        # DevicePrefetcher(...)` then `for b in feed:` — the repo idiom);
        # module-coarse name tracking is plenty for a linter
        names: set[str] = set()
        for node in ast.walk(mod.tree):
            value = getattr(node, "value", None)
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and isinstance(value, ast.Call) \
                    and mod.call_is_prefetch_factory(value):
                names.update(assign_target_names(node))
        flagged: set[int] = set()  # nested prefetch loops: report once
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            if not self._is_prefetch_iter(node.iter, mod, names):
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call) \
                            or id(sub) in flagged:
                        continue
                    name = call_name(sub)
                    # method form reaches receivers call_name can't
                    # resolve (x["loss"].block_until_ready())
                    method = (sub.func.attr
                              if isinstance(sub.func, ast.Attribute)
                              else None)
                    if is_host_blocking_call(sub):
                        flagged.add(id(sub))
                        label = name or f".{method}()"
                        yield mod.finding(
                            sub, self.code,
                            f"'{label}' blocks the host inside a "
                            "prefetched-input loop: the async feed's "
                            "queued H2D transfers stop overlapping the "
                            "step while the host waits; fetch after the "
                            "loop (or batch via the pending/drain "
                            "pattern, train/trainer.py)")
                        continue
                    # interprocedural: the sync hides inside a helper
                    helper = mod.call_blocks_host(sub)
                    if helper is not None:
                        flagged.add(id(sub))
                        yield mod.finding(
                            sub, self.code,
                            f"'{name or helper}' blocks the host inside "
                            "a prefetched-input loop (the helper "
                            f"'{helper}' transitively calls np.asarray/"
                            "block_until_ready/device_get): the async "
                            "feed's queued H2D transfers stop "
                            "overlapping the step; fetch after the loop "
                            "(pending/drain pattern, train/trainer.py)")

    @staticmethod
    def _is_prefetch_iter(expr: ast.AST, mod: ModuleContext,
                          names: set[str]) -> bool:
        """True when the loop's iterable is (or wraps, e.g. via
        ``enumerate``/``zip``) a prefetch-factory call or a name bound
        to one."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) \
                    and mod.call_is_prefetch_factory(node):
                return True
            if isinstance(node, ast.Name) and node.id in names:
                return True
        return False


@register_checker
class ServeRetraceChecker(Checker):
    """``jax.jit``/``pjit`` *called* inside a request-handling loop:
    every new input shape (or simply every fresh jit object) pays a full
    trace+compile on the request path — latency spikes of seconds where
    the steady state is milliseconds. Serving code must hit
    pre-compiled executables (``serve/compile_cache.py``: pad to a
    bucket ladder, compile once per (model, bucket) at warmup). Which
    functions count as request loops is the ``serve_funcs`` knob
    (name patterns, ``jaxlint.toml``)."""

    code = "JX110"
    name = "jit-in-request-loop"
    description = ("jax.jit/pjit called inside a request-handling loop "
                   "(per-request retrace/compile hazard)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.serve_funcs
        flagged: set[int] = set()  # nested loops: report a call once
        for info in mod.functions:
            if not any(fnmatch.fnmatch(info.node.name, p)
                       for p in patterns):
                continue
            for loop in ast.walk(info.node):
                if not isinstance(loop, (ast.For, ast.AsyncFor,
                                         ast.While)):
                    continue
                for stmt in loop.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call) \
                                or id(sub) in flagged:
                            continue
                        la = last_attr(call_name(sub))
                        if la in ("jit", "pjit"):
                            flagged.add(id(sub))
                            yield mod.finding(
                                sub, self.code,
                                f"'{call_name(sub)}' inside the "
                                f"request loop of '{info.node.name}' "
                                "traces+compiles on the request path; "
                                "hoist it out of the loop (or serve "
                                "from a warmed shape-bucketed "
                                "executable cache, serve/"
                                "compile_cache.py)")


_BROAD_EXC_NAMES = {"Exception", "BaseException"}


@register_checker
class BroadExceptStepChecker(Checker):
    """Broad ``except Exception`` / bare ``except`` around a
    compiled-step call: the checkify NaN/Inf tripwire
    (``core/step.compile_checked_train_step``) raises
    ``JaxRuntimeError`` FROM the step call — a broad handler silently
    swallows the one signal that distinguishes a numeric blow-up from a
    loggable hiccup, and the run keeps training on corrupted weights.
    Recovery code must catch ``core.step.checkify_error_cls()``
    narrowly (the Trainer's rollback does) or re-raise. Which call
    names count as compiled steps is the ``checked_step_funcs`` knob
    (``jaxlint.toml``)."""

    code = "JX111"
    name = "broad-except-around-step"
    description = ("broad 'except Exception'/bare except around a "
                   "compiled-step call (swallows the checkify NaN/Inf "
                   "tripwire)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.checked_step_funcs
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            step = self._step_call_in(node.body, patterns)
            if step is None:
                continue
            for handler in node.handlers:
                if not self._is_broad(handler.type):
                    continue
                if self._reraises(handler):
                    continue  # inspect-and-rethrow is safe
                yield mod.finding(
                    handler, self.code,
                    f"broad except around the compiled-step call "
                    f"'{call_name(step)}' swallows the checkify "
                    "NaN/Inf tripwire (JaxRuntimeError); catch "
                    "core.step.checkify_error_cls() narrowly or "
                    "re-raise")

    @staticmethod
    def _step_call_in(body, patterns) -> ast.Call | None:
        for stmt in body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                la = last_attr(call_name(sub))
                if la and any(fnmatch.fnmatch(la, p) for p in patterns):
                    return sub
        return None

    @staticmethod
    def _is_broad(exc_type: ast.AST | None) -> bool:
        """Bare ``except``, ``except Exception``/``BaseException``, or a
        tuple containing one of those."""
        if exc_type is None:
            return True
        types = (exc_type.elts if isinstance(exc_type, ast.Tuple)
                 else [exc_type])
        for t in types:
            name = last_attr(dotted_name(t))
            if name in _BROAD_EXC_NAMES:
                return True
        return False

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        """Bare ``raise``, or ``raise e`` of the handler's own bound
        name — both re-surface the caught exception unchanged."""
        for sub in ast.walk(handler):
            if not isinstance(sub, ast.Raise):
                continue
            if sub.exc is None:
                return True
            if handler.name and isinstance(sub.exc, ast.Name) \
                    and sub.exc.id == handler.name:
                return True
        return False


_TIMER_CALLS = {"time.time", "time.perf_counter", "perf_counter"}
# calls that drain the async dispatch queue (or fetch through it), so a
# clock read after one measures completed compute, not enqueue
_DISPATCH_SYNC_ATTRS = {"block_until_ready", "device_get",
                        "effects_barrier"}


@register_checker
class AsyncDispatchTimingChecker(Checker):
    """``time.time()``/``time.perf_counter()`` deltas taken around a
    compiled-step call with no ``block_until_ready()`` between call and
    stop: JAX dispatch is ASYNC — the compiled call returns the moment
    the work is enqueued, so the delta times dispatch (microseconds)
    while the chip is still computing. Such "throughput" numbers are
    lies, often by 10-100x (bench.py documents measured 8x-over-peak
    artifacts from exactly this). Which call names count as compiled
    steps is the ``timed_funcs`` knob (``jaxlint.toml``); syncs
    recognized between call and clock read: ``block_until_ready`` /
    ``jax.block_until_ready``, ``jax.device_get``,
    ``jax.effects_barrier``. Fetch-based drains a linter cannot see
    through (the Trainer's ``drain()`` float()s every pending metric)
    are what the ``[[baseline]]`` ledger is for."""

    code = "JX112"
    name = "async-dispatch-timing"
    description = ("time.time()/perf_counter() delta around a "
                   "compiled-step call without block_until_ready "
                   "between call and stop (times dispatch, not compute)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.timed_funcs
        for info in mod.functions:
            if info.parent is not None:
                continue  # nested defs scan with their parent
            yield from self._scan(mod, info.node, patterns)

    def _scan(self, mod: ModuleContext, func: FunctionNode,
              patterns) -> Iterator[Finding]:
        """Textual-order event scan of one function (nested defs
        included — closures run roughly where they're used, the same
        approximation the key-reuse scan makes)."""
        starts: list[tuple[int, str]] = []    # (line, t0 name)
        steps: list[tuple[int, str]] = []     # (line, call name)
        syncs: list[int] = []                 # lines
        deltas: list[tuple[ast.AST, int, str]] = []  # (node, line, t0)
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and call_name(node.value) in _TIMER_CALLS:
                for name in assign_target_names(node):
                    starts.append((node.lineno, name))
            if isinstance(node, ast.Call):
                cn = call_name(node)
                la = last_attr(cn)
                if la in _DISPATCH_SYNC_ATTRS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _DISPATCH_SYNC_ATTRS):
                    syncs.append(node.lineno)
                elif la and any(fnmatch.fnmatch(la, p)
                                for p in patterns):
                    steps.append((node.lineno, cn))
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Sub) \
                    and isinstance(node.left, ast.Call) \
                    and call_name(node.left) in _TIMER_CALLS \
                    and isinstance(node.right, ast.Name):
                deltas.append((node, node.lineno, node.right.id))
        for node, stop_line, t0 in deltas:
            start_line = max((ln for ln, n in starts
                              if n == t0 and ln < stop_line), default=None)
            if start_line is None:
                continue  # t0 isn't a visible timer start
            timed_steps = [(ln, cn) for ln, cn in steps
                           if start_line < ln < stop_line]
            if not timed_steps:
                continue
            last_step_line, step_name = max(timed_steps)
            if any(last_step_line < ln < stop_line for ln in syncs):
                continue  # synced between call and stop: honest timing
            yield mod.finding(
                node, self.code,
                f"clock delta over compiled-step call '{step_name}' "
                "with no block_until_ready between call and stop — "
                "async dispatch makes this time enqueue, not compute; "
                "sync the result (jax.block_until_ready) before "
                "reading the clock")


@register_checker
class LoopSleepChecker(Checker):
    """Bare ``time.sleep`` inside a supervised service loop (dispatcher
    / supervisor / router / probe / autoscaler): the sleep ignores the
    loop's stop event, so ``close()`` blocks until the full backoff
    expires — and under a long crash backoff that is SECONDS of
    shutdown hang per loop. PR 4 established the stop-responsive idiom
    (``stop_event.wait(backoff)`` sleeps identically but wakes
    instantly on close); which functions count as service loops is the
    ``loop_sleep_funcs`` knob (``jaxlint.toml``)."""

    code = "JX113"
    name = "stop-blind-sleep-in-loop"
    description = ("bare time.sleep inside a supervisor/dispatcher/"
                   "router loop (ignores the stop event; use "
                   "Event.wait(timeout))")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.loop_sleep_funcs
        flagged: set[int] = set()  # nested loops: report a call once
        for info in mod.functions:
            if not any(fnmatch.fnmatch(info.node.name, p)
                       for p in patterns):
                continue
            for loop in ast.walk(info.node):
                if not isinstance(loop, (ast.For, ast.AsyncFor,
                                         ast.While)):
                    continue
                for stmt in loop.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call) \
                                or id(sub) in flagged:
                            continue
                        name = call_name(sub)
                        bare = (isinstance(sub.func, ast.Name)
                                and sub.func.id == "sleep")
                        if name == "time.sleep" or bare:
                            flagged.add(id(sub))
                            yield mod.finding(
                                sub, self.code,
                                f"'{name or 'sleep'}' inside the "
                                f"service loop of '{info.node.name}' "
                                "ignores the stop event — close() "
                                "blocks until the sleep expires; use "
                                "the loop's stop Event.wait(timeout) "
                                "(stop-responsive backoff, PR 4 idiom)")


_WIRE_DEFAULT_NOTE = "see LintConfig.wire_funcs"


@register_checker
class F32WireChecker(Checker):
    """Host-side f32 pixel materialization feeding the device wire:
    ``x.astype(np.float32)`` (or ``np.asarray(x, np.float32)``) whose
    result flows into ``device_put``/``shard_batch``/the prefetcher
    ships 4-byte pixels over the H2D link — the exact hazard BENCH_r04
    measured as a 7x input bind (0.073 GB/s link = ~483 uint8 img/s,
    ~121 f32 img/s). The pipeline contract is: the host ships uint8
    HWC; normalization (and augmentation) runs inside the compiled
    step (``ops/normalize.maybe_normalize``, ``data/device_aug.py``).
    Which call names count as wire sinks is the ``wire_funcs`` knob
    (``jaxlint.toml``); non-image small tensors (labels, boxes) are
    cheap either way, but an f32 CAST feeding the wire is the
    tell-tale of a pipeline normalizing on the host.

    Interprocedural (ISSUE 10): a helper that RETURNS an f32 cast is a
    cast at its call sites (the ProjectContext f32-returner summary),
    and a wrapper feeding its parameter into a wire sink is a sink for
    its callers — the ``wire_funcs`` knob seeds the sink set, the
    dataflow is the mechanism."""

    code = "JX114"
    name = "f32-pixels-on-the-wire"
    description = ("host-side .astype(np.float32)/np.asarray(x, f32) "
                   "result (direct or returned by a helper) fed to "
                   "device_put/shard_batch/prefetcher (4x wire bytes; "
                   "ship uint8, normalize on device)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        for info in mod.functions:
            if info.parent is not None:
                continue  # nested defs scan with their parent
            yield from self._scan(mod, info.node)

    def _scan(self, mod: ModuleContext,
              func: FunctionNode) -> Iterator[Finding]:
        from tools.jaxlint.core import assign_target_names

        # per-name assignment history (line, came-from-an-f32-cast):
        # a name is tainted AT a use site iff its LATEST assignment
        # before that line contained a cast — a clean reassignment
        # (img = batch["image"]) clears the taint for later uses
        assigns: dict[str, list] = {}
        for node in ast.walk(func):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and getattr(node, "value", None) is not None:
                cast = mod.expr_has_f32_source(node.value)
                for name in assign_target_names(node):
                    assigns.setdefault(name, []).append(
                        (node.lineno, cast))

        def tainted_at(name: str, line: int) -> bool:
            last = None
            for lno, cast in assigns.get(name, ()):
                if lno < line and (last is None or lno > last[0]):
                    last = (lno, cast)
            return bool(last and last[1])

        flagged: set[int] = set()
        for node in ast.walk(func):
            if not isinstance(node, ast.Call) or id(node) in flagged:
                continue
            if not mod.call_is_wire_sink(node):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                direct = mod.expr_has_f32_source(arg)
                via_name = any(
                    isinstance(sub, ast.Name)
                    and tainted_at(sub.id, node.lineno)
                    for sub in ast.walk(arg))
                if direct or via_name:
                    flagged.add(id(node))
                    yield mod.finding(
                        node, self.code,
                        f"'{call_name(node)}' ships a host-side "
                        "float32 cast over the H2D wire (4 bytes/"
                        "pixel); ship uint8 and normalize on device "
                        "(ops/normalize.maybe_normalize + "
                        "data/device_aug.py)")
                    break


@register_checker
class ClusterTimeoutChecker(Checker):
    """Blocking cluster join / cross-host barrier called WITHOUT a
    timeout argument: ``jax.distributed.initialize`` with no
    ``initialization_timeout`` (the pre-ISSUE-9 ``train_dist.py``)
    hangs the launcher forever when one peer of the slice never comes
    up, and the coordination-service barriers
    (``wait_at_barrier``/``sync_global_devices``) or the repo's own
    save-barrier rendezvous (``await_all_arrived``) hang the SURVIVORS
    when a peer dies mid-protocol — the exact failure the cluster
    supervisor exists to bound. Any keyword argument matching
    ``*timeout*`` satisfies the check (``initialization_timeout``,
    ``timeout_in_ms``, ``timeout_s``, ...); which call names count is
    the ``cluster_funcs`` knob (``jaxlint.toml``), matched against both
    the dotted call name and its last attribute."""

    code = "JX115"
    name = "cluster-call-without-timeout"
    description = ("blocking cluster join/barrier (distributed."
                   "initialize, wait_at_barrier, ...) without a "
                   "timeout argument (a missing peer hangs forever)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.cluster_funcs
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            la = last_attr(cn)
            names = [n for n in (cn, la) if n]
            if not any(fnmatch.fnmatch(n, p)
                       for n in names for p in patterns):
                continue
            if any(k.arg and "timeout" in k.arg.lower()
                   for k in node.keywords):
                continue  # bounded: some *timeout* kwarg is present
            yield mod.finding(
                node, self.code,
                f"'{cn or la}' blocks on the whole cluster with no "
                "timeout argument — a missing/dead peer hangs this "
                "process forever; pass initialization_timeout/"
                "timeout_in_ms/timeout_s (supervisors must be able "
                "to degrade, resilience/cluster.py)")


_SENTINEL_FETCHERS = {"float", "int"}


@register_checker
class SentinelFetchChecker(Checker):
    """Per-step host fetch of the in-graph sentinel outputs: the
    sentinel scalars (``sent_*``, resilience/sentinel.py) are computed
    INSIDE the compiled step precisely so they can ride the existing
    pending/drain fetch cadence for free — a ``float()`` /
    ``np.asarray`` / ``jax.device_get`` / ``.item()`` of one INSIDE
    the step loop parks the host on the dispatch queue every step,
    re-introducing the JX109 stall the async feed exists to avoid (and
    the <2% sentinel overhead gate is measured WITHOUT such a sync).
    A fetch under a cadence guard (an ``if`` whose test uses ``%`` —
    the ``i % k == 0`` drain idiom) is the sanctioned exception. Which
    functions count as sentinel-consuming step loops is the
    ``sentinel_funcs`` knob (``jaxlint.toml``)."""

    code = "JX116"
    name = "per-step-sentinel-fetch"
    description = ("float()/np.asarray/device_get/.item() of a sent_* "
                   "sentinel output inside a step loop, outside the "
                   "drain cadence (re-introduces the JX109 host-sync "
                   "stall)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.sentinel_funcs
        step_patterns = mod.cfg.checked_step_funcs
        flagged: set[int] = set()  # nested loops: report a call once
        for info in mod.functions:
            if not any(fnmatch.fnmatch(info.node.name, p)
                       for p in patterns):
                continue
            for loop in ast.walk(info.node):
                if not isinstance(loop, (ast.For, ast.AsyncFor,
                                         ast.While)):
                    continue
                if not self._has_step_call(loop, step_patterns):
                    continue
                guarded = self._cadence_guarded_ids(loop)
                for sub in self._direct_body_nodes(loop):
                    if not isinstance(sub, ast.Call) \
                            or id(sub) in flagged \
                            or id(sub) in guarded:
                        continue
                    if not self._is_fetch(sub):
                        continue
                    if not self._touches_sentinel(sub):
                        continue
                    flagged.add(id(sub))
                    yield mod.finding(
                        sub, self.code,
                        f"'{call_name(sub) or '.item()'}' fetches "
                        "a sent_* sentinel output on EVERY step "
                        "of the loop in "
                        f"'{info.node.name}' — a per-step host "
                        "sync (JX109's stall) the in-graph "
                        "sentinels exist to avoid; batch it "
                        "through the pending/drain pattern or "
                        "guard it with the drain cadence "
                        "(`if i % k == 0:`)")

    @staticmethod
    def _direct_body_nodes(loop):
        """Nodes of ``loop``'s body WITHOUT descending into nested
        loops: a nested loop is its own iteration scope and gets its
        own visit (a fetch sitting after an inner step loop runs once
        per OUTER iteration — the sanctioned batch point, not a
        per-step sync)."""
        stack = list(loop.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue  # the nested loop's body is its own scope
            stack.extend(ast.iter_child_nodes(node))

    @classmethod
    def _has_step_call(cls, loop, step_patterns) -> bool:
        """A compiled-step call DIRECTLY in this loop's body (a step
        call only inside a nested loop makes the NESTED loop the
        per-step scope, not this one)."""
        for sub in cls._direct_body_nodes(loop):
            if isinstance(sub, ast.Call):
                la = last_attr(call_name(sub))
                if la and any(fnmatch.fnmatch(la, p)
                              for p in step_patterns):
                    return True
        return False

    @staticmethod
    def _cadence_guarded_ids(loop) -> set[int]:
        """ids of calls under an ``if`` whose test contains ``%`` —
        the ``i % cadence == 0`` drain-cadence idiom."""
        guarded: set[int] = set()
        for stmt in ast.walk(loop):
            if not isinstance(stmt, ast.If):
                continue
            has_mod = any(isinstance(op, ast.BinOp)
                          and isinstance(op.op, ast.Mod)
                          for op in ast.walk(stmt.test))
            if not has_mod:
                continue
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    guarded.add(id(sub))
        return guarded

    @staticmethod
    def _is_fetch(call: ast.Call) -> bool:
        name = call_name(call)
        if isinstance(call.func, ast.Name) \
                and call.func.id in _SENTINEL_FETCHERS:
            return True
        if is_host_blocking_call(call):
            return True
        return bool(name) and last_attr(name) in ("item", "device_get")

    @staticmethod
    def _touches_sentinel(call: ast.Call) -> bool:
        """The fetched expression names a sentinel output — the
        ``sent_*`` naming contract, in a subscript key, attribute, or
        variable name."""
        targets = list(call.args) + [k.value for k in call.keywords]
        if isinstance(call.func, ast.Attribute):  # x["sent_y"].item()
            targets.append(call.func.value)
        for arg in targets:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str) \
                        and sub.value.startswith("sent_"):
                    return True
                if isinstance(sub, ast.Name) \
                        and sub.id.startswith("sent_"):
                    return True
                if isinstance(sub, ast.Attribute) \
                        and sub.attr.startswith("sent_"):
                    return True
        return False


@register_checker
class SpanSyncChecker(Checker):
    """``with span(...)`` wrapping a compiled-step call with no device
    sync before the span ends: the JX112 async-dispatch lie, now for
    spans. A compiled call returns the moment the work is ENQUEUED, so
    a span closed right after it measures dispatch (microseconds), not
    compute — and a trace whose ``step`` spans are all 50us while the
    chip grinds for 20ms misattributes the epoch to whatever span the
    drain happens to land in. Honest forms the checker recognizes:
    ``span(..., device_sync=out)`` at construction, ``sp.device_sync(
    out)`` on the as-name, or ``block_until_ready`` / ``jax.device_get``
    / ``jax.effects_barrier`` between the LAST step call and the span's
    end. Which call names count as compiled steps is the ``span_funcs``
    knob (``jaxlint.toml``). Loop spans that deliberately measure
    dispatch+backpressure (the Trainer's ``step`` span — syncing would
    serialize the async feed) carry an inline pragma with the
    rationale."""

    code = "JX117"
    name = "unsynced-span-over-step"
    description = ("`with span(...)` over a compiled-step call with no "
                   "device_sync/block_until_ready before span end "
                   "(the span times async dispatch, not compute)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.span_funcs
        for info in mod.functions:
            if info.parent is not None:
                continue  # nested defs scan with their parent
            for node in ast.walk(info.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    yield from self._check_with(mod, node, patterns)

    def _check_with(self, mod: ModuleContext, node,
                    patterns) -> Iterator[Finding]:
        span_call = self._span_item(node)
        if span_call is None:
            return
        if any(k.arg == "device_sync"
               and not (isinstance(k.value, ast.Constant)
                        and k.value.value is None)
               for k in span_call.keywords):
            return  # ctor-form sync: the span end blocks on the value
        steps: list[tuple[int, str]] = []
        syncs: list[int] = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) or sub is span_call:
                continue
            cn = call_name(sub)
            la = last_attr(cn)
            if la in _DISPATCH_SYNC_ATTRS or la == "device_sync" or (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _DISPATCH_SYNC_ATTRS):
                syncs.append(sub.lineno)
            elif la and any(fnmatch.fnmatch(la, p) for p in patterns):
                steps.append((sub.lineno, cn))
        if not steps:
            return
        last_step_line, step_name = max(steps)
        if any(ln >= last_step_line for ln in syncs):
            return  # synced after (or beside) the last step call
        yield mod.finding(
            node, self.code,
            f"span over compiled-step call '{step_name}' closes with "
            "no device sync — async dispatch makes it time enqueue, "
            "not compute; use `sp.device_sync(out)` (or span(..., "
            "device_sync=...)) so the end stamp waits for the result")

    @staticmethod
    def _span_item(node) -> ast.Call | None:
        """The ``span(...)``/``tracer.span(...)`` call of a With item,
        if any."""
        for item in node.items:
            ctx = item.context_expr
            if not isinstance(ctx, ast.Call):
                continue
            if last_attr(call_name(ctx)) == "span":
                return ctx
            # call-on-call receivers (get_tracer().span(...)) have no
            # resolvable dotted name; the attribute still names it
            if isinstance(ctx.func, ast.Attribute) \
                    and ctx.func.attr == "span":
                return ctx
        return None


_F32_LITERALS = {"jnp.float32", "np.float32", "numpy.float32",
                 "jax.numpy.float32"}
_ARRAY_CREATORS = {"zeros", "ones", "full", "empty", "array", "asarray",
                   "arange", "zeros_like", "ones_like", "full_like",
                   "linspace"}


def _is_f32_literal(node) -> bool:
    """``jnp.float32`` / ``np.float32`` / the string ``"float32"`` —
    the raw-literal forms that bypass the policy object (a dtype read
    off ``self.dtype`` / ``promote_types(...)`` is policy-derived and
    passes)."""
    if isinstance(node, ast.Constant):
        return node.value == "float32"
    name = dotted_name(node)
    return name in _F32_LITERALS


@register_checker
class PrecisionPolicyChecker(Checker):
    """Raw f32 introduced inside model ``__call__``/loss bodies: the
    regression path by which the ISSUE 15 HBM diet silently erodes.
    One ``x.astype(jnp.float32)`` (or an f32-literal array creation)
    in a hot body re-materializes a full-size f32 activation on every
    step — invisible to tests (numerics only improve) and to the
    cost-analysis ledger on backends that float-normalize anyway.

    The numerics policy lives in ``core/precision.py`` and the module
    ``dtype`` convention: compute-dtype reads come off ``self.dtype``,
    precision FLOORS off ``jnp.promote_types(d, jnp.float32)``, f32
    statistics inside ``layers.MixedBatchNorm``. Those idioms pass (the
    dtype is policy-derived, not a literal); raw literals are flagged
    and must either adopt the idiom or record a reasoned baseline
    (deliberate f32 reduce floors, e.g. loss accumulation). Which
    function names count as hot bodies is the ``precision_funcs``
    knob."""

    code = "JX123"
    name = "policy-bypass-f32"
    description = ("raw jnp.float32 cast / f32-literal array creation "
                   "inside a model __call__/loss body bypassing the "
                   "numerics policy (core/precision.py)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        if path_matches_dir(mod.relpath, mod.cfg.data_dirs):
            return  # host pipelines: f32 there is JX114's (wire) beat
        patterns = mod.cfg.precision_funcs
        for info in mod.functions:
            if not any(fnmatch.fnmatch(info.node.name, p)
                       for p in patterns):
                continue
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" \
                        and node.args \
                        and _is_f32_literal(node.args[0]):
                    yield mod.finding(
                        node, self.code,
                        "raw '.astype(float32)' inside "
                        f"'{info.node.name}' bypasses the numerics "
                        "policy — use the module's compute dtype "
                        "(self.dtype) or a promote_types precision "
                        "floor, or record a reasoned baseline for a "
                        "deliberate f32 reduction")
                    continue
                name = call_name(node)
                if last_attr(name) not in _ARRAY_CREATORS:
                    continue
                dtype_args = [kw.value for kw in node.keywords
                              if kw.arg == "dtype"]
                # creators take dtype as the 2nd positional too
                if len(node.args) >= 2:
                    dtype_args.append(node.args[1])
                if any(_is_f32_literal(a) for a in dtype_args):
                    yield mod.finding(
                        node, self.code,
                        f"'{name}' creates an f32-literal array inside "
                        f"'{info.node.name}' — full-size f32 "
                        "intermediates are the diet's regression "
                        "path; derive the dtype from the policy "
                        "(self.dtype / promote_types) or baseline the "
                        "deliberate f32 floor with a reason")


# ----------------------------------------------- SPMD tier (JX124-JX126)
# Source-level companions of the compiled-IR SPMD gate
# (tools/jaxlint/shardcheck.py): shardcheck proves properties of the
# lowered program; these keep the SOURCE from growing the idioms that
# make those proofs fragile (scattered axis names, un-sharded
# transfers, inline PartitionSpecs outside the rules table).


_SPEC_CTORS = {"PartitionSpec", "P"}
_MESH_CTORS = {"Mesh", "make_mesh", "create_mesh"}
# collectives whose first argument / axis kwarg names a mesh axis
_AXIS_ARG_CALLS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pswapaxes", "axis_index", "axis_size", "psum_scatter",
}
_AXIS_KWARGS = {"axis_name", "axis_names", "axis", "spatial_axis",
                "data_axis", "model_axis"}


def _axis_literals_in(node: ast.AST, names: set[str]
                      ) -> Iterator[ast.Constant]:
    """String constants (tuples/lists included) whose value is a
    declared mesh axis name."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and sub.value in names:
            yield sub


@register_checker
class MeshAxisLiteralChecker(Checker):
    """Hardcoded mesh axis names outside the mesh's definition site.
    ``core/mesh.py`` owns ``AXIS_DATA``/``AXIS_MODEL``; a string
    ``"data"`` baked into a PartitionSpec, a ``mesh.shape[...]`` lookup
    or a collective's ``axis_name`` elsewhere means renaming or
    reshaping the mesh (the exact move ROADMAP item 1 makes) is a
    repo-wide grep instead of a one-file change — and shardcheck's
    rules table can silently diverge from what the code spells. Only
    sharding-shaped contexts are scanned, so ``"model"`` as a dict key
    or log field stays legal."""

    code = "JX124"
    name = "hardcoded-mesh-axis"
    description = ("mesh axis name spelled as a string literal outside "
                   "core/mesh.py (use AXIS_DATA/AXIS_MODEL)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        cfg = mod.cfg
        if any(fnmatch.fnmatch(mod.relpath, p)
               for p in cfg.mesh_axis_home):
            return
        names = set(cfg.mesh_axis_names)
        if not names:
            return
        seen: set[int] = set()

        def hit(const: ast.Constant, ctx: str) -> Iterator[Finding]:
            if id(const) in seen:
                return
            seen.add(id(const))
            yield mod.finding(
                const, self.code,
                f"mesh axis name '{const.value}' hardcoded in {ctx} — "
                "import AXIS_DATA/AXIS_MODEL from core.mesh so the "
                "mesh stays a one-file change")

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fn = last_attr(call_name(node))
                if fn in _SPEC_CTORS | _MESH_CTORS:
                    for arg in list(node.args) + [
                            k.value for k in node.keywords]:
                        for c in _axis_literals_in(arg, names):
                            yield from hit(c, f"a {fn}(...) argument")
                elif fn in _AXIS_ARG_CALLS:
                    args = list(node.args[1:2]) + [
                        k.value for k in node.keywords
                        if k.arg in _AXIS_KWARGS]
                    for arg in args:
                        for c in _axis_literals_in(arg, names):
                            yield from hit(c, f"the axis of {fn}(...)")
                else:
                    for k in node.keywords:
                        if k.arg in _AXIS_KWARGS:
                            for c in _axis_literals_in(k.value, names):
                                yield from hit(
                                    c, f"keyword {k.arg}= of {fn}(...)")
                # mesh.shape.get("data", 1)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "get" \
                        and isinstance(node.func.value, ast.Attribute) \
                        and node.func.value.attr == "shape" \
                        and node.args:
                    for c in _axis_literals_in(node.args[0], names):
                        yield from hit(c, "a mesh.shape lookup")
            elif isinstance(node, ast.Subscript):
                # mesh.shape["data"]
                if isinstance(node.value, ast.Attribute) \
                        and node.value.attr == "shape":
                    for c in _axis_literals_in(node.slice, names):
                        yield from hit(c, "a mesh.shape lookup")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # def f(..., spatial_axis: str = "model")
                a = node.args
                pairs = list(zip(
                    (a.posonlyargs + a.args)[::-1], a.defaults[::-1]))
                pairs += [(kw, d) for kw, d in
                          zip(a.kwonlyargs, a.kw_defaults)
                          if d is not None]
                for arg, default in pairs:
                    if "axis" not in arg.arg:
                        continue
                    for c in _axis_literals_in(default, names):
                        yield from hit(
                            c, f"the default of parameter {arg.arg!r}")


@register_checker
class UnshardedTransferChecker(Checker):
    """A bare single-argument ``jax.device_put(x)`` on a multi-device
    code path: with no sharding/device operand the transfer lands fully
    replicated on the default device — on a 2+-device mesh that
    silently gathers a sharded array (one blocking cross-device copy
    per step) or parks state off-mesh where the next compiled step
    reshards it back (the implicit-transfer class shardcheck's detector
    flags in the IR). Every transfer on a sharded path must name its
    sharding, or go through ``core.mesh.shard_batch`` which applies
    one. Which directories count as multi-device paths is the
    ``multidevice_dirs`` knob."""

    code = "JX125"
    name = "unsharded-device-put"
    description = ("single-argument device_put on a multi-device path "
                   "(no sharding: replicates onto the default device)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        if not path_matches_dir(mod.relpath, mod.cfg.multidevice_dirs):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if last_attr(call_name(node)) != "device_put":
                continue
            if len(node.args) >= 2 or any(
                    k.arg in ("device", "sharding", "dst_sharding")
                    for k in node.keywords):
                continue
            yield mod.finding(
                node, self.code,
                "device_put without a sharding on a multi-device path "
                "— the array replicates onto the default device; pass "
                "the NamedSharding (or use shard_batch) so the "
                "placement survives mesh growth")


@register_checker
class InlinePartitionSpecChecker(Checker):
    """Literal ``PartitionSpec``/``P`` construction in model or step
    code. Sharding decisions live in the declarative
    ``[[shardcheck.rule]]`` table (jaxlint.toml) that shardcheck audits
    for coverage and ROADMAP item 1's engine consumes; a spec built
    inline in ``models/``/``train/`` is invisible to both — it can't be
    coverage-checked, can't be retuned per mesh, and is exactly how a
    hand-sharded layer drifts from the rest of the model. The sharding
    plumbing itself (``core/``, ``parallel/``) is the legitimate
    interpreter of specs and stays exempt."""

    code = "JX126"
    name = "inline-partition-spec"
    description = ("literal PartitionSpec in model/step code instead "
                   "of the [[shardcheck.rule]] table")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        if not path_matches_dir(mod.relpath,
                                mod.cfg.partition_rule_dirs):
            return
        # only flag files that actually bind the constructor to a
        # PartitionSpec import — a local helper named P() elsewhere in
        # train/ is not a sharding spec
        bound: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "PartitionSpec":
                        bound.add(alias.asname or alias.name)
        if not bound:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and last_attr(call_name(node)) in bound:
                yield mod.finding(
                    node, self.code,
                    "PartitionSpec constructed inline in model/step "
                    "code — declare the sharding as a "
                    "[[shardcheck.rule]] row (regex path -> spec) so "
                    "the coverage audit and the sharding engine see it")


@register_checker
class PipelineHostRoundTripChecker(Checker):
    """Host fetch of an inter-stage value inside a pipeline execution
    path: the served DAG (``serve/pipeline.py``) exists to keep stage
    outputs device-resident between compiled stages — a ``jax.device_get``
    / ``np.asarray`` / ``.block_until_ready()`` there re-introduces the
    per-hop host round-trip (plus the dispatch-pipeline stall) the
    subsystem removes, and it does so silently: results stay correct,
    only the latency contract breaks. The engine's single final fetch
    after the whole DAG is the one sanctioned ``device_get``. Which
    functions count as pipeline execution paths is the
    ``pipeline_funcs`` knob (name patterns, ``jaxlint.toml``);
    helper-routed syncs are flagged through the project blocking-
    callable summary, same as JX109."""

    code = "JX127"
    name = "host-round-trip-in-pipeline"
    description = ("jax.device_get / np.asarray / .block_until_ready() "
                   "on an inter-stage value inside a pipeline execution "
                   "path (re-introduces the host hop the DAG removes)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.pipeline_funcs
        for info in mod.functions:
            if not any(fnmatch.fnmatch(info.node.name, p)
                       for p in patterns):
                continue
            # own body only: a nested def is its own FunctionInfo and
            # is matched (or not) on its own name
            for sub in iter_own_nodes(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                method = (sub.func.attr
                          if isinstance(sub.func, ast.Attribute)
                          else None)
                if is_host_blocking_call(sub):
                    label = name or f".{method}()"
                    yield mod.finding(
                        sub, self.code,
                        f"'{label}' fetches/syncs an inter-stage value "
                        f"inside pipeline path '{info.node.name}': "
                        "stage outputs must stay device-resident until "
                        "the engine's single final fetch — drop the "
                        "host hop (decode belongs in postprocess, "
                        "after device_get)")
                    continue
                helper = mod.call_blocks_host(sub)
                if helper is not None:
                    yield mod.finding(
                        sub, self.code,
                        f"'{name or helper}' blocks the host inside "
                        f"pipeline path '{info.node.name}' (the helper "
                        f"'{helper}' transitively calls np.asarray/"
                        "block_until_ready/device_get): inter-stage "
                        "values must stay device-resident until the "
                        "engine's final fetch")


@register_checker
class SessionHostRoundTripChecker(Checker):
    """Per-frame host round-trip on session state inside a
    stream-handling loop: stateful serving (``serve/sessions.py``) pins
    each stream's tracking slate on device between frames — the entire
    point of the subsystem — and the engine's stateful batch path
    performs exactly ONE ``device_get`` per executed batch. A
    ``jax.device_get`` / ``np.asarray`` / ``.item()`` inside the
    per-frame loop re-materializes the slate on the host every frame,
    turning the device-resident design back into the
    fetch-per-frame pipeline it replaced — results stay correct, only
    the latency contract breaks, so nothing else catches it. Which
    functions count as stream-handling loops is the ``session_funcs``
    knob (name patterns, ``jaxlint.toml``); helper-routed syncs are
    flagged through the project blocking-callable summary, same as
    JX109/JX127. Snapshotting is exempt by scoping: the store's
    snapshot path is cadence-driven host I/O, not a per-frame loop."""

    code = "JX128"
    name = "host-round-trip-in-stream-loop"
    description = ("jax.device_get / np.asarray / .item(), direct or "
                   "helper-routed, inside the per-frame loop of a "
                   "stream-handling function (re-materializes "
                   "device-resident session state every frame)")

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.session_funcs
        for info in mod.functions:
            if not any(fnmatch.fnmatch(info.node.name, p)
                       for p in patterns):
                continue
            # own body only: a nested def is its own FunctionInfo and
            # is matched (or not) on its own name
            own = {id(n): n for n in iter_own_nodes(info.node)}
            flagged: set[int] = set()  # nested loops: report once
            for loop in own.values():
                if not isinstance(loop,
                                  (ast.For, ast.AsyncFor, ast.While)):
                    continue
                for stmt in loop.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call) \
                                or id(sub) not in own \
                                or id(sub) in flagged:
                            continue
                        name = call_name(sub)
                        method = (sub.func.attr
                                  if isinstance(sub.func, ast.Attribute)
                                  else None)
                        if is_host_blocking_call(sub) \
                                or method == "item":
                            flagged.add(id(sub))
                            label = name or f".{method}()"
                            yield mod.finding(
                                sub, self.code,
                                f"'{label}' fetches session state to "
                                "the host inside the per-frame loop of "
                                f"'{info.node.name}': stream state must "
                                "stay device-resident between frames — "
                                "the engine's stateful batch path does "
                                "ONE device_get per batch; move the "
                                "fetch out of the loop (or to the "
                                "snapshot cadence)")
                            continue
                        helper = mod.call_blocks_host(sub)
                        if helper is not None:
                            flagged.add(id(sub))
                            yield mod.finding(
                                sub, self.code,
                                f"'{name or helper}' blocks the host "
                                "inside the per-frame loop of "
                                f"'{info.node.name}' (the helper "
                                f"'{helper}' transitively calls "
                                "np.asarray/block_until_ready/"
                                "device_get): per-frame host round-"
                                "trips re-introduce the fetch-per-frame "
                                "pipeline the session store removes")


@register_checker
class WeightUploadInRequestLoopChecker(Checker):
    """Per-request ``jax.device_put`` of a weight pytree inside a
    dispatch/request loop: multi-tenant residency (``serve/tenancy.py``)
    stages each tenant's weights onto the device ONCE — adopt /
    ensure_resident / rematerialize, amortized behind the LRU budget —
    and every dispatch after that reads the resident edition.
    Re-uploading ``variables``/``weights``/``params`` per request
    re-introduces the full checkpoint transfer (HBM churn + PCIe
    stall) on the hot path the residency manager exists to protect;
    results stay correct, only the cost model breaks, so nothing else
    catches it. Functions whose NAME matches the ``residency_funcs``
    knob (``jaxlint.toml``) are the sanctioned staging paths and are
    exempt; everything else that loops over requests and device_puts a
    weights-named pytree is flagged."""

    code = "JX129"
    name = "weight-upload-in-request-loop"
    description = ("jax.device_put of a weights/params/variables pytree "
                   "inside a dispatch/request loop outside a residency "
                   "manager (re-uploads the checkpoint per request)")

    WEIGHT_NAMES = {"variables", "weights", "params"}

    @classmethod
    def _weighty(cls, node: ast.AST) -> str | None:
        """Dotted-name tail of ``node`` if it names a weight pytree."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
        if not parts:
            return None
        tail = parts[0]  # last dotted segment (e.g. self.model.params)
        if tail in cls.WEIGHT_NAMES:
            return tail
        for suffix in cls.WEIGHT_NAMES:
            if tail.endswith("_" + suffix):
                return tail
        return None

    def check(self, mod: ModuleContext) -> Iterator[Finding]:
        patterns = mod.cfg.residency_funcs
        for info in mod.functions:
            if any(fnmatch.fnmatch(info.node.name, p)
                   for p in patterns):
                continue  # sanctioned staging path
            # own body only: a nested def is its own FunctionInfo and
            # is matched (or not) on its own name
            own = {id(n): n for n in iter_own_nodes(info.node)}
            flagged: set[int] = set()  # nested loops: report once
            for loop in own.values():
                if not isinstance(loop,
                                  (ast.For, ast.AsyncFor, ast.While)):
                    continue
                for stmt in loop.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call) \
                                or id(sub) not in own \
                                or id(sub) in flagged \
                                or not sub.args:
                            continue
                        if last_attr(call_name(sub)) != "device_put":
                            continue
                        tail = self._weighty(sub.args[0])
                        if tail is None:
                            continue
                        flagged.add(id(sub))
                        yield mod.finding(
                            sub, self.code,
                            f"'jax.device_put({tail}, ...)' inside the "
                            f"request loop of '{info.node.name}' "
                            "re-uploads the weight pytree per request: "
                            "weights are staged ONCE by the residency "
                            "manager (TenancyManager.adopt / "
                            "ensure_resident) and dispatch reads the "
                            "resident edition — hoist the transfer out "
                            "of the loop or route it through a "
                            "residency_funcs-matched staging path")


# concurrency tier (JX118-JX122, ISSUE 14): importing for registration
# side effects keeps every "import checkers" site (run_paths, the CLI)
# seeing the full checker set
import tools.jaxlint.concurrency  # noqa: E402,F401  (registration)
