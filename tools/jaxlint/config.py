"""jaxlint configuration: ``jaxlint.toml`` loading + the LintConfig model.

The TOML-subset reader lives in ``deepvision_tpu/minitoml.py`` (shared
with the runtime sharding engine, which consumes the same
``[[shardcheck.rule]]`` table — one reader, one dialect); this module
re-exports it and carries the config dataclasses + loaders."""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass, field
from pathlib import Path

# Re-exported names (loads_toml / TomlError were defined here before the
# sharding engine moved the reader into the library): existing importers
# (core.py, tests) keep working unchanged.
from deepvision_tpu.minitoml import TomlError, loads_toml  # noqa: F401


# ------------------------------------------------------------- LintConfig


@dataclass
class BaselineEntry:
    """A recorded, justified exception: findings matching (path, code[,
    match-substring]) are suppressed. ``reason`` is mandatory by
    convention so the debt ledger stays reviewable."""

    path: str
    code: str
    reason: str = ""
    match: str = ""
    hits: int = 0  # filled by the engine; stale entries are warned about

    def matches(self, path: str, code: str, text: str) -> bool:
        return (
            self.path == path
            and fnmatch.fnmatch(code, self.code)
            and (not self.match or self.match in text)
        )


@dataclass
class LintConfig:
    """Knobs for the checkers; defaults encode this repo's layout and are
    overridable from ``jaxlint.toml`` (``[jaxlint]`` table)."""

    # Directories whose every function is traced-by-construction (the
    # README contract: models/ops/losses are pure jit-able code).
    traced_dirs: list[str] = field(default_factory=lambda: [
        "deepvision_tpu/models", "deepvision_tpu/ops",
        "deepvision_tpu/losses",
    ])
    # Host-side data pipelines: jnp compute is a hazard here (JX107).
    data_dirs: list[str] = field(default_factory=lambda: [
        "deepvision_tpu/data",
    ])
    # Sharding-sensitive layout code: reshape/transpose must be followed
    # by a sharding constraint (JX108).
    parallel_dirs: list[str] = field(default_factory=lambda: [
        "deepvision_tpu/parallel",
    ])
    # Function-name patterns treated as traced even outside traced_dirs
    # (the step-function naming contract of train/steps.py, train/gan.py).
    traced_name_patterns: list[str] = field(default_factory=lambda: [
        "*_train_step", "*_eval_step", "*_loss_fn", "loss_fn",
        "*_step_fn",
    ])
    # Callables that trace their function argument: a function passed to
    # (or decorated by) one of these is traced, and its same-module
    # callees transitively so.
    jit_wrappers: list[str] = field(default_factory=lambda: [
        "jit", "pjit", "eval_shape", "grad", "value_and_grad", "vmap",
        "pmap", "shard_map", "checkify", "scan", "cond", "while_loop",
        "fori_loop", "switch", "remat", "checkpoint", "custom_vjp",
        "custom_jvp", "compile_train_step", "compile_eval_step",
        "compile_checked_train_step",
    ])
    # jax/lax calls that return *static* Python values — safe in Python
    # control flow, never a taint source (JX101/JX102).
    static_return_calls: list[str] = field(default_factory=lambda: [
        "axis_size", "process_index", "process_count", "device_count",
        "local_device_count", "default_backend", "devices",
        "local_devices",
    ])
    # jax.random.* that mint fresh keys rather than consuming entropy.
    key_fresheners: list[str] = field(default_factory=lambda: [
        "split", "fold_in", "key", "PRNGKey", "key_data",
        "wrap_key_data", "clone",
    ])
    # Parameter-name patterns tracked as PRNG keys (JX103); names
    # assigned from split()/fold_in()/next(KeySeq) are tracked regardless.
    key_name_patterns: list[str] = field(default_factory=lambda: [
        "key", "rng", "*_key", "*_rng", "key_*", "rng_*", "seed_key",
    ])
    # Blessed sharding-constraint sinks for JX108.
    constraint_funcs: list[str] = field(default_factory=lambda: [
        "with_sharding_constraint", "guard_thin_h",
    ])
    # Iterator factories whose consuming loops are overlapped-H2D hot
    # loops (JX109): a blocking host sync inside one stalls the async
    # feed — the queued transfers drain while the host waits.
    prefetch_funcs: list[str] = field(default_factory=lambda: [
        "device_prefetch", "DevicePrefetcher", "prefetch_to_device",
    ])
    # Function-name patterns treated as request-handling loops (JX110):
    # a jax.jit/pjit call inside a loop there traces+compiles on the
    # request path instead of hitting a warmed executable cache.
    serve_funcs: list[str] = field(default_factory=lambda: [
        "*serve*", "*dispatch*", "*handle*", "*request_loop*",
    ])
    # Call-name patterns treated as compiled-step invocations (JX111):
    # a broad `except Exception`/bare `except` around one swallows the
    # checkify NaN/Inf tripwire (core/step.compile_checked_train_step)
    # along with real device failures — recovery code must catch
    # `core.step.checkify_error_cls()` narrowly instead.
    checked_step_funcs: list[str] = field(default_factory=lambda: [
        "*_train_step", "*_eval_step", "*_step_fn", "train_step",
        "eval_step",
    ])
    # Call-name patterns treated as compiled-step invocations for the
    # async-dispatch timing check (JX112): a time.time()/perf_counter()
    # delta spanning one of these without a block_until_ready between
    # call and stop times ENQUEUE, not compute — the classic 10-100x
    # throughput lie on an async backend.
    timed_funcs: list[str] = field(default_factory=lambda: [
        "*_train_step", "*_eval_step", "*_step_fn", "train_step",
        "eval_step",
    ])
    # Function-name patterns treated as supervised service loops
    # (JX113): a bare time.sleep inside a loop there ignores the stop
    # event, so shutdown blocks until the sleep expires — PR 4's
    # stop-responsive idiom is Event.wait(backoff), which sleeps the
    # same but wakes instantly on close().
    loop_sleep_funcs: list[str] = field(default_factory=lambda: [
        "*supervise*", "*dispatch*", "*router*", "*probe*",
        "*autoscale*", "*respawn*", "*_loop*", "*watchdog*",
    ])
    # Call names treated as host->device wire sinks (JX114): a host
    # f32 cast feeding one of these ships 4-byte pixels over the H2D
    # link — the input-wall hazard ISSUE 7 removed (uint8 wire +
    # on-device normalize, ops/normalize.py + data/device_aug.py).
    wire_funcs: list[str] = field(default_factory=lambda: [
        "device_put", "shard_batch", "shard_by_process",
        "DevicePrefetcher", "device_prefetch",
        "make_array_from_process_local_data",
    ])
    # Blocking cluster joins / cross-host barriers (JX115): calling one
    # without a timeout argument hangs the launcher/supervisor forever
    # on a missing peer — jax.distributed.initialize takes
    # initialization_timeout, the coordination-service barriers take
    # timeout_in_ms, and the repo's own save-barrier rendezvous takes
    # timeout_s. Matched against the dotted call name AND its last
    # attribute; any keyword matching ``*timeout*`` satisfies the check.
    cluster_funcs: list[str] = field(default_factory=lambda: [
        "*distributed.initialize", "*wait_at_barrier*",
        "*sync_global_devices*", "*await_all_arrived*",
        "*blocking_key_value_get*",
    ])
    # Function-name patterns treated as numerics-policy hot bodies
    # (JX123): a raw jnp.float32 cast / f32-literal array creation
    # inside one bypasses the mixed-precision policy
    # (core/precision.py) — the regression path the HBM diet erodes
    # by. Policy-derived dtypes (self.dtype, promote_types floors)
    # pass; deliberate f32 reduce floors get reasoned baselines.
    precision_funcs: list[str] = field(default_factory=lambda: [
        "__call__", "loss_fn", "*_loss_fn", "*_loss",
    ])
    # Function-name patterns treated as sentinel-consuming step loops
    # (JX116): a per-step float()/np.asarray()/device_get of the
    # in-graph sentinel outputs (the `sent_*` naming contract of
    # resilience/sentinel.py) re-introduces the JX109 host-sync stall
    # the pending/drain pattern exists to avoid — sentinel fetches
    # must ride the drain cadence (an `i % k` guarded block) instead.
    sentinel_funcs: list[str] = field(default_factory=lambda: [
        "*epoch*", "*fit*", "*train_loop*", "*step_loop*",
    ])
    # Call-name patterns treated as compiled-step invocations for the
    # span-timing check (JX117): a `with span(...)` wrapping one with
    # no device_sync/block_until_ready before the span end records the
    # JX112 async-dispatch lie into the trace — the span times enqueue,
    # not compute. Same default step-call naming as JX111/JX112.
    span_funcs: list[str] = field(default_factory=lambda: [
        "*_train_step", "*_eval_step", "*_step_fn", "train_step",
        "eval_step",
    ])
    # -- concurrency tier (JX118-JX122, tools/jaxlint/concurrency.py) --
    # Name patterns (matched case-insensitively against the FINAL
    # attribute/name segment) treated as mutex objects: `with self._lock:`
    # scopes, `.acquire()` receivers, and the instance lock JX118 expects
    # shared state to hide behind.
    lock_name_patterns: list[str] = field(default_factory=lambda: [
        "*lock*", "*mutex*", "*_mu",
    ])
    # Call-name patterns treated as host-BLOCKING while a lock is held
    # (JX119): HTTP round-trips, subprocess waits, file I/O, sleeps.
    # Structural rules ride along in the checker: zero-arg `.get()` /
    # `.join()` / `.wait()` are unbounded queue/thread/event blocks
    # (a timeout argument bounds them; `str.join(iterable)` has an
    # argument and is skipped), and resolved calls to helpers that
    # TRANSITIVELY block are flagged through the project call graph.
    lock_blocking_calls: list[str] = field(default_factory=lambda: [
        "urlopen", "*.urlopen", "requests.get", "requests.post",
        "requests.put", "requests.request", "subprocess.run",
        "subprocess.check_output", "subprocess.check_call",
        "subprocess.call", "*.communicate", "*.getresponse",
        "*.recv", "*.accept", "*.connect", "open", "*.read_text",
        "*.write_text", "*.read_bytes", "*.write_bytes", "*.flush",
        "time.sleep",
    ])
    # Cross-host collective/barrier calls (JX120's flock-across-
    # collective rule): holding ANY lock across one of these deadlocks
    # the fleet the moment a peer blocked at the barrier needs the same
    # lock — the PR 8 hazard (the Trainer's cluster save is lock-free
    # for exactly this reason).
    collective_calls: list[str] = field(default_factory=lambda: [
        "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
        "pswapaxes", "wait_at_barrier", "sync_global_devices",
        "await_all_arrived",
    ])
    # Import roots that make fork-based multiprocessing unsafe (JX121):
    # once jax/tf runtime threads + locks exist, a forked child
    # inherits locked mutexes with no owner thread and wedges on first
    # use — the PR 2 tier-1 deadlock. Modules reaching these imports
    # (directly or through the project import graph) must create
    # Pool/Process/Queue through an explicit spawn context.
    fork_unsafe_imports: list[str] = field(default_factory=lambda: [
        "jax", "tensorflow",
    ])
    # -- SPMD tier source checkers (JX124-JX126) --
    # Mesh axis names the repo declares (core/mesh.py AXIS_DATA /
    # AXIS_MODEL). JX124 flags these as string LITERALS in sharding
    # contexts (PartitionSpec/Mesh arguments, ``mesh.shape[...]``
    # lookups, ``axis_name=`` keywords, collective axis arguments,
    # ``*axis*`` parameter defaults) anywhere outside the axis-name
    # home — a renamed/reshaped mesh must be a one-file change, and the
    # shardcheck rules table keys on the canonical names.
    mesh_axis_names: list[str] = field(default_factory=lambda: [
        "data", "model",
    ])
    # Files allowed to SPELL the axis-name literals (fnmatch on the
    # lint-root relpath): the single definition site.
    mesh_axis_home: list[str] = field(default_factory=lambda: [
        "deepvision_tpu/core/mesh.py",
    ])
    # Directories whose code runs against multi-device meshes (JX125):
    # a bare single-argument ``jax.device_put(x)`` there silently
    # gathers/replicates onto the default device — every transfer on a
    # sharded path must say its sharding (or go through
    # core.mesh.shard_batch, which applies one).
    multidevice_dirs: list[str] = field(default_factory=lambda: [
        "deepvision_tpu/parallel", "deepvision_tpu/serve",
        "deepvision_tpu/train", "deepvision_tpu/core",
        "deepvision_tpu/resilience",
    ])
    # Directories where literal ``PartitionSpec``/``P`` construction is
    # banned (JX126): model and step code must get specs from the
    # ``[[shardcheck.rule]]`` table (via core/step helpers), not bake
    # them in — the rules table is what shardcheck audits for coverage
    # and what ROADMAP item 1's sharding engine consumes.
    partition_rule_dirs: list[str] = field(default_factory=lambda: [
        "deepvision_tpu/models", "deepvision_tpu/train",
    ])
    # Call names (matched against the FULL dotted name — a bare "dump"
    # would exempt json.dump/pickle.dump, exactly the non-atomic I/O
    # JX122 flags) VETTED for use inside signal handlers: the
    # flight-recorder dump path is written to be best-effort/atomic
    # and never raises (obs/distributed.FlightRecorder.dump /
    # flight_dump), so handlers may route through it; everything else
    # that locks/allocates/does I/O in a handler is flagged.
    signal_safe_calls: list[str] = field(default_factory=lambda: [
        "flight_dump", "self.dump",
    ])
    # Function-name patterns treated as pipeline execution paths
    # (JX127): the device-resident DAG runner and its per-stage
    # executors (serve/pipeline.py naming contract). A jax.device_get /
    # np.asarray / .block_until_ready() on an inter-stage value there
    # re-introduces the host round-trip the pipeline subsystem exists
    # to remove — stage outputs must stay device arrays until the
    # engine's single final fetch.
    pipeline_funcs: list[str] = field(default_factory=lambda: [
        "*pipeline*", "*_stage*", "run_dag*", "*_dag_*",
    ])
    # Function-name patterns treated as stream-handling loops (JX128):
    # stateful serving (serve/sessions.py) keeps each stream's session
    # state device-resident between frames, and the engine's stateful
    # batch path does exactly ONE device_get per executed batch — a
    # jax.device_get / np.asarray / .item() inside the per-frame loop
    # re-materializes the slate on the host every frame. The store's
    # own snapshot path (cadence-driven host I/O) is exempt by scoping:
    # it isn't a per-frame loop and these names don't match it.
    session_funcs: list[str] = field(default_factory=lambda: [
        "*frame_loop*", "*session_loop*", "handle_stream*",
        "*stream_loop*", "serve_stream*",
    ])
    # Function-name patterns treated as weight-residency managers
    # (JX129): the tenancy layer (serve/tenancy.py) owns the ONE
    # sanctioned path that stages weight pytrees onto the device —
    # adopt / ensure_resident / rematerialize, amortized across
    # requests behind the LRU budget. A ``jax.device_put`` of a
    # weights/params/variables pytree inside a dispatch or request
    # loop anywhere else re-uploads the full checkpoint per request
    # (HBM churn + PCIe stall on the hot path); results stay correct,
    # only the residency contract breaks.
    residency_funcs: list[str] = field(default_factory=lambda: [
        "*residency*", "*rematerialize*", "ensure_resident*",
        "*stage_weights*", "adopt*",
    ])
    disable: list[str] = field(default_factory=list)
    baseline: list[BaselineEntry] = field(default_factory=list)


def load_config(path: str | Path | None) -> LintConfig:
    """Build a LintConfig from ``jaxlint.toml`` (or defaults if absent)."""
    cfg = LintConfig()
    if path is None:
        return cfg
    path = Path(path)
    if not path.exists():
        return cfg
    data = loads_toml(path.read_text())
    table = data.get("jaxlint", {})
    for name in (
        "traced_dirs", "data_dirs", "parallel_dirs",
        "traced_name_patterns", "jit_wrappers", "static_return_calls",
        "key_fresheners", "key_name_patterns", "constraint_funcs",
        "prefetch_funcs", "serve_funcs", "checked_step_funcs",
        "timed_funcs", "loop_sleep_funcs", "wire_funcs",
        "cluster_funcs", "sentinel_funcs", "span_funcs",
        "precision_funcs", "pipeline_funcs", "session_funcs",
        "residency_funcs",
        "lock_name_patterns", "lock_blocking_calls", "collective_calls",
        "fork_unsafe_imports", "signal_safe_calls",
        "mesh_axis_names", "mesh_axis_home", "multidevice_dirs",
        "partition_rule_dirs", "disable",
    ):
        if name in table:
            setattr(cfg, name, list(table[name]))
    for entry in data.get("baseline", []):
        if "path" not in entry or "code" not in entry:
            raise TomlError(
                "baseline entries need at least 'path' and 'code': "
                f"{entry!r}")
        if not str(entry.get("reason", "")).strip():
            # the ledger is a reviewed debt list, not a mute button:
            # an exception nobody can justify is not an exception
            raise TomlError(
                "baseline entry for "
                f"{entry['path']!r} {entry['code']!r} has no 'reason' — "
                "every recorded exception must say why it is deliberate")
        cfg.baseline.append(BaselineEntry(
            path=entry["path"], code=entry["code"],
            reason=entry["reason"], match=entry.get("match", ""),
        ))
    return cfg


# ---------------------------------------------------------- ircheck config


@dataclass
class DonationWaiver:
    """A justified exception to the IR-level donation gate (JX104
    enforcement): ``model``'s compiled step is allowed an undonated
    state fraction up to ``max_undonated_fraction``. ``reason`` is
    mandatory — the ledger burns down, it does not accrete."""

    model: str
    reason: str
    max_undonated_fraction: float = 1.0
    hits: int = 0  # filled by ircheck; stale waivers are warned about


@dataclass
class HbmBaseline:
    """Recorded ``hbm_gb_per_step`` for one (model, platform, mesh,
    batch) lowering — the regression ledger the ±tolerance gate compares
    against, so the 76 GB class of numbers can only go down.

    ``wire_gb_per_step`` (optional, ISSUE 15) is the backend-neutral
    twin: logical traced-step bytes at the avals' own dtypes
    (ircheck.jaxpr_wire_bytes) — the number the bf16 diet provably
    moves even where a backend's float normalization blinds cost
    analysis to dtype (this box's cpu backend does exactly that)."""

    model: str
    platform: str  # jax backend the number was recorded on (cpu/tpu/...)
    batch: int
    hbm_gb_per_step: float
    mesh: str = "1x1"
    note: str = ""
    wire_gb_per_step: float | None = None


@dataclass
class DietTarget:
    """A declared mixed-precision diet floor: the case's bf16-policy
    trace must show at least ``min_reduction`` lower wire bytes than
    its f32 twin (``ircheck --diet``). The acceptance numbers of
    ISSUE 15 live here instead of in prose."""

    model: str
    min_reduction: float
    reason: str = ""


@dataclass
class DtypeWaiver:
    """A justified f32 pixel input on the H2D boundary of ``model``'s
    step (the IR twin of JX114) — e.g. feeds with no uint8 source.
    ``reason`` is mandatory."""

    model: str
    reason: str
    hits: int = 0


@dataclass
class IRCheckConfig:
    """Knobs + ledgers for the compiled-IR contract gate
    (``tools/jaxlint/ircheck.py``), loaded from the ``[ircheck]`` table
    and the ``[[ircheck.donation]]`` / ``[[ircheck.hbm]]`` /
    ``[[ircheck.dtype]]`` arrays of ``jaxlint.toml``."""

    # minimum donated fraction of state BYTES that must be aliased
    # input->output in the compiled executable (JX104 enforcement)
    donation_min_fraction: float = 0.99
    # HBM ledger gate: fail when measured > baseline * (1 + tolerance);
    # nudge to re-record when measured < baseline * (1 - tolerance)
    hbm_tolerance: float = 0.05
    # ircheck CASE names cheap enough for the tier-1/`make check`
    # subset (a case may cover several registry entries, e.g. "dcgan")
    fast_models: list[str] = field(default_factory=lambda: [
        "lenet5", "lenet5_tf", "dcgan",
    ])
    # registry-median floor for the --diet sweep (full runs only)
    diet_median_min: float = 0.25
    donation: list[DonationWaiver] = field(default_factory=list)
    hbm: list[HbmBaseline] = field(default_factory=list)
    dtype: list[DtypeWaiver] = field(default_factory=list)
    diet: list[DietTarget] = field(default_factory=list)

    def hbm_baseline(self, model: str, platform: str, mesh: str,
                     batch: int) -> HbmBaseline | None:
        for b in self.hbm:
            if (b.model, b.platform, b.mesh, b.batch) == \
                    (model, platform, mesh, batch):
                return b
        return None

    def donation_waiver(self, model: str) -> DonationWaiver | None:
        for w in self.donation:
            if w.model == model:
                return w
        return None

    def dtype_waiver(self, model: str) -> DtypeWaiver | None:
        for w in self.dtype:
            if w.model == model:
                return w
        return None

    def diet_target(self, model: str) -> DietTarget | None:
        for t in self.diet:
            if t.model == model:
                return t
        return None


def load_ircheck_config(path: str | Path | None) -> IRCheckConfig:
    """Build an IRCheckConfig from ``jaxlint.toml`` (defaults if
    absent). Donation/dtype waivers without a ``reason`` are rejected —
    same contract as the ``[[baseline]]`` ledger."""
    cfg = IRCheckConfig()
    if path is None:
        return cfg
    path = Path(path)
    if not path.exists():
        return cfg
    data = loads_toml(path.read_text())
    table = data.get("ircheck", {})
    for name in ("donation_min_fraction", "hbm_tolerance",
                 "diet_median_min"):
        if name in table:
            setattr(cfg, name, float(table[name]))
    if "fast_models" in table:
        cfg.fast_models = [str(x) for x in table["fast_models"]]
    for entry in table.get("donation", []):
        if "model" not in entry:
            raise TomlError(f"ircheck.donation entry needs 'model': {entry!r}")
        if not str(entry.get("reason", "")).strip():
            raise TomlError(
                f"ircheck.donation waiver for {entry['model']!r} has no "
                "'reason' — every donation exception must say why")
        cfg.donation.append(DonationWaiver(
            model=entry["model"], reason=entry["reason"],
            max_undonated_fraction=float(
                entry.get("max_undonated_fraction", 1.0)),
        ))
    for entry in table.get("hbm", []):
        for req in ("model", "platform", "batch", "hbm_gb_per_step"):
            if req not in entry:
                raise TomlError(
                    f"ircheck.hbm baseline needs {req!r}: {entry!r}")
        wire = entry.get("wire_gb_per_step")
        cfg.hbm.append(HbmBaseline(
            model=entry["model"], platform=entry["platform"],
            batch=int(entry["batch"]),
            hbm_gb_per_step=float(entry["hbm_gb_per_step"]),
            mesh=str(entry.get("mesh", "1x1")),
            note=str(entry.get("note", "")),
            wire_gb_per_step=float(wire) if wire is not None else None,
        ))
    for entry in table.get("diet", []):
        for req in ("model", "min_reduction"):
            if req not in entry:
                raise TomlError(
                    f"ircheck.diet entry needs {req!r}: {entry!r}")
        cfg.diet.append(DietTarget(
            model=entry["model"],
            min_reduction=float(entry["min_reduction"]),
            reason=str(entry.get("reason", "")),
        ))
    for entry in table.get("dtype", []):
        if "model" not in entry:
            raise TomlError(f"ircheck.dtype entry needs 'model': {entry!r}")
        if not str(entry.get("reason", "")).strip():
            raise TomlError(
                f"ircheck.dtype waiver for {entry['model']!r} has no "
                "'reason' — every f32-pixel exception must say why")
        cfg.dtype.append(DtypeWaiver(
            model=entry["model"], reason=entry["reason"],
        ))
    return cfg


# -------------------------------------------------------- shardcheck config


@dataclass
class PartitionRule:
    """One row of the declarative sharding rules table
    (``[[shardcheck.rule]]``): a regex over '/'-joined state-leaf paths
    (``params/Conv_0/kernel``, ``opt_state/0/mu/Dense_0/bias`` …) and
    the PartitionSpec it prescribes. ``spec`` is a tiny DSL whose ONE
    interpreter is the runtime sharding engine
    (``deepvision_tpu/core/sharding.py`` — trainer, checkpoint restore
    and shardcheck's ZeRO-1 compile all call it):

    - ``"replicated"`` — ``P()`` on every matched leaf
    - ``"data"`` / ``"data,*"`` … — per-dim axis entries (``*`` = None)
    - ``"largest(data)"`` — shard the LARGEST axis-divisible dim
      (``core.step.weight_update_sharding``'s ZeRO-1 rule)

    shardcheck's coverage audit asserts every leaf of every registry
    model matches a rule (first match wins, like the baseline ledger);
    ``largest(...)`` rules additionally mark the ZeRO-1 worklist the
    ``--zero1-ready`` residency table quantifies."""

    pattern: str
    spec: str
    reason: str = ""
    hits: int = 0  # filled by shardcheck; stale rules are warned about

    def matches(self, leaf_path: str) -> bool:
        return re.search(self.pattern, leaf_path) is not None


@dataclass
class CommsBaseline:
    """Recorded collective-traffic bytes for one (model, platform,
    mesh, batch) compile: ``coll_gb_per_step`` sums the output bytes of
    every collective instruction (all-reduce / all-gather /
    reduce-scatter / all-to-all / collective-permute) in the optimized
    SPMD module — per-participant bytes, the ratchet twin of the
    ``[[ircheck.hbm]]`` rows for the interconnect.

    ``zero1 = true`` rows key the ZeRO-1 compile (``shardcheck
    --zero1``): the weight-update sharding legitimately trades
    all-reduce for reduce-scatter/all-gather traffic, so replicated
    and ZeRO-1 programs ratchet against separate baselines."""

    model: str
    platform: str
    batch: int
    coll_gb_per_step: float
    mesh: str = "2x1"
    note: str = ""
    zero1: bool = False


@dataclass
class ReshardWaiver:
    """A justified implicit-resharding exception: ``model``'s compiled
    step at ``mesh`` is allowed collective opcode ``op`` (fnmatch)
    beyond the expected data-parallel set. ``reason`` is mandatory —
    a deliberate reshard (ZeRO-1's reduce-scatter + all-gather, spatial
    halo exchange) is declared here; an accidental one is a bug."""

    model: str
    op: str
    reason: str
    mesh: str = "*"
    hits: int = 0


@dataclass
class ShardCheckConfig:
    """Knobs + ledgers for the SPMD/collective-traffic gate
    (``tools/jaxlint/shardcheck.py``), loaded from the ``[shardcheck]``
    table and the ``[[shardcheck.rule]]`` / ``[[shardcheck.comms]]`` /
    ``[[shardcheck.reshard]]`` arrays of ``jaxlint.toml``."""

    # comms ledger gate: fail when measured > baseline * (1 + tol);
    # nudge to re-record when measured < baseline * (1 - tol)
    comms_tolerance: float = 0.05
    # case names cheap enough for the tier-1/`make lint-comms` subset
    fast_models: list[str] = field(default_factory=lambda: [
        "lenet5", "lenet5_tf", "dcgan",
    ])
    # mesh shapes ("NxM") every case is lowered at; >=2 shapes arm the
    # mesh-generalization gate (collective structure must not depend on
    # the grid extents). Chosen so the data axis divides every case's
    # batch (min registry batch is 2).
    mesh_shapes: list[str] = field(default_factory=lambda: [
        "2x1", "2x2",
    ])
    # collective opcodes a pure data-parallel replicated-params step is
    # EXPECTED to contain (fnmatch): gradient/metric all-reduce. Any
    # other collective in the compiled module is an implicit reshard
    # pjit inserted behind the program's back and needs a waiver.
    expected_collectives: list[str] = field(default_factory=lambda: [
        "all-reduce",
    ])
    rules: list[PartitionRule] = field(default_factory=list)
    comms: list[CommsBaseline] = field(default_factory=list)
    reshard: list[ReshardWaiver] = field(default_factory=list)

    def comms_baseline(self, model: str, platform: str, mesh: str,
                       batch: int, *,
                       zero1: bool = False) -> CommsBaseline | None:
        for b in self.comms:
            if (b.model, b.platform, b.mesh, b.batch, b.zero1) == \
                    (model, platform, mesh, batch, zero1):
                return b
        return None

    def reshard_waiver(self, model: str, mesh: str,
                       op: str) -> ReshardWaiver | None:
        for w in self.reshard:
            if w.model == model and fnmatch.fnmatch(op, w.op) \
                    and fnmatch.fnmatch(mesh, w.mesh):
                return w
        return None

    def match_rule(self, leaf_path: str) -> PartitionRule | None:
        for r in self.rules:
            if r.matches(leaf_path):
                return r
        return None


def load_shardcheck_config(path: str | Path | None) -> ShardCheckConfig:
    """Build a ShardCheckConfig from ``jaxlint.toml`` (defaults if
    absent). Reshard waivers without a ``reason`` and rules with an
    unparseable regex are rejected — same contract as every other
    ledger in this file."""
    cfg = ShardCheckConfig()
    if path is None:
        return cfg
    path = Path(path)
    if not path.exists():
        return cfg
    data = loads_toml(path.read_text())
    table = data.get("shardcheck", {})
    if "comms_tolerance" in table:
        cfg.comms_tolerance = float(table["comms_tolerance"])
    if "fast_models" in table:
        cfg.fast_models = [str(x) for x in table["fast_models"]]
    if "mesh_shapes" in table:
        cfg.mesh_shapes = [str(x) for x in table["mesh_shapes"]]
    if "expected_collectives" in table:
        cfg.expected_collectives = [
            str(x) for x in table["expected_collectives"]]
    for entry in table.get("rule", []):
        for req in ("pattern", "spec"):
            if req not in entry:
                raise TomlError(
                    f"shardcheck.rule entry needs {req!r}: {entry!r}")
        try:
            re.compile(str(entry["pattern"]))
        except re.error as e:
            raise TomlError(
                f"shardcheck.rule pattern {entry['pattern']!r} is not a "
                f"valid regex: {e}") from None
        cfg.rules.append(PartitionRule(
            pattern=str(entry["pattern"]), spec=str(entry["spec"]),
            reason=str(entry.get("reason", "")),
        ))
    for entry in table.get("comms", []):
        for req in ("model", "platform", "batch", "coll_gb_per_step"):
            if req not in entry:
                raise TomlError(
                    f"shardcheck.comms baseline needs {req!r}: {entry!r}")
        cfg.comms.append(CommsBaseline(
            model=entry["model"], platform=entry["platform"],
            batch=int(entry["batch"]),
            coll_gb_per_step=float(entry["coll_gb_per_step"]),
            mesh=str(entry.get("mesh", "2x1")),
            note=str(entry.get("note", "")),
            zero1=bool(entry.get("zero1", False)),
        ))
    for entry in table.get("reshard", []):
        for req in ("model", "op"):
            if req not in entry:
                raise TomlError(
                    f"shardcheck.reshard entry needs {req!r}: {entry!r}")
        if not str(entry.get("reason", "")).strip():
            raise TomlError(
                f"shardcheck.reshard waiver for {entry['model']!r} "
                f"{entry['op']!r} has no 'reason' — every deliberate "
                "reshard must say why it is intended")
        cfg.reshard.append(ReshardWaiver(
            model=entry["model"], op=entry["op"],
            reason=entry["reason"], mesh=str(entry.get("mesh", "*")),
        ))
    return cfg
