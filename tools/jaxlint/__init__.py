"""jaxlint — TPU-hazard static analysis for this repo.

The classes of bugs that silently destroy TPU step time — host↔device
syncs inside jitted code, recompilation hazards, PRNG key reuse, missing
buffer donation, dropped sharding constraints — are exactly the ones
pytest does not catch (the program is *correct*, just slow or subtly
non-reproducible). This package encodes those invariants as a FOUR-TIER
analyzer every PR runs:

    python -m tools.jaxlint deepvision_tpu/          # interprocedural AST pass
    python -m tools.jaxlint.evalcheck                # whole-zoo abstract-eval gate
    python -m tools.jaxlint.ircheck [--fast]         # compiled-IR contract gate
    python -m tools.jaxlint.shardcheck [--fast]      # SPMD/collective-traffic gate

Tier 1 (core.py + checkers.py) is the AST pass, interprocedural since
ISSUE 10: a per-run ProjectContext resolves calls across function and
module boundaries, so hazards routed through imported helpers are
caught without ``*_funcs`` name-pattern knobs (the knobs remain as
seeds); ``--format sarif`` emits a SARIF 2.1.0 log and
``--prune-baselines [--fix]`` burns paid-down debt out of the ledger.
Tier 2 (ircheck.py) lowers + compiles the REAL train step of
every registry model and verifies contracts on the jaxpr/optimized HLO:
donation actually aliased (JX104 enforcement + ledger), no f64 / no f32
pixels on the H2D boundary, jaxpr stability across bucket sizes,
collective axes vs the mesh, and the per-model ``hbm_gb_per_step`` /
``wire_gb_per_step`` regression ledgers (±5%, jaxlint.toml). Tier 3
(concurrency.py + threadcheck.py) is the host-runtime lock/thread
discipline — JX118–JX122 statically, plus the runtime lock sanitizer.
Tier 4 (shardcheck.py) rides ircheck's harness at real multi-device
CPU meshes: the per-(model, mesh, batch) collective-byte ledger
(``[[shardcheck.comms]]``, ±5%), the implicit-resharding detector
(unexpected collective opcodes need reasoned ``[[shardcheck.reshard]]``
waivers), the partition-rule coverage audit (every state leaf of every
registry model must match a ``[[shardcheck.rule]]`` row;
``--zero1-ready`` prints the ZeRO-1 residency worklist), and the
mesh-generalization gate (collective structure identical across mesh
shapes).

Checker codes (tools/jaxlint/checkers.py):

    JX101  host-sync call (.item()/.tolist()/np.asarray/float()) in traced code
    JX102  Python if/while on a traced array value (use lax.cond/while_loop)
    JX103  PRNG key consumed >1 time without an intervening split/fold_in
    JX104  jitted step function without donate_argnums
    JX105  unhashable / float Python value in a static jit argument
    JX106  print() in traced code (use jax.debug.print)
    JX107  jnp/jax.numpy in a host data pipeline (data/ must stay on host)
    JX108  reshape/transpose in parallel/ without a sharding constraint
    JX109  blocking host sync (np.asarray/.block_until_ready()/
           jax.device_get) inside a loop consuming a prefetched iterator
    JX110  jax.jit/pjit called inside a request-handling loop
           (per-request retrace/compile on the serving path)
    JX111  broad 'except Exception'/bare except around a compiled-step
           call (swallows the checkify NaN/Inf tripwire)
    JX112  time.time()/perf_counter() delta around a compiled-step call
           without block_until_ready between call and stop (async
           dispatch: the delta times enqueue, not compute)
    JX113  bare time.sleep inside a supervisor/dispatcher/router loop
           (ignores the stop event: shutdown hangs for the full
           backoff; use Event.wait(timeout))
    JX114  host-side float32 cast feeding the device wire
           (device_put/shard_batch/prefetcher): 4x H2D bytes — ship
           uint8, normalize/augment on device
    JX115  blocking cluster join/barrier (distributed.initialize,
           wait_at_barrier, await_all_arrived, ...) without a timeout
           argument — a missing/dead peer hangs the process forever
    JX116  per-step float()/np.asarray/device_get of a sent_* sentinel
           output inside a step loop, outside the drain cadence
           (re-introduces the JX109 host-sync stall)
    JX117  `with span(...)` over a compiled-step call with no
           device_sync/block_until_ready before the span end (the
           JX112 async-dispatch lie recorded into the trace)
    JX118  shared instance state touched by a thread-target method and
           a public method with either side outside the instance lock
    JX119  blocking call (HTTP/subprocess/file I/O/sleep/unbounded
           get/join/wait, incl. transitively) under a held lock
    JX120  lock-order cycle in the project-wide acquisition graph, or
           any lock held across a cross-host collective/barrier
    JX121  multiprocessing Pool/Process/Queue without an explicit
           spawn context in a module that reaches jax/tf (fork after
           runtime init inherits dead mutexes)
    JX122  signal handler that locks/allocates/does non-atomic I/O
           (self-deadlock when it interrupts its own critical section)
    JX123  raw f32 cast / f32-literal array in a model/loss hot body
           (the mixed-precision diet's erosion path)
    JX124  hardcoded mesh axis-name literal ("data"/"model" in
           PartitionSpec/Mesh ctors, collective axis args,
           mesh.shape lookups, axis-parameter defaults) outside
           core/mesh.py — spell AXIS_DATA/AXIS_MODEL/axis_size(mesh)
    JX125  bare jax.device_put with no sharding on a multi-device
           path (parks the tree on device 0; the donated jit rejects
           or silently reshards it every step)
    JX126  inline PartitionSpec(...) in model/step code — sharding
           decisions belong in the [[shardcheck.rule]] table or
           core/'s spec-building helpers
    JX127  jax.device_get/np.asarray/.block_until_ready() on an
           inter-stage value inside a pipeline execution path
           (``pipeline_funcs`` knob) — stage outputs must stay
           device-resident until the engine's single final fetch
    JX128  jax.device_get/np.asarray/.item() inside the per-frame
           loop of a stream-handling function (``session_funcs``
           knob) — session state stays device-resident between
           frames; the stateful batch path does ONE fetch per batch
    JX129  jax.device_put of a weights/params/variables pytree inside
           a dispatch/request loop outside a residency manager
           (``residency_funcs`` knob) — weights are staged ONCE by
           the tenancy layer; per-request uploads re-introduce the
           full checkpoint transfer on the hot path

Suppression: append ``# jaxlint: disable=JX103`` to the offending line
(or the line above), or record a repo-level exception in ``jaxlint.toml``
with a one-line justification. New checkers subclass
:class:`tools.jaxlint.core.Checker` and register with
``@register_checker`` — see README "Static analysis".
"""

from tools.jaxlint.core import (  # noqa: F401
    Checker,
    Finding,
    LintConfig,
    register_checker,
    run_paths,
)
