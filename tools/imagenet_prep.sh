#!/usr/bin/env bash
# ImageNet raw-download preparation (VERDICT §2 item 38).
# Capability parity with ref: Datasets/ILSVRC2012/{untar,flatten,
# flatten-val}-script.sh + DATASET.md:73-118 — unpack the per-synset
# train tars and flatten train/val into the single directories
# data/folder.py's loader expects (synset-prefixed filenames).
#
# Usage:
#   imagenet_prep.sh untar   <dir-with-per-synset-tars>
#   imagenet_prep.sh flatten <train-dir> <out-dir>
#   imagenet_prep.sh flatten-val <val-dir> <out-dir> <val-labels-file>
#     (val-labels-file: 50k ground-truth synsets in file order —
#      deepvision_tpu/data/assets/imagenet_val_labels.txt)
set -euo pipefail

cmd=${1:?usage: imagenet_prep.sh untar|flatten|flatten-val ...}

case "$cmd" in
  untar)
    dir=${2:?need dir with nXXXXXXXX.tar files}
    cd "$dir"
    for a in *.tar; do
      b=${a%.tar}
      mkdir -p "$b"
      tar xf "$a" -C "$b"
    done
    ;;
  flatten)
    src=${2:?need train dir}; out=${3:?need output dir}
    mkdir -p "$out"
    # files are already synset-prefixed (nXXXXXXXX_YYYY.JPEG)
    find "$src" -mindepth 2 -type f -exec cp -t "$out" '{}' +
    ;;
  flatten-val)
    src=${2:?need val dir}; out=${3:?need output dir}
    labels=${4:?need val-labels file}
    mkdir -p "$out"
    # rename ILSVRC2012_val_NNNNNNNN.JPEG -> <synset>_NNNNNNNN.JPEG so the
    # folder loader can parse the label from the filename; single pass
    # over both streams (no per-file sed rescans)
    paste -d' ' <(find "$src" -maxdepth 1 -type f -name '*.JPEG' | sort) \
                "$labels" | while read -r f syn; do
      cp "$f" "$out/${syn}_$(basename "$f" | grep -o '[0-9]*\.JPEG')"
    done
    ;;
  *)
    echo "unknown command: $cmd" >&2; exit 2;;
esac
echo "done: $cmd"
