#!/usr/bin/env python
"""Training CLI — surface parity with the reference:
``python train.py -m resnet50 [-c CKPT_EPOCH]``
(ref: ResNet/pytorch/train.py:541-562).

Extras over the reference:
- ``--data-dir`` points at TFRecords/idx files; with no data dir the run
  uses the synthetic dataset so every config smoke-trains hermetically
  (generalizing the reference's commented-out synthetic path,
  ref: CycleGAN/tensorflow/train.py:338-342).
- ``--epochs`` / ``--batch-size`` / ``--precision`` overrides.
"""

from __future__ import annotations

import argparse

import numpy as np


def parse_args():
    from deepvision_tpu.train.configs import TRAINING_CONFIG

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--model", required=True,
                   choices=sorted(TRAINING_CONFIG))
    p.add_argument("-c", "--checkpoint", type=int, default=None,
                   help="epoch to resume from (default: latest if present)")
    p.add_argument("--resume", action="store_true",
                   help="resume from latest checkpoint")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--workdir", default="runs")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--num-classes", type=int, default=None,
                   help="override the config's class count (synthetic "
                        "task-metric gates train with few classes)")
    p.add_argument("--lr", type=float, default=None,
                   help="override the config's base learning rate")
    p.add_argument("--input-size", type=int, default=None,
                   help="override the config's train-time crop size "
                        "(small-input smoke runs, launcher tests)")
    p.add_argument("--num-joints", type=int, default=None,
                   help="override the pose configs' joint count (the "
                        "synthetic set is fully learnable at 3 joints — "
                        "one per color channel)")
    p.add_argument("--precision", default=None,
                   choices=["bf16", "bf16_scaled", "f32"],
                   help="numerics policy (core/precision.py): bf16 "
                        "activations/gradients over f32 master weights, "
                        "bf16_scaled adds dynamic loss scaling, f32 is "
                        "the parity/fallback mode. Default: the model "
                        "config's explicit 'precision' declaration — "
                        "the config table is the source of truth, this "
                        "flag the only override")
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. 'cpu' for smoke runs; "
                        "jax.config wins over the JAX_PLATFORMS env var, "
                        "which site hooks may pin)")
    p.add_argument("--raw", dest="use_raw", action="store_true",
                   default=None,
                   help="require the pre-decoded raw-frame fast path "
                        "(data/builders/raw_crops.py); error if absent")
    p.add_argument("--no-raw", dest="use_raw", action="store_false",
                   help="read JPEG records even if raw-frame shards exist")
    p.add_argument("--synthetic-size", type=int, default=2048,
                   help="synthetic dataset size when no --data-dir")
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="override train steps per epoch (subset runs; "
                        "the ImageNet reader otherwise assumes the full "
                        "1.28M-image epoch)")
    p.add_argument("--output-bucket", default=None,
                   help="GCS bucket to publish the final checkpoint to "
                        "(ref: Hourglass/tensorflow/main.py:50-65)")
    p.add_argument("--output-dir", default=None,
                   help="GCS object prefix within --output-bucket")
    p.add_argument("--check-numerics", action="store_true",
                   help="run the train step under checkify float checks "
                        "(NaN/Inf raise with the failing op; ~2x slower)")
    p.add_argument("--zero1", "--shard-weight-update", dest="zero1",
                   action="store_true", default=None,
                   help="ZeRO-1 cross-replica weight-update sharding "
                        "(arXiv:2004.13336): grads reduce-scattered, "
                        "optimizer state sharded over the data axis, "
                        "params all-gathered — per the "
                        "[[shardcheck.rule]] table (core/sharding.py); "
                        "frees ~(1-1/N) of optimizer memory per chip, "
                        "numerics bit-comparable. train_dist.py turns "
                        "this on by default on multi-host launches")
    p.add_argument("--no-zero1", dest="zero1", action="store_false",
                   help="force the replicated weight update (opt out of "
                        "train_dist.py's multi-host ZeRO-1 default)")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="overlap per-epoch Orbax saves with training "
                        "(save() returns after staging to host)")
    p.add_argument("--keep-best", action="store_true",
                   help="retain the best checkpoints by the plateau "
                        "metric instead of the most recent (the "
                        "reference's save-on-new-best, "
                        "ref: YOLO/tensorflow/train.py:243-257)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="seconds without a completed step before the "
                        "stall watchdog fires (0 = off) — detects "
                        "wedged device/runtime RPCs that block the "
                        "step loop in a C call")
    p.add_argument("--stall-abort", action="store_true",
                   help="on stall, exit 75 (EX_TEMPFAIL) so a "
                        "supervisor restarts into --resume instead of "
                        "hanging forever")
    p.add_argument("--rss-limit-gb", type=float, default=0.0,
                   help="self-preempt (mid-epoch save + exit 143) when "
                        "host RSS crosses this many GB (0 = off) — "
                        "outruns the relay client's per-transfer host "
                        "memory leak on multi-hour runs; a supervisor "
                        "relaunches into --resume with a fresh process")
    p.add_argument("--label-smooth", type=float, default=0.0,
                   help="one-sided label smoothing on the DCGAN "
                        "discriminator's real targets (Salimans et al. "
                        "2016); 0 = reference-parity plain BCE")
    p.add_argument("--recover", action="store_true",
                   help="self-healing mode (resilience/): the NaN/Inf "
                        "tripwire rolls back to the last verified "
                        "checkpoint and skips the offending batch "
                        "window (implies --check-numerics), transient "
                        "data reads retry with backoff, and resume "
                        "quarantines corrupt checkpoints and falls "
                        "back to the newest verified epoch")
    p.add_argument("--max-rollbacks", type=int, default=3,
                   help="consecutive NaN rollbacks before --recover "
                        "aborts anyway (a persistent divergence must "
                        "still fail loudly)")
    p.add_argument("--lr-rewarm", type=float, default=None,
                   help="multiply the optimizer lr_scale by this "
                        "factor on every rollback (e.g. 0.5) — the "
                        "classic post-blow-up re-warm; default: keep "
                        "the LR")
    p.add_argument("--faults", default=None,
                   help="deterministic fault schedule for chaos drills "
                        "(resilience/faults.py grammar, e.g. "
                        "'nan@14,ckpt@1,io@8x2'); pair with --recover "
                        "to test self-healing, omit it to verify the "
                        "fail-fast paths")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for probabilistic (~) fault specs")
    p.add_argument("--sentinel", action="store_true",
                   help="silent-failure defense (resilience/sentinel"
                        ".py): cheap numeric invariants (update/param "
                        "norms + loss) computed INSIDE the compiled "
                        "step and z-scored on the drain cadence; under "
                        "a cluster (train_dist.py --supervise) adds "
                        "the cross-host state-agreement audit, and "
                        "every checkpoint manifest gains the save-time "
                        "state fingerprint (audited checkpoints)")
    p.add_argument("--audit-every", type=int, default=16,
                   help="run-step cadence of the cross-host state "
                        "fingerprint audit (and the worst-case SDC "
                        "detection latency, in steps); requires "
                        "--sentinel")
    p.add_argument("--sentinel-z", type=float, default=8.0,
                   help="z-score threshold of the sentinel EWMA "
                        "anomaly detector (trips feed the --recover "
                        "rollback, or fail fast without it)")
    p.add_argument("--sentinel-warmup", type=int, default=16,
                   help="observations per sentinel series before the "
                        "z-test arms (a cold variance estimate trips "
                        "on everything)")
    p.add_argument("--no-ckpt-integrity", action="store_true",
                   help="skip the per-save checksum manifest (one "
                        "SHA-256 pass over each committed checkpoint) "
                        "— trades a verified --recover resume for "
                        "save-time seconds on multi-GB states; "
                        "manifest-less epochs restore unverified")
    p.add_argument("--data-echo", type=int, default=1,
                   help="optimizer steps per transferred batch (data "
                        "echoing, arXiv:1907.05550) — multiplies step "
                        "throughput when the input pipeline or H2D "
                        "link, not the chip, is the bottleneck")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="enable obs span tracing (epoch/step/fetch/"
                        "eval/checkpoint + the feed producer's "
                        "host_next/shard) and export a Chrome-trace "
                        "JSON here on exit (chrome://tracing / "
                        "Perfetto; summarize with "
                        "tools/trace_summary.py)")
    p.add_argument("--profile-steps", default=None, metavar="A:B",
                   help="capture a jax.profiler trace over global "
                        "steps A..B (transferred-batch indices, "
                        "0-based) — a bounded window instead of "
                        "gigabytes of whole-run XPlane")
    p.add_argument("--profile-dir", default=None,
                   help="where --profile-steps writes the profiler "
                        "trace (default: WORKDIR/MODEL/profile)")
    p.add_argument("--device-aug", action="store_true",
                   help="split input pipeline (data/device_aug.py): the "
                        "host stops at decode+resize and ships uint8; "
                        "crop/flip/jitter/normalize run INSIDE the "
                        "compiled step, keyed through KeySeq so "
                        "preemption/chaos bit-determinism holds. "
                        "Record-backed runs only (--data-dir imagenet/"
                        "detection/pose/cyclegan)")
    p.add_argument("--mixup", type=float, default=0.0, metavar="ALPHA",
                   help="device-side mixup (Zhang et al. 2018) with "
                        "Beta(ALPHA, ALPHA) mixing, fused into the step "
                        "(classification configs, requires "
                        "--device-aug); 0 = off")
    p.add_argument("--loader-workers", type=int, default=1,
                   help="spread the host decode stage over N spawned "
                        "processes (data/loader.py; deterministic "
                        "round-robin merge over disjoint file shards) — "
                        "the multi-core answer to a decode-bound host; "
                        "ImageNet record runs only")
    p.add_argument("--max-worker-restarts", type=int, default=2,
                   help="bounded self-healing for a dead loader decode "
                        "worker: respawn it at its shard position "
                        "(merge order preserved, counted as "
                        "loader_worker_restarts) up to this many "
                        "CONSECUTIVE deaths per worker, then fail "
                        "fast; 0 = fail on the first death")
    p.add_argument("--prefetch-depth", type=int, default=2,
                   help="device batches the async feed keeps in flight "
                        "ahead of the step (data/prefetch.py); 1 = "
                        "classic double buffering, larger values ride "
                        "out host-pipeline jitter at the cost of one "
                        "staged batch of host+HBM memory each")
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deepvision_tpu.core import create_mesh
    from deepvision_tpu.data.mnist import batches, load_mnist_idx, synthetic_mnist
    from deepvision_tpu.models import get_model
    from deepvision_tpu.train.configs import get_config
    from deepvision_tpu.train.trainer import Trainer

    cfg = get_config(args.model)
    if args.batch_size:
        cfg["batch_size"] = args.batch_size
    if args.num_classes:
        cfg["num_classes"] = args.num_classes
    if args.lr:
        cfg["optimizer_params"]["lr"] = args.lr
    if args.num_joints and "num_heatmaps" in cfg:
        cfg["num_heatmaps"] = args.num_joints
    if args.input_size:
        cfg["input_size"] = args.input_size
    from deepvision_tpu.core.precision import get_policy

    # get_policy validates config-sourced names (argparse choices only
    # cover the CLI flag) and normalizes aliases like "bfloat16".
    # Resolution order: CLI override > the config's EXPLICIT declaration
    # (every shipped entry carries one — train/configs.py is the source
    # of truth, so the CLI docs and the table can no longer disagree).
    policy = get_policy(args.precision or cfg["precision"])
    cfg["precision"] = policy.name  # Trainer builds the same policy
    dtype = policy.compute_dtype
    if args.use_raw is not None and not (
            args.data_dir and cfg["dataset"] == "imagenet"):
        raise SystemExit(
            "--raw/--no-raw only applies to --data-dir ImageNet configs "
            f"(this run: dataset={cfg['dataset']!r}, "
            f"data_dir={args.data_dir!r})"
        )
    if args.label_smooth and cfg["dataset"] != "gan_mnist":
        raise SystemExit(
            "--label-smooth only applies to the DCGAN config "
            f"(this run: {args.model!r})")
    if not 0.0 <= args.label_smooth < 1.0:
        raise SystemExit(
            f"--label-smooth must be in [0, 1), got {args.label_smooth}")
    if args.stall_timeout < 0:
        raise SystemExit(
            f"--stall-timeout must be >= 0, got {args.stall_timeout}")
    if args.prefetch_depth < 1:
        raise SystemExit(
            f"--prefetch-depth must be >= 1, got {args.prefetch_depth}")
    if args.lr_rewarm is not None and not args.recover:
        raise SystemExit("--lr-rewarm only applies with --recover "
                         "(it scales the LR on each rollback)")
    if args.loader_workers < 1:
        raise SystemExit(
            f"--loader-workers must be >= 1, got {args.loader_workers}")
    if args.max_worker_restarts < 0:
        raise SystemExit(f"--max-worker-restarts must be >= 0, got "
                         f"{args.max_worker_restarts}")
    if args.loader_workers > 1 and not (
            args.data_dir and cfg["dataset"] == "imagenet"):
        raise SystemExit(
            "--loader-workers parallelizes the record decode stage — "
            "--data-dir ImageNet configs only (this run: "
            f"dataset={cfg['dataset']!r}, data_dir={args.data_dir!r})")
    if args.device_aug and (
            not args.data_dir
            or cfg["dataset"] not in ("imagenet", "detection", "pose",
                                      "gan_unpaired")):
        raise SystemExit(
            "--device-aug splits a record-backed host pipeline — "
            "--data-dir imagenet/detection/pose/cyclegan configs only "
            f"(this run: dataset={cfg['dataset']!r}, "
            f"data_dir={args.data_dir!r})")
    if args.mixup and not (args.device_aug
                           and cfg["dataset"] == "imagenet"):
        raise SystemExit(
            "--mixup is a device-side classification augmentation; it "
            "requires --device-aug on a --data-dir ImageNet config "
            f"(this run: {args.model!r})")
    if args.mixup < 0:
        raise SystemExit(f"--mixup must be >= 0, got {args.mixup}")
    _maybe_enable_trace(args)
    # recovery/injector built BEFORE the data factories: the loader's
    # worker_kill chaos site and bounded respawn hook into the ImageNet
    # reader construction below
    recovery = None
    if args.recover:
        from deepvision_tpu.resilience import RecoveryPolicy

        recovery = RecoveryPolicy(max_rollbacks=args.max_rollbacks,
                                  lr_rewarm=args.lr_rewarm)
    injector = None
    if args.faults:
        import os as _os

        from deepvision_tpu.resilience import FaultInjector

        # the ':hostH'-targeted sdc sites key on the ORIGINAL cluster
        # host id (stable across elastic relaunches); supervisor
        # replays run quiesced so the replayed window is ground truth
        orig_host = _os.environ.get("DVTPU_CLUSTER_ORIG_HOST")
        injector = FaultInjector(
            args.faults, seed=args.fault_seed,
            host=int(orig_host) if orig_host is not None else None,
            sdc_quiesce=bool(_os.environ.get("DVTPU_SDC_QUIESCE")))
        print(f"fault injection armed: {args.faults!r}", flush=True)
    sentinel = None
    if args.sentinel:
        import os as _os

        from deepvision_tpu.resilience.sentinel import SentinelMonitor

        replay = _os.environ.get("DVTPU_SENTINEL_REPLAY")
        sentinel = SentinelMonitor(
            z_threshold=args.sentinel_z, warmup=args.sentinel_warmup,
            audit_every=args.audit_every,
            replay_until=int(replay) if replay else None)
        print("[sentinel] armed: in-graph invariants + EWMA z-score "
              f"(z={args.sentinel_z:g}, warmup={args.sentinel_warmup})"
              f", state audits every {args.audit_every} steps"
              + (f"; REPLAY mode through run step {replay}"
                 if replay else ""), flush=True)
    if cfg["dataset"].startswith("gan"):
        if args.recover or args.faults or args.sentinel:
            raise SystemExit(
                "--recover/--faults/--sentinel ride the Trainer "
                "rollback/drain loop; the GAN fit_gan path has no "
                f"hook yet (this run: {args.model!r})")
        if args.profile_steps or args.profile_dir:
            raise SystemExit(
                "--profile-steps/--profile-dir ride the Trainer step "
                "counter; the GAN fit_gan path has no profiler hook "
                f"yet (this run: {args.model!r}; --trace works)")
        run_gan(args, cfg, policy)
        return
    if cfg["dataset"] == "pose":
        model = get_model(args.model, dtype=dtype,
                          num_heatmaps=cfg["num_heatmaps"],
                          **cfg.get("model_kwargs", {}))
    else:
        model = get_model(args.model, dtype=dtype,
                          num_classes=cfg["num_classes"],
                          **cfg.get("model_kwargs", {}))

    size, ch = cfg["input_size"], cfg["channels"]
    step_fns = {}
    if cfg["dataset"] == "pose":
        from deepvision_tpu.train.steps import pose_eval_step, pose_train_step

        step_fns = {"train_step": pose_train_step,
                    "eval_step": pose_eval_step}
        if args.data_dir:
            from deepvision_tpu.data.pose import make_pose_data

            steps = args.steps_per_epoch or 22245 // cfg["batch_size"]  # MPII
            train_data, val_data, steps = make_pose_data(
                args.data_dir, cfg["batch_size"], size,
                steps_per_epoch=steps, device_aug=args.device_aug,
            )
        else:
            from deepvision_tpu.data.pose import (
                synthetic_pose,
                synthetic_pose_batches,
            )

            n = args.synthetic_size
            size = min(size, 128)  # keep the synthetic smoke config small
            imgs, kx, ky, v = synthetic_pose(
                n, size=size, num_joints=cfg["num_heatmaps"]
            )
            split = max(cfg["batch_size"], int(n * 0.1))
            train_data = lambda e: synthetic_pose_batches(
                imgs[split:], kx[split:], ky[split:], v[split:],
                cfg["batch_size"], rng=np.random.default_rng(e),
            )
            val_data = lambda: synthetic_pose_batches(
                imgs[:split], kx[:split], ky[:split], v[:split],
                cfg["batch_size"], drop_remainder=False,
            )
            steps = (n - split) // cfg["batch_size"]
        cfg["input_size"] = size
    elif cfg["dataset"] == "detection":
        if cfg.get("steps") == "centernet":
            from deepvision_tpu.train.steps import (
                centernet_eval_step as det_eval,
                centernet_train_step as det_train,
            )
        else:
            from deepvision_tpu.train.steps import (
                yolo_eval_step as det_eval,
                yolo_train_step as det_train,
            )

        step_fns = {"train_step": det_train, "eval_step": det_eval}
        if args.data_dir:
            from deepvision_tpu.data.detection import make_detection_data

            steps = args.steps_per_epoch or 2501 // cfg["batch_size"]  # VOC07
            train_data, val_data, steps = make_detection_data(
                args.data_dir, cfg["batch_size"], size,
                steps_per_epoch=steps, device_aug=args.device_aug,
            )
        else:
            from deepvision_tpu.data.detection import (
                synthetic_batches,
                synthetic_detection,
            )

            n = args.synthetic_size
            size = min(size, 128)  # keep the synthetic smoke config small
            imgs, boxes, labels = synthetic_detection(
                n, size=size, num_classes=cfg["num_classes"]
            )
            split = max(cfg["batch_size"], int(n * 0.1))
            train_data = lambda e: synthetic_batches(
                imgs[split:], boxes[split:], labels[split:],
                cfg["batch_size"], rng=np.random.default_rng(e),
                augment=True,
            )
            val_data = lambda: synthetic_batches(
                imgs[:split], boxes[:split], labels[:split],
                cfg["batch_size"], drop_remainder=False,
            )
            steps = (n - split) // cfg["batch_size"]
        cfg["input_size"] = size
    elif args.data_dir and cfg["dataset"] == "imagenet":
        from deepvision_tpu.data.imagenet import make_imagenet_data

        train_data, val_data, steps = make_imagenet_data(
            args.data_dir, cfg["batch_size"], size,
            augment=cfg.get("augment", "tf"),
            use_raw=args.use_raw,
            steps_per_epoch=args.steps_per_epoch,
            device_aug=args.device_aug,
            loader_workers=args.loader_workers,
            max_worker_restarts=args.max_worker_restarts,
            fault_injector=injector,
        )
    elif args.data_dir and cfg["dataset"] == "mnist":
        import os

        tr_i, tr_l = load_mnist_idx(
            os.path.join(args.data_dir, "train-images-idx3-ubyte"),
            os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
        )
        te_i, te_l = load_mnist_idx(
            os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
            os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
        )
        train_data = lambda e: batches(tr_i, tr_l, cfg["batch_size"],
                                       rng=np.random.default_rng(e))
        val_data = lambda: batches(te_i, te_l, cfg["batch_size"],
                                   drop_remainder=False)
        steps = len(tr_l) // cfg["batch_size"]
    else:
        # hermetic synthetic fallback
        n = args.synthetic_size
        if cfg["dataset"] == "mnist":
            imgs, labels = synthetic_mnist(n)
            split = max(cfg["batch_size"], int(n * 0.1))
        else:
            from deepvision_tpu.data.synthetic import (
                synthetic_classification,
            )

            imgs, labels, split = synthetic_classification(
                n, size, ch, cfg["num_classes"], cfg["batch_size"]
            )
        train_data = lambda e: batches(imgs[split:], labels[split:],
                                       cfg["batch_size"],
                                       rng=np.random.default_rng(e))
        val_data = lambda: batches(imgs[:split], labels[:split],
                                   cfg["batch_size"], drop_remainder=False)
        steps = (n - split) // cfg["batch_size"]

    if not step_fns and cfg.get("augment") == "pt":
        # PT-lineage configs ship uint8 crops; the on-device normalization
        # must be the torchvision mean/std, not the TF mean subtraction.
        from functools import partial

        from deepvision_tpu.train.steps import (
            classification_eval_step,
            classification_train_step,
        )

        step_fns = {
            "train_step": partial(classification_train_step,
                                  normalize_kind="torch"),
            "eval_step": partial(classification_eval_step,
                                 normalize_kind="torch"),
        }

    if args.device_aug:
        # device stage of the split pipeline: the host shipped
        # decode-stage uint8 (the make_*_data device_aug flags above);
        # the stochastic ops run INSIDE the compiled step, keyed
        # through the step's KeySeq subkey (bit-deterministic resume).
        # Detection/pose flips transform boxes/keypoints consistently;
        # eval steps stay unwrapped (validation has no augmentation).
        from deepvision_tpu.data.device_aug import (
            MPII_FLIP_PERM,
            DeviceAugment,
            augment_step,
        )
        from deepvision_tpu.data.imagenet import PT_JITTER

        if cfg["dataset"] == "detection":
            aug = DeviceAugment("detection", flip=True)
        elif cfg["dataset"] == "pose":
            aug = DeviceAugment(
                "pose", flip=True,
                # the left/right channel swap is defined by the MPII
                # joint order; reduced-joint synthetic configs have no
                # left/right semantics to swap
                flip_pairs=(MPII_FLIP_PERM
                            if cfg["num_heatmaps"] == 16 else None))
        else:  # imagenet classification
            aug = DeviceAugment(
                "classification", flip=True,
                jitter=(PT_JITTER if cfg.get("augment") == "pt"
                        else 0.0),
                mixup=args.mixup)
        if not step_fns:
            from deepvision_tpu.train.steps import (
                classification_train_step as _cls_train,
            )

            step_fns = {"train_step": _cls_train}
        step_fns["train_step"] = augment_step(step_fns["train_step"],
                                              aug)
        print(f"[device-aug] {aug} fused into the train step",
              flush=True)

    if args.steps_per_epoch:
        steps = args.steps_per_epoch
        if not args.data_dir or cfg["dataset"] == "mnist":
            # the tf.data paths bake the limit into their readers; the
            # in-memory iterators must be truncated here or the LR
            # schedule (built from `steps`) would desynchronize from the
            # actual epoch length
            from itertools import islice

            train_data = (lambda f: lambda e: islice(f(e), steps))(
                train_data)

    if jax.process_count() > 1 and (not args.data_dir
                                    or cfg["dataset"] == "mnist"):
        # In-memory synthetic datasets generate the SAME global batches
        # in every process (seeded rng); core.shard_batch treats its
        # input as the process-LOCAL share, so each process must feed
        # only its disjoint row block — else a 2-process run would
        # silently train on a 2x global batch of duplicated rows. The
        # tf.data --data-dir paths (imagenet/pose/detection) instead
        # file-shard per process inside their make_*_data factories.
        train_data, val_data = (
            _localize_batches(f, jax.process_count(), jax.process_index())
            for f in (train_data, val_data)
        )

    mesh = create_mesh()
    print(f"devices: {jax.devices()}  mesh: {mesh.shape}")
    trainer = Trainer(
        model, cfg, mesh, train_data, val_data,
        workdir=args.workdir, steps_per_epoch=steps,
        check_numerics=args.check_numerics,
        shard_weight_update=bool(args.zero1),
        async_checkpoint=args.async_checkpoint,
        keep_best=args.keep_best, data_echo=args.data_echo,
        prefetch_depth=args.prefetch_depth,
        stall_timeout=args.stall_timeout or None,
        stall_abort=args.stall_abort,
        rss_limit_gb=args.rss_limit_gb or None,
        recovery=recovery, fault_injector=injector,
        sentinel=sentinel,
        ckpt_integrity=not args.no_ckpt_integrity,
        profile_steps=args.profile_steps, profile_dir=args.profile_dir,
        **step_fns,
    )
    # multi-host cluster supervision (train_dist.py --supervise): the
    # launcher exports the coordination dir; attach BEFORE resume() —
    # cluster resumes are lock-free/collective and heartbeats must
    # cover the restore
    from deepvision_tpu.resilience.cluster import ClusterMember

    member = ClusterMember.from_env()
    if member is not None:
        trainer.attach_cluster(member)
        print(f"[cluster] host {member.host}/{member.nhosts} "
              f"coordinating via {member.directory}", flush=True)
    if args.resume or args.checkpoint is not None:
        trainer.resume(args.checkpoint)
        print(f"resumed at epoch {trainer.start_epoch}"
              + (f" step {trainer.start_step}" if trainer.start_step
                 else ""))
    # SIGTERM (TPU-VM / k8s preemption grace signal) -> synchronous
    # mid-epoch checkpoint + exit 143; `--resume` picks it up and
    # continues bit-identically (SURVEY §5.3 — the reference has no
    # preemption story at all)
    trainer.install_preemption_handler()
    from deepvision_tpu.resilience.sentinel import (
        AuditDivergence,
        SentinelTrip,
    )

    try:
        trainer.fit(args.epochs)
    except (SentinelTrip, AuditDivergence) as e:
        # silent-data-corruption verdict: markers are already on the
        # cluster dir (trip / divergence); exit 76 tells a supervisor
        # this was an SDC stop, not a crash or a preemption
        print(f"[sentinel] FATAL: {e}", flush=True)
        raise SystemExit(76) from e
    finally:
        # export on EVERY exit (preemption and crashes included): a
        # truncated run's trace is exactly the one worth reading
        _maybe_export_trace(args)
    if trainer.replay_done:
        # replay-bisection window completed cleanly: the audit files
        # ARE the verdict; nothing to publish, nothing was saved
        print("[sentinel] replay verdict recorded; exiting 0",
              flush=True)
        return
    if trainer.preempted:
        raise SystemExit(143)
    _maybe_publish(args, f"{args.workdir}/{args.model}/ckpt")


def _maybe_enable_trace(args) -> None:
    if not args.trace:
        return
    from deepvision_tpu.obs.trace import get_tracer

    get_tracer().enable()
    print(f"[obs] span tracing on -> {args.trace}", flush=True)


def _maybe_export_trace(args) -> None:
    if not args.trace:
        return
    from deepvision_tpu.obs.trace import get_tracer

    n = get_tracer().export(args.trace)
    print(f"[obs] wrote {n} spans to {args.trace} "
          "(load in chrome://tracing or Perfetto; summarize with "
          "tools/trace_summary.py)", flush=True)


def _localize_batches(data_fn, nproc: int, pid: int):
    """Wrap a batch-iterator factory so every yielded batch is this
    process's row block (rows [pid·b/n, (pid+1)·b/n) of each globally
    identical batch)."""

    def wrapped(*a):
        for batch in data_fn(*a):
            n = next(iter(batch.values())).shape[0]
            if n % nproc:
                raise ValueError(
                    f"batch of {n} rows not divisible by {nproc} processes"
                )
            lb = n // nproc
            yield {k: v[pid * lb:(pid + 1) * lb] for k, v in batch.items()}

    return wrapped


def _maybe_publish(args, ckpt_dir: str):
    if not (args.output_bucket and args.output_dir):
        return
    from pathlib import Path

    from deepvision_tpu.train.publish import publish_to_gcs

    # publish only the newest retained epoch, not the whole manager tree
    root = Path(ckpt_dir)
    epochs = sorted(
        (p for p in root.iterdir() if p.name.isdigit()),
        key=lambda p: int(p.name),
    )
    target = epochs[-1] if epochs else root
    publish_to_gcs(target, args.output_bucket, args.output_dir)


def run_gan(args, cfg, policy):
    """GAN path: two-network state + fit_gan loop (train/gan.py)."""
    import jax

    dtype = policy.compute_dtype

    from deepvision_tpu.core import create_mesh
    from deepvision_tpu.data.mnist import synthetic_mnist
    from deepvision_tpu.models import get_model
    from deepvision_tpu.train.gan import (
        create_cyclegan_state,
        create_dcgan_state,
        cyclegan_train_step,
        dcgan_train_step,
        fit_gan,
    )
    from deepvision_tpu.train.schedules import linear_decay

    mesh = create_mesh()
    bs = cfg["batch_size"]
    epochs = args.epochs or cfg["total_epochs"]
    workdir = f"{args.workdir}/{cfg['name']}"

    if cfg["name"] == "dcgan":
        from deepvision_tpu.data.mnist import load_mnist_idx
        from deepvision_tpu.data.padding import iter_array_batches

        if args.data_dir:
            import os

            imgs, _ = load_mnist_idx(
                os.path.join(args.data_dir, "train-images-idx3-ubyte"),
                os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
                pad_to_32=False,
            )
        else:
            imgs, _ = synthetic_mnist(args.synthetic_size)
            imgs = imgs[:, 2:30, 2:30, :]  # 28² (DCGAN geometry)
        imgs = (imgs * 2.0 - 1.0).astype(np.float32)  # [-1, 1] (ref :26)
        train_data = lambda e: iter_array_batches(
            {"image": imgs}, bs, rng=np.random.default_rng(e)
        )
        state = create_dcgan_state(
            get_model("dcgan_generator", dtype=dtype),
            get_model("dcgan_discriminator", dtype=dtype),
            noise_dim=cfg["noise_dim"],
            lr=cfg["optimizer_params"]["lr"],
            policy=policy,
        )
        step_fn = dcgan_train_step
        if args.label_smooth:
            from functools import partial

            step_fn = partial(dcgan_train_step,
                              label_smooth=args.label_smooth)
    else:  # cyclegan
        size = cfg["input_size"]
        if args.data_dir:
            from deepvision_tpu.data.gan import make_cyclegan_data

            steps = args.steps_per_epoch or 1000 // bs
            train_data = make_cyclegan_data(
                args.data_dir, bs, size, steps_per_epoch=steps,
                device_aug=args.device_aug,
            )
        else:
            from deepvision_tpu.data.gan import synthetic_unpaired
            from deepvision_tpu.data.padding import iter_array_batches

            size = min(size, 64)
            a, b = synthetic_unpaired(args.synthetic_size, size=size)
            steps = len(a) // bs
            train_data = lambda e: iter_array_batches(
                {"a": a, "b": b}, bs, rng=np.random.default_rng(e)
            )
        lr = linear_decay(
            cfg["optimizer_params"]["lr"],
            cfg["total_epochs"] * steps,
            cfg["decay_epochs"] * steps,
        )
        state = create_cyclegan_state(
            get_model("cyclegan_generator", dtype=dtype),
            get_model("cyclegan_discriminator", dtype=dtype),
            image_size=size,
            lr_schedule=lr,
            beta1=cfg["optimizer_params"]["beta1"],
            policy=policy,
        )
        step_fn = cyclegan_train_step
        if args.device_aug:
            # split pipeline, GAN flavor: the host ships the uint8
            # size+30 canvas (data/gan.py device_aug); crop/flip and
            # the [-1,1] scale fuse into the compiled two-phase step
            # (the GAN steps don't call maybe_normalize themselves, so
            # the augment carries normalize="tanh")
            from deepvision_tpu.data.device_aug import (
                DeviceAugment,
                augment_step,
            )

            aug = DeviceAugment("gan", crop=size, flip=True,
                                normalize="tanh")
            step_fn = augment_step(step_fn, aug)
            print(f"[device-aug] {aug} fused into the train step",
                  flush=True)

    print(f"devices: {jax.devices()}  mesh: {mesh.shape}")
    # SIGTERM -> stop at the next epoch boundary with an off-cadence save
    # (same contract as Trainer.install_preemption_handler)
    from deepvision_tpu.train.trainer import (
        StallWatchdog,
        make_preempt_flag,
    )

    preempted = make_preempt_flag()
    # --rss-limit-gb on the GAN path: the epoch-granular preempt poll
    # doubles as the RSS check (fit_gan saves at epoch boundaries, so
    # "stop after this epoch + exit 143 + supervised --resume" is the
    # right granularity here)
    if args.rss_limit_gb:
        from deepvision_tpu.train.trainer import make_rss_limit_flag

        rss_exceeded = make_rss_limit_flag(args.rss_limit_gb)
        sigterm = preempted
        preempted = lambda: sigterm() or rss_exceeded()  # noqa: E731
    watchdog = (StallWatchdog(args.stall_timeout, abort=args.stall_abort)
                if args.stall_timeout else None)
    try:
        fit_gan(
            state, step_fn, train_data, mesh,
            epochs=epochs, workdir=workdir,
            save_every=cfg.get("save_every", 2),
            resume=args.resume or args.checkpoint is not None,
            resume_epoch=args.checkpoint,
            check_numerics=args.check_numerics,
            shard_weight_update=bool(args.zero1),
            async_checkpoint=args.async_checkpoint,
            preempt=preempted,
            watchdog=watchdog,
            prefetch_depth=args.prefetch_depth,
        )
    finally:
        _maybe_export_trace(args)  # same every-exit contract as main()
    if preempted():
        raise SystemExit(143)
    _maybe_publish(args, f"{workdir}/ckpt")


if __name__ == "__main__":
    main()
