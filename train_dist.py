#!/usr/bin/env python
"""Multi-host distributed training launcher.

The reference's READMEs advertise a ``train_dist.py`` that was never
committed (ref: ResNet/pytorch/README.md:15 — SURVEY §0); this is that
file, TPU-native. Run the SAME command on every host of a TPU slice (or
a CPU/GPU cluster with explicit coordinator flags):

    # TPU pod slice (all topology auto-detected from the TPU metadata):
    python train_dist.py -m resnet50 --data-dir gs://.../imagenet

    # explicit coordinator (CPU/GPU clusters, local testing):
    python train_dist.py --coordinator host0:1234 --num-processes 2 \
        --process-id 0 -m resnet50 ...

Mechanics (SURVEY §5.8's DCN mapping):
- ``jax.distributed.initialize`` joins the processes into one runtime;
  ``jax.devices()`` then spans every chip of every host and the regular
  ``create_mesh`` lays the global (data, model) mesh over ICI + DCN.
- each process feeds only its own file shard
  (``make_dataset(num_process=, process_index=)``), pushed through its
  own async device-feed thread (``data/prefetch.py`` — per-process
  prefetch + overlapped H2D). The split-pipeline flags pass straight
  through to train.py: ``--device-aug`` ships decode-stage uint8 and
  fuses crop/flip/jitter/normalize into the compiled step
  (``data/device_aug.py`` — 4x less DCN/PCIe wire traffic per host),
  and ``--loader-workers N`` spreads each process's decode stage over
  N spawned sub-workers (``data/loader.py``; the file-shard contract
  composes: process shard x worker shard). ``core.shard_batch`` assembles
  per-process local arrays into global jax.Arrays
  (``jax.make_array_from_process_local_data``). Multi-host runs default
  to ``--prefetch-depth 3`` (one extra in-flight batch) because the
  global-array assembly adds per-batch latency jitter a deeper queue
  absorbs; pass the flag explicitly to override.
- everything else — step functions, checkpointing (Orbax is
  multi-process-aware), metrics — is identical to single-host train.py,
  which this script delegates to after initialization.
"""

from __future__ import annotations

import argparse
import sys


def main():
    # peel off the launcher-only flags, pass the rest through to train.py
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--coordinator", default=None,
                   help="coordinator address host:port (omit on TPU pods "
                        "— auto-detected from the TPU metadata)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. 'cpu' for local "
                        "multi-process testing; jax.config wins over the "
                        "JAX_PLATFORMS env var, which site hooks may pin)")
    dist_args, train_argv = p.parse_known_args()

    import jax

    if dist_args.platform:
        jax.config.update("jax_platforms", dist_args.platform)
    kwargs = {}
    if dist_args.coordinator:
        kwargs = dict(
            coordinator_address=dist_args.coordinator,
            num_processes=dist_args.num_processes,
            process_id=dist_args.process_id,
        )
    jax.distributed.initialize(**kwargs)
    print(
        f"process {jax.process_index()}/{jax.process_count()}: "
        f"{jax.local_device_count()} local / "
        f"{jax.device_count()} global devices"
    )

    if jax.process_count() > 1 and not any(
            a == "--prefetch-depth" or a.startswith("--prefetch-depth=")
            for a in train_argv):
        # deeper default on real multi-host runs: the per-batch
        # make_array_from_process_local_data assembly adds latency
        # jitter that a 2-deep queue lets through to the step
        train_argv += ["--prefetch-depth", "3"]

    sys.argv = [sys.argv[0], *train_argv]
    import train

    train.main()


if __name__ == "__main__":
    main()
