#!/usr/bin/env python
"""Multi-host distributed training launcher + cluster supervisor.

The reference's READMEs advertise a ``train_dist.py`` that was never
committed (ref: ResNet/pytorch/README.md:15 — SURVEY §0); this is that
file, TPU-native and preemption-tolerant. Two modes:

**Worker mode** (default) — run the SAME command on every host of a TPU
slice (or a CPU/GPU cluster with explicit coordinator flags):

    # TPU pod slice (all topology auto-detected from the TPU metadata):
    python train_dist.py -m resnet50 --data-dir gs://.../imagenet

    # explicit coordinator (CPU/GPU clusters, local testing):
    python train_dist.py --coordinator host0:1234 --num-processes 2 \
        --process-id 0 -m resnet50 ...

``jax.distributed.initialize`` is ALWAYS called with a bounded
``--init-timeout-s`` (a missing peer used to hang the launcher
forever); on timeout the worker fails with a per-host error naming the
coordinator it waited on and exits 69 (EX_UNAVAILABLE) so a supervisor
can relaunch.

**Supervisor mode** (``--supervise N``) — spawn N worker processes on
this machine and keep the JOB alive through preemption
(``resilience/cluster.py``): per-host heartbeat liveness + straggler
detection (obs gauges ``cluster_host_alive`` / ``cluster_step_lag``), a
SIGTERM preemption notice triggering the coordinated save barrier (all
hosts commit ONE mid-epoch step through the PR 4 manifest machinery),
and deterministic elastic resume — the job relaunches on the surviving
host set with ``--resume``, the loader re-partitions its file shards
over the new host count, and ``KeySeq.skip`` replays identical PRNG
draws. Chaos-testable end to end:

    python train_dist.py --supervise 2 --platform cpu \
        --faults host_preempt@8 -m lenet5 --epochs 3 ...

``--faults`` schedules split automatically: ``host_preempt`` /
``host_stall`` specs drive the supervisor (consulted once per observed
cluster step — drills replay bit-identically), everything else passes
through to the in-job injectors. Exit line:
``[cluster] preemptions=P resumes=R stragglers=S host_deaths=D``.

Mechanics (SURVEY §5.8's DCN mapping): ``jax.distributed.initialize``
joins the processes into one runtime; each process feeds only its own
file shard (``make_dataset(num_process=, process_index=)``) through its
own async device-feed thread; ``core.shard_batch`` assembles per-process
local arrays into global jax.Arrays. Multi-host runs default to
``--prefetch-depth 3`` and to ZeRO-1 cross-replica weight-update
sharding (``--zero1``; ``--no-zero1`` opts out — core/sharding.py).
Everything else — step functions, checkpointing
(Orbax is multi-process-aware), metrics — is identical to single-host
train.py, which worker mode delegates to after initialization.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--coordinator", default=None,
                   help="coordinator address host:port (omit on TPU pods "
                        "— auto-detected from the TPU metadata)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. 'cpu' for local "
                        "multi-process testing; jax.config wins over the "
                        "JAX_PLATFORMS env var, which site hooks may pin)")
    p.add_argument("--init-timeout-s", type=float, default=300.0,
                   help="bound on jax.distributed.initialize — a missing "
                        "peer fails the join with a clear per-host error "
                        "instead of hanging the launcher forever")
    p.add_argument("--supervise", type=int, default=None, metavar="N",
                   help="cluster-supervisor mode: spawn N local worker "
                        "processes, watch heartbeats, deliver/absorb "
                        "preemptions, and relaunch on the surviving "
                        "host set (resilience/cluster.py)")
    p.add_argument("--faults", default=None,
                   help="deterministic fault schedule (resilience/"
                        "faults.py grammar); host_preempt/host_stall "
                        "specs drive the supervisor, the rest pass "
                        "through to the workers' in-job injectors")
    p.add_argument("--fault-seed", type=int, default=0)
    p.add_argument("--heartbeat-timeout-s", type=float, default=120.0,
                   help="supervisor: a host silent this long is dead — "
                        "the generation is killed and relaunched from "
                        "the newest commonly-verified epoch")
    p.add_argument("--straggler-after-s", type=float, default=5.0,
                   help="supervisor: heartbeat age that flags a host as "
                        "a straggler (logged + counted, gauges updated)")
    p.add_argument("--barrier-lead", type=int, default=None,
                   help="coordinated-save stop-step lead (default 64; "
                        "must exceed 2x the trainer's fetch cadence)")
    p.add_argument("--barrier-timeout-s", type=float, default=30.0,
                   help="bound on the all-hosts save-barrier rendezvous; "
                        "on timeout the save is skipped and resume "
                        "falls back to the newest commonly-verified "
                        "epoch")
    p.add_argument("--max-relaunches", type=int, default=3,
                   help="supervisor: crash/dead-host relaunch budget "
                        "(graceful preemptions don't consume it)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="supervisor: serve the obs registry as a "
                        "Prometheus scrape surface on this port "
                        "(GET /metrics: cluster_host_alive / "
                        "cluster_step_lag liveness gauges + the "
                        "sentinel_* SDC counters; 0 = ephemeral)")
    return p


def run_supervisor(dist_args, train_argv) -> int:
    from deepvision_tpu.resilience.cluster import (
        BARRIER_LEAD,
        ClusterSupervisor,
        argv_value,
    )
    from deepvision_tpu.resilience.faults import (
        CLUSTER_SITES,
        FaultInjector,
        split_schedule,
    )

    injector = None
    if dist_args.faults:
        mine, rest = split_schedule(dist_args.faults, CLUSTER_SITES)
        if mine:
            injector = FaultInjector(mine, seed=dist_args.fault_seed)
            print(f"[cluster] supervisor fault injection armed: "
                  f"{mine!r}", flush=True)
        if rest:
            train_argv = [*train_argv, "--faults", rest,
                          "--fault-seed", str(dist_args.fault_seed)]
    workdir = argv_value(train_argv, "--workdir") or "runs"
    sup = ClusterSupervisor(
        train_argv, dist_args.supervise, workdir,
        launcher=__file__,
        platform=dist_args.platform,
        injector=injector,
        init_timeout_s=dist_args.init_timeout_s,
        heartbeat_timeout_s=dist_args.heartbeat_timeout_s,
        straggler_after_s=dist_args.straggler_after_s,
        barrier_lead=(dist_args.barrier_lead
                      if dist_args.barrier_lead is not None
                      else BARRIER_LEAD),
        barrier_timeout_s=dist_args.barrier_timeout_s,
        max_relaunches=dist_args.max_relaunches,
    )
    server = None
    if dist_args.metrics_port is not None:
        # the multi-host scrape surface, now FEDERATED
        # (obs/distributed.py): the supervisor's own registry (liveness
        # gauges + sentinel_* SDC counters) plus every live host's
        # registry dump — published on the heartbeat cadence into the
        # generation dir — re-exported with {host=<id>} labels and
        # exact counter sums, so one scrape describes the whole fleet
        from deepvision_tpu.obs.metrics import start_exposition_server

        server, port = start_exposition_server(
            dist_args.metrics_port,
            render_fn=sup.render_federated_metrics)
        print(f"[cluster] Prometheus metrics on :{port}/metrics "
              "(federated over the live hosts)", flush=True)
    try:
        return sup.run()
    finally:
        if server is not None:
            server.shutdown()


def run_worker(dist_args, train_argv) -> None:
    import os

    import jax

    if dist_args.platform:
        jax.config.update("jax_platforms", dist_args.platform)
    platform = (dist_args.platform
                or os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in platform:
        # multiprocess CPU computations need an explicit collectives
        # backend on this jax (without it every cross-process psum —
        # orbax's sync barriers included — fails with "Multiprocess
        # computations aren't implemented on the CPU backend")
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass  # option absent on this jax: defaults already work
    kwargs = {}
    if dist_args.coordinator:
        kwargs = dict(
            coordinator_address=dist_args.coordinator,
            num_processes=dist_args.num_processes,
            process_id=dist_args.process_id,
        )
    import inspect

    bounded = "initialization_timeout" in inspect.signature(
        jax.distributed.initialize).parameters
    who = (f"process {dist_args.process_id}/{dist_args.num_processes}"
           if dist_args.process_id is not None else "this process")
    # banner BEFORE the join: some jax builds hard-abort (absl FATAL,
    # SIGABRT) on DEADLINE_EXCEEDED instead of raising, so the per-host
    # context must already be in the log when the process dies
    print(f"[cluster] {who}: joining coordinator "
          f"{dist_args.coordinator or '<auto-detected>'} "
          f"(--init-timeout-s {dist_args.init_timeout_s:.0f}s; a "
          "DEADLINE_EXCEEDED abort below means a peer never came up)",
          flush=True)
    try:
        # bounded join (jaxlint JX115): a blocking cluster join without
        # a timeout hangs forever on a missing peer
        if bounded:
            jax.distributed.initialize(
                initialization_timeout=int(dist_args.init_timeout_s),
                **kwargs)
        else:  # ancient jax: no bounded join available
            jax.distributed.initialize(**kwargs)  # jaxlint: disable=JX115
    except Exception as e:
        print(
            f"[cluster] {who}: jax.distributed.initialize failed after "
            f"--init-timeout-s={dist_args.init_timeout_s:.0f}s against "
            f"coordinator {dist_args.coordinator or '<auto-detected>'}: "
            f"{type(e).__name__}: {e} — are all "
            f"{dist_args.num_processes or '?'} peers up and reachable?",
            file=sys.stderr, flush=True)
        raise SystemExit(69)  # EX_UNAVAILABLE: supervisor may relaunch
    print(
        f"process {jax.process_index()}/{jax.process_count()}: "
        f"{jax.local_device_count()} local / "
        f"{jax.device_count()} global devices"
    )

    if dist_args.faults:
        train_argv = [*train_argv, "--faults", dist_args.faults,
                      "--fault-seed", str(dist_args.fault_seed)]
    if jax.process_count() > 1 and not any(
            a == "--prefetch-depth" or a.startswith("--prefetch-depth=")
            for a in train_argv):
        # deeper default on real multi-host runs: the per-batch
        # make_array_from_process_local_data assembly adds latency
        # jitter that a 2-deep queue lets through to the step
        train_argv += ["--prefetch-depth", "3"]
    if jax.process_count() > 1 and not any(
            a in ("--zero1", "--no-zero1", "--shard-weight-update")
            for a in train_argv):
        # ZeRO-1 default on multi-host: with >1 host the data axis is
        # where the memory is — cross-replica weight-update sharding
        # (arXiv:2004.13336) frees ~(1-1/N) of optimizer state per
        # chip for a reduce-scatter/all-gather swap that is free-to-
        # cheap on TPU ICI. --no-zero1 opts back into the replicated
        # update.
        train_argv += ["--zero1"]
        print("[cluster] multi-host: ZeRO-1 weight-update sharding on "
              "by default (--no-zero1 opts out)", flush=True)

    sys.argv = [sys.argv[0], *train_argv]
    import train

    train.main()


def main():
    # peel off the launcher-only flags, pass the rest through to train.py
    dist_args, train_argv = build_parser().parse_known_args()
    if dist_args.supervise is not None:
        raise SystemExit(run_supervisor(dist_args, train_argv))
    run_worker(dist_args, train_argv)


if __name__ == "__main__":
    main()
