# Ops targets — surface parity with the reference's per-model Makefiles
# (ref: ResNet/pytorch/Makefile: nohup train_*/resume_* with timestamped
# logs, tensorboard, process inspection), generalized over one shared CLI.
#
#   make train_resnet50 DATA=/data/imagenet   background train + log file
#   make resume_resnet50                       resume from latest checkpoint
#   make test | make bench | make dryrun       CI entry points
#   make tensorboard                           serve ./runs

# bash + pipefail: the gate targets pipe train/eval through tee, and a
# crashed run must fail the target, not "pass" on tee's exit 0
SHELL := /bin/bash
.SHELLFLAGS := -o pipefail -c

TIME := `/bin/date "+%Y-%m-%d-%H-%M-%S"`
DATA ?=
DATA_FLAG := $(if $(DATA),--data-dir $(DATA),)
WORKDIR ?= runs
PY ?= python

MODELS := lenet5 alexnet1 alexnet2 vgg16 vgg19 inception1 inception3 \
          resnet34 resnet50 resnet152 resnet50v2 mobilenet1 shufflenet1 \
          darknet53 yolov3 centernet hourglass104 dcgan cyclegan

# make train_<model>: nohup background run with a timestamped log
# (the reference's crash-survival mechanism, ref: ResNet/pytorch/Makefile)
train_%:
	mkdir -p $(WORKDIR) logs
	nohup $(PY) -u train.py -m $* $(DATA_FLAG) --workdir $(WORKDIR) \
		> "logs/$*-$(TIME).log" 2>&1 &
	@echo "started $*; tail -f logs/$*-*.log"

# make resume_<model>: continue from the latest Orbax checkpoint
resume_%:
	mkdir -p $(WORKDIR) logs
	nohup $(PY) -u train.py -m $* $(DATA_FLAG) --workdir $(WORKDIR) \
		--resume > "logs/$*-resume-$(TIME).log" 2>&1 &

test:
	$(PY) -m pytest tests/ -x -q

# fast tier: <5 min on a 1-core box (tests/conftest.py tiering registry)
smoke:
	$(PY) -m pytest tests/ -m smoke -x -q

# TPU-hazard static analysis (interprocedural; tools/jaxlint/core.py)
# over the library AND the top-level entry points, the registry-wide
# abstract-eval gate, and the CPU-cheap subset of the compiled-IR
# contract gate. Suppressions + baselines/ledgers in jaxlint.toml.
# Runs on every PR via `make check`.
LINT_PATHS := deepvision_tpu/ tools/ train.py train_dist.py serve.py \
              bench.py predict.py evaluate.py
lint:
	$(PY) -m tools.jaxlint $(LINT_PATHS)
	$(PY) -m tools.jaxlint.evalcheck
	$(PY) -m tools.jaxlint.ircheck --fast

# concurrency tier only (ISSUE 14, tools/jaxlint/concurrency.py):
# JX118 unguarded shared state, JX119 blocking call under lock, JX120
# lock-order deadlock graph (incl. lock-across-collective), JX121
# fork-unsafe multiprocessing after jax/tf import, JX122 signal-handler
# safety. The full `make lint` sweep above already runs these five —
# this target is the fast (~10s) entry point when touching only
# threads/locks, and what CI greps when a concurrency finding fires.
lint-threads:
	$(PY) -m tools.jaxlint --select JX118,JX119,JX120,JX121,JX122 \
	    $(LINT_PATHS)

# compiled-IR contract gate, registry-wide (tools/jaxlint/ircheck.py):
# lowers the REAL train step of every registry model (under its
# config's declared numerics policy) and verifies donation aliasing
# (JX104 enforcement), dtype discipline (no f64, no f32 pixels on the
# wire), jaxpr stability across two bucket sizes, collective axis
# names vs the mesh, the per-model hbm_gb_per_step cost-analysis
# ledger AND the backend-neutral wire_gb_per_step ledger (±5%,
# jaxlint.toml [[ircheck.hbm]]), plus the --diet assertion: each
# case's bf16-policy trace vs its f32 twin must clear the
# [[ircheck.diet]] reduction floors (ISSUE 15; the cpu backend
# float-normalizes convs, so cost analysis alone cannot see the
# dtype diet — measured in tools/jaxlint/ircheck.jaxpr_wire_bytes's
# docstring). The --fast subset gates every PR inside `make lint`;
# this full sweep compiles every family (minutes on a CPU box — heavy
# models live here, not in tier-1) and is the gate when
# step/model/optimizer/precision code moves.
lint-ir:
	$(PY) -m tools.jaxlint.ircheck --diet
	$(PY) -m tools.jaxlint.shardcheck

# SPMD sharding & collective-traffic gate, fast subset
# (tools/jaxlint/shardcheck.py): comms-byte ledger vs the
# [[shardcheck.comms]] ratchets, implicit-resharding detector,
# partition-rule coverage audit, and the mesh-generalization check
# (2x1 vs 2x2 collective structure must match) on the cheap cases.
# The registry-wide sweep rides `make lint-ir` above.
lint-comms:
	$(PY) -m tools.jaxlint.shardcheck --fast

# post-diet residual: the remaining f32 surface per model — by design
# the policy floors only (BN statistics accumulation, f32 heads and
# carriers, loss reductions; JX123 keeps new raw-f32 out)
bf16-ready:
	$(PY) -m tools.jaxlint.ircheck --bf16-ready

# mixed-precision smoke (ISSUE 15): a short lenet synthetic run must
# CONVERGE under the scaled-bf16 policy (train_top1 strictly improves
# over the pre-train eval) with the mp_* metrics present, and the
# fast-tier ledger (hbm + wire + donation) must hold — the
# `make check` numerics-policy gate
precision-smoke:
	@mkdir -p logs; L="logs/precision-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	rm -rf runs/precision-smoke; \
	$(PY) train.py -m lenet5 --platform cpu --precision bf16_scaled \
		--epochs 2 --synthetic-size 512 --batch-size 64 \
		--workdir runs/precision-smoke 2>&1 | tee "$$L" && \
	grep -q "train_mp_loss_scale" "$$L" && \
	grep -q "train_mp_grads_finite=1" "$$L" && \
	$(PY) -c "import json, re, sys; \
	    log = open('$$L'.strip()).read(); \
	    top1 = [float(m) for m in re.findall(r'val_top1=([0-9.e+-]+)', log)]; \
	    assert len(top1) >= 2 and top1[-1] > top1[0] + 0.2, top1; \
	    print(f'precision-smoke converged: val_top1 {top1[0]} -> {top1[-1]}')" && \
	$(PY) -m tools.jaxlint.ircheck --fast 2>&1 | tee -a "$$L" && \
	echo "precision-smoke OK (bf16_scaled converged + fast ledger green)"

# serving smoke: boot the stdin-JSONL server on lenet5 (compiles its
# bucket executables at startup), push 3 requests through the engine,
# assert 3 results come back — the `make check` serving gate
serve-smoke:
	$(PY) -c "import json, numpy as np; \
	    [print(json.dumps({'id': i, 'model': 'lenet5', \
	     'input': np.zeros((32, 32, 1)).tolist()})) for i in range(3)]" \
	| $(PY) serve.py -m lenet5 --buckets 1,4 \
	| $(PY) -c "import sys, json; \
	    rows = [json.loads(l) for l in sys.stdin if l.strip()]; \
	    ok = [r for r in rows if 'result' in r]; \
	    assert len(ok) == 3, rows; \
	    print('serve-smoke OK (3/3 responses)')"

# pipeline smoke: the device-resident DAG tier (serve/pipeline.py),
# two legs. (1) a 2-stage toy DAG (resize glue -> lenet5) from a
# generated --pipelines spec, served over the stdin-JSONL CLI alongside
# plain model traffic — asserts 3/3 DAG + 2/2 plain responses and the
# grep-stable `[pipeline]` exit line (served counts + frozen cache).
# (2) the REAL detect->crop->pose DAG at reduced geometry
# (tools/pipeline_smoke.py): decision parity vs the sequential client,
# flat post-warm miss counter, per-stage spans merged and verified by
# the trace_merge --assert-flow gate. Evidence log under logs/.
# Crash-safe stateful sessions (PR 19): 4 synthetic video streams x 12
# frames through the tracking pipeline on a 2-replica fleet, with a
# replica SIGKILLed mid-stream. Gates: every frame answered, ZERO
# stream resets (state_reset=false on every response — migrated
# streams restore from shared snapshots + windowed replay), and the
# router exit line proves streams actually migrated (sessions_migrated
# >= 1) while the reset counter stayed at 0.
stream-smoke:
	@mkdir -p logs; L="logs/stream-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) -c "import json, numpy as np; \
	    rng = np.random.default_rng(0); \
	    [print(json.dumps({'id': f'cam{s}-{i}', 'model': 'track', \
	     'session': f'cam{s}', 'seq': i, \
	     'input': (rng.standard_normal((16, 16, 1)) * 0.3).tolist()})) \
	     for i in range(12) for s in range(4)]" \
	| $(PY) serve.py --fleet 2 --track synth:4 --buckets 4 \
	    --snapshot-every 3 --faults replica_kill@20 --timeout-s 20 \
	    2> "$$L" \
	| $(PY) -c "import sys, json; \
	    rows = [json.loads(l) for l in sys.stdin if l.strip()]; \
	    ok = [r for r in rows if 'result' in r]; \
	    assert len(ok) == 48, (len(ok), rows[:3]); \
	    resets = [r for r in ok if r['result'].get('state_reset')]; \
	    assert not resets, resets[:3]; \
	    seqs = {}; \
	    [seqs.setdefault(r['result']['session'], []).append( \
	        r['result']['seq']) for r in ok]; \
	    assert all(v == sorted(v) for v in seqs.values()), seqs; \
	    print('stream-smoke stream OK (48/48 frames, 0 resets)')" && \
	grep -qE "sessions_migrated=[1-9]" "$$L" && \
	grep -qE " resets=0" "$$L" && \
	grep -qE "deaths=1" "$$L" && \
	echo "stream-smoke OK (replica SIGKILLed mid-stream, streams" \
	     "migrated, zero resets)"

pipeline-smoke:
	@mkdir -p logs; L="logs/pipeline-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) -c "import json; print(json.dumps({'name': 'lenetpipe', \
	    'input': {'shape': [64, 64, 1]}, 'buckets': [1, 4], \
	    'nodes': [ \
	        {'name': 'shrink', 'glue': 'resize', 'params': {'size': 32}}, \
	        {'name': 'cls', 'model': 'lenet5', 'inputs': ['shrink']}], \
	    'outputs': ['cls']}))" > logs/pipeline-smoke-spec.json && \
	$(PY) -c "import json, numpy as np; \
	    [print(json.dumps({'id': i, 'pipeline': 'lenetpipe', \
	     'input': np.zeros((64, 64, 1)).tolist()})) for i in range(3)]; \
	    [print(json.dumps({'id': 10 + i, 'model': 'lenet5', \
	     'input': np.zeros((32, 32, 1)).tolist()})) for i in range(2)]" \
	| $(PY) serve.py -m lenet5 --buckets 1,4 \
	    --pipelines logs/pipeline-smoke-spec.json 2> "$$L" \
	| $(PY) -c "import sys, json; \
	    rows = [json.loads(l) for l in sys.stdin if l.strip()]; \
	    dag = [r for r in rows if 'result' in r and 'cls' in r['result']]; \
	    plain = [r for r in rows if 'result' in r and 'classes' in r['result']]; \
	    assert len(dag) == 3 and len(plain) == 2, rows; \
	    print('pipeline-smoke stream OK (3 DAG + 2 plain responses)')" && \
	grep -qE "\[pipeline\] served lenetpipe=3 frozen=True" "$$L" && \
	$(PY) tools/pipeline_smoke.py 2>&1 | tee -a "$$L" && \
	grep -q "pipeline-smoke OK" "$$L"

# multi-tenant hot-swap smoke (ISSUE 20): a 2-tenant host — lenet5
# plus a pre-exported StableHLO side artifact — serves a paced JSONL
# stream while tenant lenet5's weights hot-swap mid-stream (a
# {"control": "swap"} line on stdin; perturb path: new fingerprint
# without a second checkpoint). Gates: every data line answered (zero
# drops — in-flight old-edition requests drain untouched), responses
# from BOTH weight editions observed (the atomic flip landed
# mid-stream), the side tenant untouched, and the grep-stable
# `[tenancy] swaps=1 evictions=E` exit line. Evidence log under logs/.
swap-smoke:
	@mkdir -p logs; L="logs/swap-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) -c "import numpy as np; \
	    from deepvision_tpu.export import export_forward, save_exported; \
	    rng = np.random.default_rng(0); \
	    w = rng.normal(size=(8, 10)).astype(np.float32); \
	    save_exported('logs/swap-smoke-side.stablehlo', \
	        export_forward(lambda v, x: x @ v['w'], {'w': w}, \
	                       np.zeros((1, 8), np.float32), \
	                       train_kwarg=False))" && \
	$(PY) -c "import json, time, numpy as np; \
	    x32 = np.zeros((32, 32, 1)).tolist(); \
	    x8 = np.zeros(8).tolist(); \
	    emit = lambda o: (print(json.dumps(o), flush=True), \
	                      time.sleep(0.04)); \
	    [emit({'id': i, 'model': 'lenet5', 'input': x32}) \
	     for i in range(10)]; \
	    [emit({'id': 100 + i, 'model': 'side', 'input': x8}) \
	     for i in range(3)]; \
	    emit({'control': 'swap', 'model': 'lenet5', 'perturb': 0.01}); \
	    [emit({'id': 200 + i, 'model': 'lenet5', 'input': x32}) \
	     for i in range(30)]; \
	    [emit({'id': 300 + i, 'model': 'side', 'input': x8}) \
	     for i in range(3)]" \
	| $(PY) serve.py -m lenet5 \
	    --artifact side=logs/swap-smoke-side.stablehlo --buckets 1 \
	    2> "$$L" \
	| $(PY) -c "import sys, json; \
	    rows = [json.loads(l) for l in sys.stdin if l.strip()]; \
	    ok = [r for r in rows if 'result' in r]; \
	    assert len(ok) == 46, (len(ok), rows[:3]); \
	    side = [r for r in ok \
	            if 100 <= r['id'] < 200 or r['id'] >= 300]; \
	    assert len(side) == 6, side; \
	    pre = {tuple(r['result']['probs']) for r in ok \
	           if r['id'] < 100}; \
	    post = [tuple(r['result']['probs']) for r in \
	            sorted((r for r in ok if 200 <= r['id'] < 300), \
	                   key=lambda r: r['id'])]; \
	    assert len(pre) == 1, 'pre-swap answers must agree'; \
	    assert post[-1] not in pre, 'swap never landed mid-stream'; \
	    print('swap-smoke stream OK (46/46 responses, both', \
	          'editions observed)')" && \
	grep -qE "\[tenancy\] swaps=1 evictions=[0-9]+" "$$L" && \
	echo "swap-smoke OK (2 tenants, zero drops, hot-swap mid-stream)"

# router smoke: boot a 2-replica lenet process fleet behind the router
# (serve.py --fleet), stream 24 JSONL requests through it while the
# chaos schedule SIGKILLs one replica at routed-request #5, and assert
# (1) zero lost requests — every request gets a result, the killed
# one(s) via failover — and (2) the grep-stable `[router] failovers=N`
# exit line: the `make check` fleet-availability gate
router-smoke:
	@mkdir -p logs; L="logs/router-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) -c "import json, numpy as np; \
	    [print(json.dumps({'id': i, 'model': 'lenet5', \
	     'input': np.zeros((32, 32, 1)).tolist()})) for i in range(24)]" \
	| $(PY) serve.py --fleet 2 -m lenet5 --buckets 1,4 \
	    --faults replica_kill@5 2> "$$L" \
	| $(PY) -c "import sys, json; \
	    rows = [json.loads(l) for l in sys.stdin if l.strip()]; \
	    ok = [r for r in rows if 'result' in r]; \
	    assert len(ok) == 24, (len(ok), rows[:3]); \
	    print('router-smoke stream OK (24/24 responses)')" && \
	grep -qE "\[router\] failovers=[1-9]" "$$L" && \
	grep -qE "deaths=1" "$$L" && \
	echo "router-smoke OK (replica SIGKILLed, failover line present)"

# observability smoke: train 2 synthetic lenet epochs with span tracing
# on, assert the exported Chrome trace carries the fetch/step/eval/
# checkpoint spans and attributes >= 95% of epoch wall time to named
# spans (tools/trace_summary.py), then GET /metrics from an in-process
# server and assert Prometheus exposition-format parse + intact /stats
# keys (tools/obs_smoke.py) — the `make check` observability gate
obs-smoke:
	@mkdir -p logs; L="logs/obs-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	rm -rf runs/obs-smoke; \
	$(PY) train.py -m lenet5 --platform cpu --epochs 2 \
		--synthetic-size 256 --batch-size 64 --steps-per-epoch 3 \
		--trace runs/obs-smoke/trace.json \
		--workdir runs/obs-smoke 2>&1 | tee "$$L" && \
	$(PY) tools/trace_summary.py runs/obs-smoke/trace.json \
		--assert-spans fetch,step,eval,checkpoint \
		--min-coverage 0.95 2>&1 | tee -a "$$L" && \
	$(PY) tools/obs_smoke.py 2>&1 | tee -a "$$L" && \
	echo "obs-smoke OK (trace attribution + /metrics exposition)"

# fleet observability smoke: boot a REAL 2-replica lenet process fleet
# with span spooling on, serve a short HTTP load, then assert the three
# distributed-obs contracts on live artifacts (tools/obs_fleet_smoke.py):
# federated /metrics sums child request counters exactly with
# per-replica labels, tools/trace_merge.py assembles the processes'
# spools into ONE Perfetto trace with >= 1 request's flow crossing the
# router and a replica row, and every process left a flight-recorder
# black box on SIGTERM — the `make check` fleet-observability gate
obs-fleet-smoke:
	@mkdir -p logs; L="logs/obs-fleet-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) tools/obs_fleet_smoke.py 2>&1 | tee "$$L" && \
	grep -q "obs-fleet-smoke OK" "$$L"

# input-pipeline smoke: drive the REAL record readers + prefetcher on a
# tiny self-built JPEG record set and assert the split pipeline's wire
# contract (ISSUE 7): uint8 crossing H2D, measured h2d_bytes_per_image
# >= 3.9x smaller than the f32 reference path, and host-vs-device
# augmentation parity at pinned tolerance on shared decisions — the
# `make check` input-wall gate (data/device_aug.py + data/loader.py)
feed-smoke:
	@mkdir -p logs; L="logs/feed-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) tools/feed_smoke.py 2>&1 | tee "$$L" && \
	grep -q "feed-smoke OK" "$$L"

# chaos smoke: a scripted fault schedule on the lenet synthetic config —
# one NaN step (epoch-2 batch 2), one corrupt checkpoint (the epoch-1
# save, i.e. the rollback's first restore candidate), and two transient
# data-read errors — must complete (exit 0) WITH the expected recovery
# counters in the log: the `make check` self-healing gate
# (deepvision_tpu/resilience/; drop --recover to watch it fail fast)
chaos-smoke:
	@mkdir -p logs; L="logs/chaos-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	rm -rf runs/chaos-smoke; \
	$(PY) train.py -m lenet5 --platform cpu --epochs 3 \
		--synthetic-size 512 --batch-size 64 --steps-per-epoch 6 \
		--recover --faults "nan@14,ckpt@1,io@8x2" \
		--workdir runs/chaos-smoke 2>&1 | tee "$$L" && \
	grep -q "rollbacks=1 ckpt_fallbacks=1 data_retries=2" "$$L" && \
	echo "chaos-smoke OK (recovered: rollback + ckpt fallback + retries)"

# distributed chaos smoke: a REAL 2-process jax.distributed CPU cluster
# (lenet synthetic) under the supervisor; host_preempt@8 SIGTERMs one
# host mid-job, the hosts commit a coordinated checkpoint (or exit
# after the epoch save when the barrier lands past the epoch end —
# both are coordinated), and the job relaunches on the surviving host
# with deterministic elastic resume. Asserts the grep-stable
# `[cluster] preemptions=1 resumes=1` exit line + exit 0: the
# `make check` multi-host-availability gate (resilience/cluster.py)
chaos-dist-smoke:
	@mkdir -p logs; L="logs/chaos-dist-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	rm -rf runs/chaos-dist-smoke; \
	$(PY) train_dist.py --supervise 2 --platform cpu \
		--barrier-lead 3 --barrier-timeout-s 60 \
		--straggler-after-s 30 --heartbeat-timeout-s 240 \
		--init-timeout-s 120 --faults host_preempt@14 \
		-m lenet5 --epochs 2 --synthetic-size 1024 --batch-size 64 \
		--steps-per-epoch 12 --workdir runs/chaos-dist-smoke 2>&1 | tee "$$L" && \
	grep -qE "\[cluster\] preemptions=1 resumes=1" "$$L" && \
	grep -q "hosts=1/2" "$$L" && \
	echo "chaos-dist-smoke OK (coordinated preempt + elastic resume on the survivor)"

# SDC chaos smoke (silent-failure defense, resilience/sentinel.py): a
# REAL 2-process CPU cluster with `--sentinel` audits every 8 steps
# and a SILENT sdc_grad corruption (one leaf scaled by 1+2^-10 — no
# NaN, no loss spike) injected on host 1 at run step 20. Asserts the
# full kill chain: cross-host fingerprint divergence at audit step 24
# (detection latency 4 <= K=8), generation teardown, ONE replay
# (= ceil(log2 2)) of the clean host re-deriving the ground truth,
# host 1 quarantined into the excluded-hosts ledger, elastic
# completion on the survivor, and the grep-stable `[sentinel]` exit
# line with trips=0 (the z-score must NOT fire on a silent fault —
# that is the audit's job)
chaos-sdc-smoke:
	@mkdir -p logs; L="logs/chaos-sdc-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	rm -rf runs/chaos-sdc-smoke; \
	$(PY) train_dist.py --supervise 2 --platform cpu \
		--barrier-lead 3 --barrier-timeout-s 60 \
		--straggler-after-s 60 --heartbeat-timeout-s 300 \
		--init-timeout-s 120 --faults sdc_grad@20:host1 \
		-m lenet5 --epochs 2 --synthetic-size 2048 --batch-size 64 \
		--steps-per-epoch 16 --sentinel --audit-every 8 \
		--workdir runs/chaos-sdc-smoke 2>&1 | tee "$$L" && \
	grep -q "fingerprints disagree at audit step 24" "$$L" && \
	grep -q "QUARANTINED host 1" "$$L" && \
	grep -q "gen 1: launching hosts \[0\]" "$$L" && \
	grep -qE "\[sentinel\] trips=0 audits=[0-9]+ divergences=1 quarantined=1" "$$L" && \
	echo "chaos-sdc-smoke OK (silent SDC caught <= K, host 1 quarantined by replay bisection, survivor completed)"

# ZeRO-1 smoke (ISSUE 17): a REAL 2-process jax.distributed CPU
# cluster on lenet5 — multi-host turns weight-update sharding ON by
# default (the grep on the [cluster] injection line proves that wiring)
# — against its --no-zero1 replicated twin on identical seeds and
# flags. Final train/val losses must agree at the pinned 1e-4 relative
# tolerance: the sharded optimizer is an arithmetic re-association of
# the same update, not a different algorithm. Then the lint tier proves
# the conversion is real: shardcheck --zero1 compiles lenet5 under the
# engine's specs and its worklist-empty note asserts every prescribed
# opt-state leaf is STORED sharded in the executable — the
# `make check` ZeRO-1 gate (core/sharding.py + train/state.py)
zero1-smoke:
	@mkdir -p logs; T="$$(date +%Y-%m-%d-%H-%M-%S)"; \
	L="logs/zero1-smoke-$$T.log"; R="logs/zero1-smoke-$$T-replicated.log"; \
	rm -rf runs/zero1-smoke; \
	$(PY) train_dist.py --supervise 2 --platform cpu \
		--barrier-lead 3 --barrier-timeout-s 60 \
		--straggler-after-s 60 --heartbeat-timeout-s 300 \
		--init-timeout-s 120 \
		-m lenet5 --epochs 1 --synthetic-size 512 --batch-size 64 \
		--steps-per-epoch 8 --workdir runs/zero1-smoke/sharded 2>&1 | tee "$$L" && \
	grep -q "ZeRO-1 weight-update sharding on by default" "$$L" && \
	$(PY) train_dist.py --supervise 2 --platform cpu \
		--barrier-lead 3 --barrier-timeout-s 60 \
		--straggler-after-s 60 --heartbeat-timeout-s 300 \
		--init-timeout-s 120 \
		-m lenet5 --epochs 1 --synthetic-size 512 --batch-size 64 \
		--steps-per-epoch 8 --no-zero1 \
		--workdir runs/zero1-smoke/replicated 2>&1 | tee "$$R" && \
	$(PY) -c "import re; \
	    last = lambda k, t: [float(m) for m in \
	        re.findall(k + r'=([0-9.eE+-]+)', t)][-1]; \
	    a = open('$$L').read(); b = open('$$R').read(); \
	    pairs = [(k, last(k, a), last(k, b)) \
	        for k in ('train_loss', 'val_loss')]; \
	    bad = [p for p in pairs \
	        if abs(p[1] - p[2]) > 1e-4 * max(abs(p[2]), 1e-9)]; \
	    assert not bad, bad; \
	    print(f'zero1-smoke parity OK (rel 1e-4): {pairs}')" && \
	$(PY) -m tools.jaxlint.shardcheck lenet5 --zero1 2>&1 | tee -a "$$L" && \
	grep -q "zero1 worklist empty" "$$L" && \
	echo "zero1-smoke OK (default-on 2-host ZeRO-1 matches the replicated twin; worklist empty)"

# runtime thread-sanitizer gate (tools/jaxlint/threadcheck.py): the
# static tier above proves lock DISCIPLINE from source; this proves the
# locks the serving/cluster tiers ACTUALLY take at runtime form an
# acyclic acquisition order. Two legs: (1) --smoke boots a real
# engine + 2-replica router lifecycle under instrumented locks and
# asserts acyclicity + exports the Perfetto-loadable lock graph JSON;
# (2) the engine/router/cluster lifecycle tests re-run with
# DVTPU_THREADCHECK=1 — every Lock/RLock the suite creates is
# sanitized, the session fixture in tests/conftest.py asserts the
# observed graph is acyclic at teardown and exports it beside the
# PR 11 spools (logs/lockgraph-tier1.json)
threadcheck-smoke:
	@mkdir -p logs; L="logs/threadcheck-smoke-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	rm -f logs/lockgraph-tier1.json; \
	$(PY) -m tools.jaxlint.threadcheck --smoke \
	    --export logs/lockgraph-smoke.json 2>&1 | tee "$$L" && \
	grep -q "threadcheck-smoke OK" "$$L" && \
	DVTPU_THREADCHECK=1 DVTPU_THREADCHECK_EXPORT=logs/lockgraph-tier1.json \
	$(PY) -m pytest tests/test_serve.py tests/test_router.py \
	    tests/test_cluster.py -x -q 2>&1 | tee -a "$$L" && \
	test -s logs/lockgraph-tier1.json && \
	echo "threadcheck-smoke OK (engine+router lifecycle + tier re-run acyclic)"

# the default CI path: hazard lint + serving smoke + chaos smoke +
# whole-zoo shape gate + full suite (the suite's own full-registry
# evalcheck test is deselected — `lint` above just ran the identical
# ~2-min gate via the CLI)
check: lint lint-comms serve-smoke pipeline-smoke router-smoke stream-smoke swap-smoke obs-smoke obs-fleet-smoke chaos-smoke chaos-dist-smoke chaos-sdc-smoke feed-smoke threadcheck-smoke precision-smoke zero1-smoke
	$(PY) -m pytest tests/ -x -q \
		--deselect tests/test_jaxlint.py::test_evalcheck_full_registry

bench:
	$(PY) bench.py

# the driver's multi-chip validation, runnable locally on 8 virtual CPUs
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

tensorboard:
	tensorboard --logdir $(WORKDIR) --port 6006

# offline metrics (mAP / PCK / exact top-1) against the latest checkpoint
eval_detection:
	$(PY) evaluate.py detection -m yolov3 --workdir $(WORKDIR)/yolov3 $(DATA_FLAG)

eval_pose:
	$(PY) evaluate.py pose -m hourglass104 --workdir $(WORKDIR)/hourglass104 $(DATA_FLAG)

eval_classification:
	$(PY) evaluate.py classification -m resnet50 --workdir $(WORKDIR)/resnet50 $(DATA_FLAG)

# loss/accuracy curves re-plotted from inside the checkpoint
curves_%:
	$(PY) predict.py curves --workdir $(WORKDIR)/$* -o $*-curves.png

# reference checkpoint -> Orbax (CKPT=path/to/ref.pt MODEL=resnet50)
convert:
	$(PY) -m deepvision_tpu.convert $(CKPT) -m $(MODEL) -o $(WORKDIR)

# synthetic task-metric gates: train to convergence on the hermetic
# synthetic sets, then score with the real eval metrics (mAP / PCK).
# Data sizes follow the measured r3/r4 scaling curve (mAP 0.67 @ 1024,
# 0.856 @ 2048, 0.880 @ 4096, crossed 0.9 @ 8192+flip — EVIDENCE.md);
# --keep-best retains the val-loss-ranked checkpoints so the peak epoch
# can be scored with `evaluate.py --epoch` after the overfit knee
# every gate tees train + eval into ONE timestamped file under logs/
# permanently: gate numbers must exist in driver-verifiable committed
# logs (VERDICT r4 weak #2). Single recipe line so the timestamp is
# captured once and pipefail + && propagate a crashed train.
gate_detection:
	@mkdir -p logs; L="logs/gate_detection-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) train.py -m yolov3 --num-classes 5 --lr 1e-3 --batch-size 32 \
		--epochs 50 --synthetic-size 8192 --keep-best \
		--workdir $(WORKDIR)/gates 2>&1 | tee "$$L" && \
	$(PY) evaluate.py detection -m yolov3 --num-classes 5 \
		--workdir $(WORKDIR)/gates/yolov3 2>&1 | tee -a "$$L"

# the 16384-image scaling-curve point (~4h on one v5e chip): supervised
# restart loop around the same recipe at 2x data, tools/run_yolo_16384.sh
gate_detection_16384:
	bash tools/run_yolo_16384.sh

# classification gate (VERDICT r4 #3): train resnet34 on the hermetic
# synthetic classification set, score the held-out slice through
# evaluate.py's exact masked full-set eval. --num-classes 5: the
# synthetic class signal aliases past 7 classes (data/synthetic.py)
# MODEL=resnet50 runs the same recipe on the north-star architecture
# (both scored held-out top-1 1.0 on-chip, EVIDENCE.md r5)
gate_classification: MODEL ?= resnet34
gate_classification:
	@mkdir -p logs; L="logs/gate_classification_$(MODEL)-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) train.py -m $(MODEL) --num-classes 5 --synthetic-size 4096 \
		--batch-size 64 --epochs 6 --lr 0.05 --keep-best \
		--workdir $(WORKDIR)/gates 2>&1 | tee "$$L" && \
	$(PY) evaluate.py classification -m $(MODEL) --num-classes 5 \
		--synthetic-size 4096 --train-batch-size 64 \
		--workdir $(WORKDIR)/gates/$(MODEL) 2>&1 | tee -a "$$L"

# two-phase recipe from EVIDENCE.md r4: the plateau scheduler never
# fires on this task (val micro-improves each epoch), so the CenterNet-
# paper x10 lr drop is applied manually via resume
gate_centernet:
	@mkdir -p logs; L="logs/gate_centernet-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) train.py -m centernet --num-classes 5 --epochs 50 --keep-best \
		--synthetic-size 2048 --stall-timeout 420 \
		--workdir $(WORKDIR)/gates 2>&1 | tee "$$L" && \
	$(PY) train.py -m centernet --num-classes 5 --epochs 65 --lr 1e-4 \
		--synthetic-size 2048 --keep-best --stall-timeout 420 \
		--workdir $(WORKDIR)/gates --resume 2>&1 | tee -a "$$L" && \
	$(PY) evaluate.py detection -m centernet --num-classes 5 --size 128 \
		--workdir $(WORKDIR)/gates/centernet 2>&1 | tee -a "$$L"

gate_gan:
	@mkdir -p logs; L="logs/gate_gan-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) train.py -m cyclegan --synthetic-size 256 --epochs 40 \
		--workdir $(WORKDIR)/gates 2>&1 | tee "$$L" && \
	$(PY) evaluate.py gan -m cyclegan \
		--workdir $(WORKDIR)/gates/cyclegan 2>&1 | tee -a "$$L" && \
	$(PY) train.py -m dcgan --synthetic-size 2048 --epochs 20 \
		--workdir $(WORKDIR)/gates 2>&1 | tee -a "$$L" && \
	$(PY) evaluate.py gan -m dcgan \
		--workdir $(WORKDIR)/gates/dcgan 2>&1 | tee -a "$$L"

# --num-joints 3: the synthetic set encodes one joint per color channel
# (data/pose.synthetic_pose); at the MPII default of 16 the channel
# assignment j%3 is ambiguous and no model can score high PCK.
# 1024 images + lr 1e-3: 256 images generalization-capped PCK at ~0.5
# (37% gross misses on held-out draws) and the config lr of 1e-4
# converged 5x slower (EVIDENCE.md r4)
gate_pose:
	@mkdir -p logs; L="logs/gate_pose-$$(date +%Y-%m-%d-%H-%M-%S).log"; \
	$(PY) train.py -m hourglass104 --num-joints 3 --epochs 120 \
		--synthetic-size 1024 --lr 1e-3 --keep-best \
		--workdir $(WORKDIR)/gates 2>&1 | tee "$$L" && \
	$(PY) evaluate.py pose -m hourglass104 --num-joints 3 \
		--workdir $(WORKDIR)/gates/hourglass104 2>&1 | tee -a "$$L"

# one-command real-data rehearsal: generated JPEG folder -> TFRecords ->
# raw-frame shards -> train -> evaluate -> StableHLO export, plus the
# reference-checkpoint converter leg — the full ImageNet-day operator
# path on hermetic data (VERDICT r3 missing #1)
rehearsal:
	$(PY) tools/rehearsal.py --workdir /tmp/dvt_rehearsal
	$(PY) -m pytest tests/test_convert.py::test_converter_cli_end_to_end -q

find-python:
	ps -ef | grep python

list-models:
	@echo $(MODELS)

.PHONY: test smoke lint lint-threads lint-ir lint-comms bf16-ready precision-smoke zero1-smoke check serve-smoke pipeline-smoke router-smoke stream-smoke swap-smoke obs-smoke obs-fleet-smoke feed-smoke chaos-dist-smoke chaos-sdc-smoke threadcheck-smoke bench dryrun tensorboard find-python list-models rehearsal
