#!/usr/bin/env python
"""Serving CLI — the batched inference engine behind two zero-dep
surfaces (``deepvision_tpu/serve/``):

    # stdin-JSONL (default): one JSON request per line, responses to stdout
    serve.py -m lenet5=runs/lenet5
    {"id": 1, "model": "lenet5", "input": [[...32x32x1 floats...]]}
    -> {"id": 1, "model": "lenet5", "result": {...}, "ms": 4.2}

    # HTTP (stdlib http.server, no new deps)
    serve.py --http 8080 -m resnet50=runs/resnet50 -m yolov3=runs/yolov3
    POST /v1/predict   {"model": "resnet50", "input": [[...]]}  -> result
    GET  /stats        engine telemetry + cache + queue snapshot (JSON)
    GET  /metrics      Prometheus text exposition from the obs registry
                       (serve_* counters/quantiles + mem_* gauges)
    GET  /healthz      "ok" once warmup completed

    # serve a StableHLO artifact from predict.py export
    serve.py --artifact lenet5=lenet5.stablehlo

    # serving FLEET: router front tier over N child-process replicas
    # (health-gated balancing, failover, circuit breaker, autoscaling)
    serve.py --fleet 2 -m lenet5 --http 8080
    serve.py --fleet 2 --fleet-max 4 --slo lenet5=0.5 -m lenet5

``-m name[=workdir]`` is repeatable (multi-model host); every model's
(bucket) executables compile at startup, so the first request is as
fast as the thousandth. Saturation returns 429/shed responses with a
``retry_after`` hint instead of unbounded queueing.

In ``--fleet N`` mode this process never touches jax: it spawns N
copies of itself (``serve.py --http 0 --port-file ...``) as replicas
and routes over them (``deepvision_tpu/serve/router.py``). ``--faults``
then arms the ROUTER's chaos sites (``replica_kill`` / ``replica_slow``
— a scheduled kill is a real SIGKILL), and the exit path prints the
grep-stable ``[router] failovers=N ...`` line the router smoke gate
asserts.
"""

from __future__ import annotations

import argparse
import json
import sys
from concurrent.futures import TimeoutError as _FutureTimeout
from pathlib import Path

import numpy as np

from deepvision_tpu.serve.admission import ShedError


def _parse_spec(spec: str) -> tuple[str, str | None]:
    name, _, workdir = spec.partition("=")
    return name, (workdir or None)


def _parse_tenant_map(specs, *, flag: str, cast):
    """``NAME=VALUE`` repeatable flags -> dict (tenant isolation maps:
    ``--tenant-quota lenet5=8``, ``--slo-class lenet5=gold``)."""
    out = {}
    for spec in specs or []:
        name, sep, val = spec.partition("=")
        if not sep or not name:
            sys.exit(f"bad {flag} spec {spec!r}; want NAME=VALUE")
        try:
            out[name] = cast(val)
        except ValueError as e:
            sys.exit(f"bad {flag} spec {spec!r}: {e}")
    return out or None


def build_engine(args):
    from deepvision_tpu.serve import InferenceEngine, from_stablehlo
    from deepvision_tpu.serve.models import load_served

    import contextlib

    models = []
    # restore chatter ("restored epoch N" / "no checkpoint found") goes
    # to stderr: stdout is the JSONL response stream in --stdin mode
    with contextlib.redirect_stdout(sys.stderr):
        for spec in args.model or []:
            name, workdir = _parse_spec(spec)
            models.append(load_served(
                name, workdir, num_classes=args.num_classes,
                top_k=args.top, score_thresh=args.score))
        for spec in args.artifact or []:
            name, path = _parse_spec(spec)
            if path is None:
                name, path = None, name
            models.append(from_stablehlo(path, name=name,
                                         top_k=args.top))
    if not models and not getattr(args, "track", None):
        sys.exit("no models: pass -m NAME[=WORKDIR], --artifact, "
                 "or --track")
    buckets = tuple(int(b) for b in args.buckets.split(","))
    mesh, buckets = _serving_mesh(buckets)
    pipelines = []
    if getattr(args, "pipelines", None):
        from deepvision_tpu.serve.pipeline import (
            Pipeline,
            load_pipeline_specs,
        )

        by_name = {m.name: m for m in models}
        for path in args.pipelines:
            for spec in load_pipeline_specs(path):
                # validates structure + every DAG edge's avals here,
                # before any compile — a bad spec kills startup, not a
                # request
                pipelines.append(Pipeline(spec, by_name))
    injector = None
    if args.faults:
        from deepvision_tpu.resilience import FaultInjector

        injector = FaultInjector(args.faults, seed=args.fault_seed)
        print(f"fault injection armed: {args.faults!r}", file=sys.stderr)
    if getattr(args, "track", None):
        # stateful tracking stream: --track MODEL[:K] serves a
        # TrackingPipeline named "track" over detect-model MODEL
        # ("synth" builds the weight-free synthetic detector). Session
        # state is device-resident per stream; crash-safe snapshots
        # land under --session-dir so a respawned/surviving replica
        # restores migrated streams.
        import tempfile

        from deepvision_tpu.serve.sessions import (
            SessionStore,
            TrackingPipeline,
            synthetic_detector,
        )

        det_name, _, k = args.track.partition(":")
        by_name = {m.name: m for m in models}
        if det_name in by_name:
            det = by_name[det_name]
        elif det_name == "synth":
            det = synthetic_detector()
            models.append(det)
        else:
            sys.exit(f"--track {args.track!r}: no model named "
                     f"{det_name!r} (pass -m, or use 'synth')")
        sdir = args.session_dir or tempfile.mkdtemp(
            prefix="dvtpu-sessions-")
        print(f"session snapshots -> {sdir} "
              f"(cadence {args.snapshot_every} frames)", file=sys.stderr)
        store = SessionStore(
            capacity=args.session_capacity, ttl_s=args.session_ttl_s,
            snapshot_dir=sdir, snapshot_every=args.snapshot_every,
            injector=injector)
        models.append(TrackingPipeline(
            "track", det, store,
            detect_every=int(k) if k else 4))
    print(f"serving {[m.name for m in models]}"
          f"{' pipelines ' + str([p.name for p in pipelines]) if pipelines else ''}"
          f" buckets={buckets} on {mesh.devices.size} device(s); "
          "compiling...", file=sys.stderr)
    engine = InferenceEngine(
        models, mesh=mesh, buckets=buckets, max_queue=args.max_queue,
        per_model_limit=args.per_model_limit,
        batch_window_s=args.batch_window_ms / 1e3,
        fault_injector=injector,
        pipelines=pipelines,
        # pipelines warm end-to-end, so the cache can be FROZEN: any
        # later miss (a hidden request-time compile) raises instead of
        # silently costing tail latency
        freeze_cache=bool(pipelines),
        store=getattr(args, "store", None),
        residency_bytes=(int(args.residency_mb * 1024 * 1024)
                         if getattr(args, "residency_mb", None)
                         else None),
        tenant_quota=_parse_tenant_map(
            getattr(args, "tenant_quota", None),
            flag="--tenant-quota", cast=int),
        slo_class=_parse_tenant_map(
            getattr(args, "slo_class", None),
            flag="--slo-class", cast=str),
    )
    stats = engine.stats()
    from_store = stats.get("warmed_from_store") or []
    print(f"warmup done in {engine.warmup_s}s "
          f"({stats['cache']['entries']} executables"
          + (f", {len(from_store)} from store" if from_store else "")
          + ")",
          file=sys.stderr)
    return engine


def _serving_mesh(buckets: tuple[int, ...]):
    """-> (mesh, ladder) with all devices on the data axis.

    Batches shard over the data axis, so every bucket must divide by
    the device count — on a multi-chip host the requested ladder is
    ADAPTED rather than the mesh degraded: buckets below the device
    count are raised to it, indivisible ones are rounded up to the
    next multiple (the default 1/4/16/64 on 8 chips becomes 8/16/64).
    Only a ladder that cannot be adapted (no devices?) falls back to a
    single-device mesh."""
    import jax

    from deepvision_tpu.core.mesh import create_mesh

    n = len(jax.devices())
    if n > 1:
        adapted = tuple(sorted({((b + n - 1) // n) * n for b in buckets}))
        if adapted != buckets:
            print(f"ladder {buckets} adapted to {adapted} for the "
                  f"{n}-device data axis", file=sys.stderr)
        return create_mesh(n, 1), adapted
    return create_mesh(1, 1), buckets


def build_fleet(args):
    """Router front tier over ``args.fleet`` child-process replicas —
    no jax in this process; each replica is this same CLI in
    single-engine HTTP mode on an ephemeral port."""
    from deepvision_tpu.serve.replica import ProcessReplica, replica_argv
    from deepvision_tpu.serve.router import AutoscaleConfig, FleetRouter

    if not (args.model or args.artifact or args.track):
        sys.exit("no models: pass -m NAME[=WORKDIR], --artifact, "
                 "or --track")
    session_dir = None
    if args.track:
        # replicas must SHARE the snapshot dir: on a replica death the
        # router re-pins orphaned streams to a survivor, which restores
        # each stream's slate from the newest snapshot the dead replica
        # wrote here
        import tempfile

        session_dir = args.session_dir or tempfile.mkdtemp(
            prefix="dvtpu-sessions-")
        print(f"session snapshots (shared across replicas) -> "
              f"{session_dir}", file=sys.stderr)
    child_argv = replica_argv(
        args.model or [], artifact_specs=args.artifact or [],
        buckets=args.buckets,
        # shared AOT store: replica #1 traces and populates it, every
        # later (re)spawn warms from disk — the respawn compile storm
        # PR 6 measured is paid once per fleet, not once per process
        store=args.store,
        extra=(["--num-classes", str(args.num_classes)]
               if args.num_classes is not None else [])
        + ["--top", str(args.top), "--score", str(args.score),
           "--max-queue", str(args.max_queue),
           "--batch-window-ms", str(args.batch_window_ms),
           "--timeout-s", str(args.timeout_s)]
        + [a for spec in (args.tenant_quota or [])
           for a in ("--tenant-quota", spec)]
        + [a for spec in (args.slo_class or [])
           for a in ("--slo-class", spec)]
        + (["--residency-mb", str(args.residency_mb)]
           if args.residency_mb else [])
        + [a for path in (args.pipelines or [])
           for a in ("--pipelines", path)]
        + (["--track", args.track, "--session-dir", session_dir,
            "--session-capacity", str(args.session_capacity),
            "--session-ttl-s", str(args.session_ttl_s),
            "--snapshot-every", str(args.snapshot_every)]
           if args.track else [])
        + (["--trace-spool", args.trace_spool]
           if args.trace_spool else []))

    def factory(sid: str):
        # each replica spools/dumps under its slot id, so the merged
        # fleet trace names its pid rows r1/r2/...
        return ProcessReplica(sid, child_argv + ["--obs-role", sid])

    injector = None
    if args.faults:
        from deepvision_tpu.resilience import FaultInjector

        injector = FaultInjector(args.faults, seed=args.fault_seed)
        print(f"fault injection armed (router sites): {args.faults!r}",
              file=sys.stderr)
    slo = {}
    for spec in args.slo or []:
        name, _, sec = spec.partition("=")
        try:
            slo[name] = float(sec)
        except ValueError:
            sys.exit(f"bad --slo spec {spec!r}; want NAME=SECONDS")
    fleet_max = args.fleet_max or args.fleet
    autoscale = None
    if fleet_max > args.fleet:
        autoscale = AutoscaleConfig(min_replicas=args.fleet,
                                    max_replicas=fleet_max)
    models = [(_parse_spec(s)[0]) for s in args.model or []]
    if args.pipelines:
        # pipeline NAMES are routable like models; spec parsing is pure
        # json (the router process never imports jax — each replica
        # builds/validates/warms its own DAGs)
        from deepvision_tpu.serve.pipeline import load_pipeline_specs

        models += [spec.name for path in args.pipelines
                   for spec in load_pipeline_specs(path)]
    if args.track:
        # the tracking pipeline (and, for "synth", its generated
        # detector) are routable names each replica builds itself
        models.append("track")
        det_name = args.track.partition(":")[0]
        if det_name not in models:
            models.append(det_name)
    print(f"starting fleet of {args.fleet} replica(s) "
          f"({models or args.artifact}); replicas compile in "
          "parallel...", file=sys.stderr)
    router = FleetRouter(
        factory, replicas=args.fleet, models=models, slo=slo or None,
        default_deadline_s=args.timeout_s, max_queue=args.max_queue,
        per_model_limit=args.per_model_limit, autoscale=autoscale,
        hedge_after_s=args.hedge_after, fault_injector=injector,
        session_replay_window=args.session_replay_window,
        # tenant isolation at the FLEET front door too: a noisy tenant
        # sheds here before it can crowd any replica's queue
        tenant_quota=_parse_tenant_map(
            args.tenant_quota, flag="--tenant-quota", cast=int),
        slo_class=_parse_tenant_map(
            args.slo_class, flag="--slo-class", cast=str),
    )
    print(f"fleet up: {router.health()}", file=sys.stderr)
    return router


def _setup_obs(args, role: str):
    """Wire this process's distributed-observability surfaces: label
    the tracer, attach a span spool when ``--trace-spool`` (or the
    ``DVTPU_TRACE_SPOOL`` env a parent exported) names a directory,
    and install the always-on flight recorder with a dump-on-SIGTERM
    handler — so a drained/preempted replica leaves its black box next
    to its spool. Returns the spool (or None)."""
    import os
    import signal

    from deepvision_tpu.obs.distributed import (
        ENV_SPOOL,
        SpanSpool,
        enable_spool_from_env,
        flight_dump,
        install_flight_recorder,
    )
    from deepvision_tpu.obs.trace import get_tracer

    get_tracer().set_labels(role=role)
    if args.trace_spool:
        spool = SpanSpool(args.trace_spool, role=role)
    else:
        spool = enable_spool_from_env(role=role)
    obs_dir = args.trace_spool or os.environ.get(ENV_SPOOL)
    install_flight_recorder(obs_dir, meta={"role": role})

    def _on_sigterm(sig, frame):
        # black box first, then a GRACEFUL exit: SystemExit propagates
        # out of serve_forever/stdin so the finally blocks run — the
        # engine/router closes, child replicas are stopped (a fleet
        # parent dying abruptly would orphan them), spools flush
        flight_dump(f"signal-{sig}")
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _on_sigterm)
    return spool


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


# ---------------------------------------------------------- stdin-JSONL


def run_stdin(engine, args, stdin=None, stdout=None):
    """One JSON request per line; responses (in submission order) to
    stdout. Requests keep flowing while earlier ones execute, so the
    dispatcher sees real micro-batches even from a pipe.

    Control lines ride the same stream: ``{"control": "swap",
    "model": NAME, "perturb": F | "workdir": DIR}`` hot-swaps a
    tenant's weights on a background thread while data lines keep
    flowing — the swap-smoke drill's zero-drop evidence. Control
    lines produce stderr chatter only (stdout stays a pure
    data-response stream); the ``[tenancy]`` exit line carries the
    swap count."""
    import contextlib
    import threading
    import time

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout

    pending: list[tuple[object, object, float]] = []  # (id, future, t0)
    control_threads: list[threading.Thread] = []

    def start_control(req: dict) -> None:
        if req.get("control") != "swap":
            print(f"[tenancy] unknown control {req.get('control')!r}",
                  file=sys.stderr, flush=True)
            return
        hot_swap = getattr(engine, "hot_swap", None)
        if hot_swap is None:
            print("[tenancy] swap control needs a single-engine host "
                  "(fleet routers don't own weights)",
                  file=sys.stderr, flush=True)
            return

        def _do_swap():
            kw = {k: req[k] for k in ("workdir", "perturb")
                  if k in req}
            try:
                # checkpoint-restore chatter must not pollute the
                # stdout data stream
                with contextlib.redirect_stdout(sys.stderr):
                    hot_swap(req["model"], **kw)
            except Exception as e:
                print(f"[tenancy] swap {req.get('model')!r} failed: "
                      f"{type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)

        t = threading.Thread(target=_do_swap, daemon=True,
                             name="tenancy-swap")
        t.start()
        control_threads.append(t)

    def emit(rid, fut, t0):
        try:
            result = fut.result(timeout=args.timeout_s + 1.0)
            line = {"id": rid, "result": _jsonable(result),
                    "ms": round((time.perf_counter() - t0) * 1e3, 2)}
        except ShedError as e:
            # async sheds (the router's circuit-open / all-replicas-
            # draining path) carry the same retry hint a synchronous
            # admission shed does
            line = {"id": rid, "error": str(e),
                    "retry_after": e.retry_after_s}
        except Exception as e:
            line = {"id": rid, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(line), file=stdout, flush=True)

    for raw in stdin:
        raw = raw.strip()
        if not raw:
            continue
        try:
            req = json.loads(raw)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
            if "control" in req:
                start_control(req)
                continue
            x = np.asarray(req["input"], np.float32)
            # stateful streams: session (stream id) + seq (frame no.)
            seq = req.get("seq")
            seq = int(seq) if seq is not None else None
        except (ValueError, KeyError, TypeError) as e:
            print(json.dumps({"error": f"bad request: {e}"}),
                  file=stdout, flush=True)
            continue
        rid = req.get("id")
        t0 = time.perf_counter()
        try:
            # a pipeline is addressed like a model ({"pipeline": name}
            # is sugar for {"model": name}) — same queue, same engine
            fut = engine.submit(x, model=(req.get("model")
                                          or req.get("pipeline")),
                                timeout_s=args.timeout_s,
                                trace=req.get("trace"),
                                session=req.get("session"), seq=seq)
        except ShedError as e:
            print(json.dumps({"id": rid, "error": str(e),
                              "retry_after": e.retry_after_s}),
                  file=stdout, flush=True)
            continue
        except (ValueError, RuntimeError) as e:
            print(json.dumps({"id": rid, "error": str(e)}),
                  file=stdout, flush=True)
            continue
        pending.append((rid, fut, t0))
        # bounded in-flight window: keep ~2 ladders' worth queued so
        # batching happens, without unbounded memory on long streams
        while len(pending) > 2 * max(engine.buckets):
            emit(*pending.pop(0))
    for item in pending:
        emit(*item)
    for t in control_threads:
        # a swap started near EOF still completes (and is counted in
        # the [tenancy] exit line) before the engine closes
        t.join(timeout=args.timeout_s)


# ----------------------------------------------------------------- HTTP


def make_handler(engine, args):
    """BaseHTTPRequestHandler subclass bound to ``engine`` — factored
    out of :func:`run_http` so tests can mount it on an ephemeral-port
    server."""
    import http.server

    from deepvision_tpu.serve import ShedError

    # static after build_engine: resolved once so the (load-balancer-
    # hammered) /healthz probe never pays a full stats() snapshot
    models = engine.stats()["models"]

    from deepvision_tpu.obs.distributed import TRACE_HEADER

    class Handler(http.server.BaseHTTPRequestHandler):
        # HTTP/1.1: keep-alive connections, so a router/load-balancer
        # client pays connection setup (and this server a handler
        # thread) once per CLIENT, not once per request — every
        # response path below sets Content-Length, which 1.1 requires
        protocol_version = "HTTP/1.1"

        # quiet per-request logging; telemetry is the observability
        def log_message(self, *a):
            pass

        def _send(self, code: int, payload: dict,
                  headers: dict | None = None):
            self._send_text(code, json.dumps(payload),
                            "application/json", headers)

        def _send_text(self, code: int, body: str, content_type: str,
                       headers: dict | None = None) -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                # degraded (503) while the dispatcher supervisor sits in
                # a post-crash backoff: load balancers should drain this
                # replica, not route fresh traffic into the restart.
                # The 503 carries Retry-After (rest of the backoff
                # window) so balancers re-probe on schedule — the same
                # hint contract the 429 shed path has always had.
                h = engine.health()
                h["models"] = models
                if h["status"] == "ok":
                    self._send(200, h)
                else:
                    import math

                    ra = max(1, math.ceil(h.get("retry_after_s", 1.0)))
                    self._send(503, h, {"Retry-After": str(ra)})
            elif self.path == "/stats":
                # /stats reads through the obs-backed telemetry
                # snapshot: every histogram's (count, total, samples)
                # triple is read under the metric's own lock, so a
                # scrape landing mid-record can never see a torn
                # count/total pair — the pre-obs snapshot only got that
                # guarantee via the engine lock the handler didn't hold
                self._send(200, engine.stats())
            elif self.path == "/metrics":
                # a fleet router renders the FEDERATED surface (its own
                # router_* families + every replica's serve_* families
                # with {replica=...} labels and exact counter sums); a
                # single engine renders the process registry as before
                render = getattr(engine, "render_metrics", None)
                self._send_text(200,
                                render() if render is not None
                                else _render_metrics(),
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
            elif self.path == "/metrics.json":
                # the typed registry dump (histogram reservoirs
                # included): what a fleet router scrapes from each
                # replica to federate exactly instead of re-parsing
                # lossy quantile text
                from deepvision_tpu.obs.metrics import default_registry

                self._send(200, default_registry().dump())
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            # POST /v1/pipeline/<name> addresses a served DAG by URL;
            # the engine serves pipelines through the same submit path
            # as models, so past this point the request is ordinary
            pipeline = None
            if self.path == "/v1/swap":
                self._do_swap()
                return
            if self.path.startswith("/v1/pipeline/"):
                pipeline = self.path[len("/v1/pipeline/"):]
                if not pipeline:
                    self._send(404, {"error": "not found"})
                    return
            elif self.path not in ("/v1/predict", "/predict"):
                self._send(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                if not isinstance(req, dict):
                    raise ValueError("request must be a JSON object")
                x = _decode_input(req)
                # stateful streams (the fleet router forwards these on
                # its replica hop): session = stream id, seq = frame
                session = req.get("session")
                seq = req.get("seq")
                seq = int(seq) if seq is not None else None
                # per-request deadline (the fleet router forwards its
                # remaining budget here); the CLI blanket is a CEILING
                timeout_s = args.timeout_s
                if "timeout_s" in req:
                    timeout_s = min(float(req["timeout_s"]),
                                    args.timeout_s)
                    if timeout_s <= 0:
                        raise ValueError(
                            f"timeout_s must be > 0, got {timeout_s}")
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"bad request: {e}"})
                return
            # distributed trace id: the router hop carries it as the
            # X-DVTPU-Trace header (the JSONL surface as a "trace"
            # field) — the engine stamps its queue/device/postprocess
            # spans with it so the merged fleet trace links this
            # request across processes
            trace = self.headers.get(TRACE_HEADER) or req.get("trace")
            try:
                fut = engine.submit(
                    x,
                    model=(pipeline or req.get("model")
                           or req.get("pipeline")),
                    timeout_s=timeout_s, trace=trace,
                    session=session, seq=seq)
                result = fut.result(timeout=timeout_s + 1.0)
            except ShedError as e:
                self._send(429, {"error": str(e),
                                 "retry_after": e.retry_after_s},
                           {"Retry-After": str(e.retry_after_s)})
                return
            # concurrent.futures.TimeoutError (the result-wait timeout)
            # only aliases builtin TimeoutError from Python 3.11; catch
            # both so a 3.10 wait-expiry is a 504, not a crashed handler
            except (TimeoutError, _FutureTimeout) as e:
                self._send(504, {"error": f"deadline expired: {e}"})
                return
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            except RuntimeError as e:
                # server-side failure (dispatcher crash, engine closed,
                # exhausted fleet failover): 500, NOT 400 — a 400 tells
                # clients (and the fleet router, which maps it to a
                # non-retryable client error) never to retry, burying
                # exactly the fault class failover exists to absorb
                self._send(500, {"error": str(e)})
                return
            self._send(200, {"result": _jsonable(result)})

        def _do_swap(self):
            """POST /v1/swap {"model": NAME, "perturb": F |
            "workdir": DIR}: zero-drop weight hot-swap. Synchronous —
            the 200 means the new ladder is compiled, installed, and
            flipped; in-flight requests drained on the old weights.
            Other handler threads keep serving throughout (the flip
            happens between dispatcher batches, not here)."""
            hot_swap = getattr(engine, "hot_swap", None)
            if hot_swap is None:
                self._send(404, {"error": "swap needs a single-engine "
                                 "replica (fleet routers don't own "
                                 "weights)"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                if not isinstance(req, dict) or "model" not in req:
                    raise ValueError("need a JSON object with 'model'")
                kw = {k: req[k] for k in ("workdir", "perturb")
                      if k in req}
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"bad request: {e}"})
                return
            import contextlib

            try:
                with contextlib.redirect_stdout(sys.stderr):
                    result = hot_swap(req["model"], **kw)
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, {"result": result})

    return Handler


def _decode_input(req: dict) -> np.ndarray:
    """Request payload -> input array. Two wire formats:

    - ``"input"``: nested JSON float lists (human-typable, the
      original format);
    - ``"input_b64"`` + ``"shape"`` [+ ``"dtype"``, default float32]:
      base64 of the raw little-endian array bytes. ~20x cheaper to
      encode/decode than float lists on both ends — the format the
      fleet router uses, where per-request JSON cost is fleet-wide
      routing capacity.
    """
    if "input_b64" in req:
        import base64

        dtype = np.dtype(req.get("dtype", "float32"))
        raw = base64.b64decode(req["input_b64"], validate=True)
        x = np.frombuffer(raw, dtype=dtype).reshape(req["shape"])
        return np.ascontiguousarray(x, np.float32)
    return np.asarray(req["input"], np.float32)


def _render_metrics() -> str:
    """Prometheus text for GET /metrics: the process obs registry
    (serve_* counters + latency quantiles, plus whatever else this
    process registered), with the mem_* device gauges refreshed per
    scrape (one memory_stats() read per device; no-op on CPU)."""
    from deepvision_tpu.obs.metrics import default_registry
    from deepvision_tpu.obs.profiler import sample_memory_gauges

    sample_memory_gauges()
    return default_registry().render_prometheus()


def _make_server(addr, handler):
    """ThreadingHTTPServer tuned for fleet traffic: a deep accept
    backlog (the default 5 drops SYNs under a router's connection
    burst — each drop is a 1-3s TCP retransmit stall that reads as a
    'slow replica'), and daemon handler threads so shutdown never
    hangs on an idle keep-alive connection."""
    import http.server

    srv = http.server.ThreadingHTTPServer(addr, handler,
                                          bind_and_activate=False)
    srv.request_queue_size = 128
    srv.daemon_threads = True
    srv.server_bind()
    srv.server_activate()
    return srv


def run_http(engine, args):
    server = _make_server(("", args.http), make_handler(engine, args))
    port = server.server_address[1]
    if getattr(args, "port_file", None):
        # atomic write: a fleet router polls this file to find the
        # ephemeral port (--http 0), and must never read a torn value
        import os
        import tempfile

        fd, tmp = tempfile.mkstemp(
            dir=str(Path(args.port_file).parent) or ".")
        with os.fdopen(fd, "w") as f:
            f.write(str(port))
        os.replace(tmp, args.port_file)
    print(f"listening on :{port} "
          f"(POST /v1/predict, GET /stats, GET /metrics, GET /healthz)",
          file=sys.stderr)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--model", action="append",
                   help="NAME[=WORKDIR], repeatable (multi-model host)")
    p.add_argument("--artifact", action="append",
                   help="[NAME=]PATH to a StableHLO export, repeatable")
    p.add_argument("--pipelines", action="append", metavar="FILE",
                   help="JSON pipeline spec file (one spec, a list, or "
                        "{'pipelines': [...]}), repeatable; each DAG is "
                        "validated (acyclic, aval-compatible, ladder-"
                        "divisible) and warmed end-to-end at startup, "
                        "then served via {'pipeline': NAME} on the "
                        "JSONL surface or POST /v1/pipeline/NAME")
    p.add_argument("--http", type=int, default=None,
                   help="HTTP port (default: stdin-JSONL mode); 0 binds "
                        "an ephemeral port (see --port-file)")
    p.add_argument("--port-file", default=None,
                   help="write the actually-bound HTTP port here "
                        "(atomic); how a fleet router finds its "
                        "ephemeral-port replicas")
    p.add_argument("--fleet", type=int, default=None,
                   help="run a ROUTER over this many child-process "
                        "replicas instead of one in-process engine")
    p.add_argument("--fleet-max", type=int, default=None,
                   help="autoscaler ceiling (default: --fleet, i.e. "
                        "autoscaling off); the metric-driven autoscaler "
                        "adds/drains replicas between --fleet and this")
    p.add_argument("--slo", action="append",
                   help="NAME=SECONDS per-model p95 deadline budget, "
                        "repeatable; feeds SLO-aware admission and the "
                        "default request deadline (fleet mode)")
    p.add_argument("--hedge-after", type=float, default=None,
                   help="fleet mode: launch a duplicate attempt on a "
                        "second replica when the primary hasn't "
                        "answered within this many seconds (first "
                        "response wins, exactly once); off by default "
                        "— hedging trades duplicate work for tail "
                        "latency")
    p.add_argument("--buckets", default="1,4,16,64",
                   help="batch bucket ladder (comma-separated)")
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--per-model-limit", type=int, default=None)
    p.add_argument("--batch-window-ms", type=float, default=2.0,
                   help="wait this long for a bucket to fill before "
                        "running a padded partial batch")
    p.add_argument("--timeout-s", type=float, default=30.0,
                   help="per-request deadline")
    p.add_argument("--track", default=None, metavar="MODEL[:K]",
                   help="serve a stateful tracking-by-detection stream "
                        "named 'track' over detect-model MODEL "
                        "('synth' builds a weight-free synthetic "
                        "detector); the detector runs every K-th frame "
                        "(default 4), frames between run the compiled "
                        "advance program on the stream's device-"
                        "resident slate. Requests address it with "
                        "{'model': 'track', 'session': ID, 'seq': N}")
    p.add_argument("--session-dir", default=None, metavar="DIR",
                   help="crash-safe session snapshot directory "
                        "(default: auto tempdir; fleet mode shares one "
                        "dir across replicas so a migrated stream "
                        "restores on the survivor)")
    p.add_argument("--session-capacity", type=int, default=64,
                   help="max live sessions per engine; NEW sessions "
                        "are shed at submit when full — existing "
                        "pinned state is never dropped for a newcomer")
    p.add_argument("--session-ttl-s", type=float, default=300.0,
                   help="idle seconds before a session is evicted "
                        "(dirty state snapshots first)")
    p.add_argument("--snapshot-every", type=int, default=8,
                   help="incremental session snapshot cadence in "
                        "frames (bounds replay work after a crash)")
    p.add_argument("--session-replay-window", type=int, default=32,
                   help="fleet mode: frames the router buffers per "
                        "stream to replay the snapshot->present gap "
                        "after a failover; a gap wider than this "
                        "degrades to a DECLARED state_reset")
    p.add_argument("--store", default=None, metavar="DIR",
                   help="persistent AOT artifact store: warm "
                        "executables from this directory's verified "
                        "StableHLO blobs instead of re-tracing (cold "
                        "misses trace and populate it); fleet mode "
                        "shares the DIR across replicas so respawns "
                        "skip the compile storm")
    p.add_argument("--residency-mb", type=float, default=None,
                   help="HBM budget for resident tenant weights in "
                        "MiB: least-recently-served tenants beyond it "
                        "are evicted to host and re-materialized on "
                        "demand (default: everything stays resident)")
    p.add_argument("--tenant-quota", action="append", metavar="NAME=N",
                   help="per-tenant admission quota (max queued "
                        "requests), repeatable — a noisy tenant sheds "
                        "alone at its own cap")
    p.add_argument("--slo-class", action="append",
                   metavar="NAME=CLASS",
                   help="per-tenant SLO class (gold/standard/batch), "
                        "repeatable: under contention a tenant only "
                        "occupies its class's fraction of the queue "
                        "(1.0/0.8/0.5); alone it gets the whole host")
    p.add_argument("--num-classes", type=int, default=None)
    p.add_argument("--top", type=int, default=5)
    p.add_argument("--score", type=float, default=0.5)
    p.add_argument("--faults", default=None,
                   help="deterministic fault schedule for chaos drills "
                        "(resilience/faults.py grammar, e.g. "
                        "'crash@2' crashes the dispatcher on its 3rd "
                        "batch — the supervisor must recover)")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for probabilistic (~) fault specs")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the whole "
                        "serving session into this directory (started "
                        "after warmup, stopped at shutdown)")
    p.add_argument("--trace-spool", default=None, metavar="DIR",
                   help="distributed tracing: append every completed "
                        "span to a crash-safe per-process spool file "
                        "under DIR (fleet mode forwards it to every "
                        "replica); merge the fleet's spools into ONE "
                        "Perfetto trace with tools/trace_merge.py. "
                        "Flight-recorder dumps land in the same DIR")
    p.add_argument("--obs-role", default=None,
                   help="process label on spans/spools/dumps (fleet "
                        "mode sets each replica's slot id "
                        "automatically; default: router/replica by "
                        "mode)")
    args = p.parse_args(argv)

    if args.fleet is not None:
        # fleet mode: router over child processes, no jax in THIS
        # process (the replicas compile; the router only routes)
        spool = _setup_obs(args, args.obs_role or "router")
        router = build_fleet(args)
        try:
            if args.http is not None:
                run_http(router, args)
            else:
                run_stdin(router, args)
        finally:
            router.close()
            if spool is not None:
                spool.close()
            # grep-stable exit line: the router smoke gate asserts it
            print(router.summary_line(), file=sys.stderr, flush=True)
        return

    from deepvision_tpu.obs.profiler import profile_session

    spool = _setup_obs(args, args.obs_role or "replica")
    engine = build_engine(args)
    try:
        # the profiler bracket starts AFTER build_engine so warmup
        # compiles don't drown the serving steady state in the trace
        with profile_session(args.profile_dir):
            if args.http is not None:
                run_http(engine, args)
            else:
                run_stdin(engine, args)
    finally:
        engine.close()
        if spool is not None:
            spool.close()
        stats = engine.stats()
        if stats.get("pipelines"):
            # grep-stable exit line: the pipeline smoke gate asserts
            # served counts and that the frozen cache saw zero
            # post-warm misses (no request paid a hidden compile)
            served = ",".join(f"{k}={v}" for k, v in
                              sorted(stats["pipelines"].items()))
            cache = stats["cache"]
            print(f"[pipeline] served {served} "
                  f"frozen={cache['frozen']} misses={cache['misses']} "
                  f"hits={cache['hits']}", file=sys.stderr, flush=True)
        # grep-stable tenancy exit line: the swap smoke gate asserts
        # swaps=N on it (and zero dropped data responses upstream)
        print(engine.tenancy.summary_line(), file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
