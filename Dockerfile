# Cloud-submittable training image — the TPU-VM analog of the reference's
# CUDA image (ref: Hourglass/tensorflow/Dockerfile: nvidia/cuda:10.1 base,
# pip deps, ENTRYPOINT main.py). TPU access comes from running on a
# TPU VM (the libtpu runtime ships with the jax[tpu] wheel); no driver
# layers needed in the image itself.

FROM python:3.12-slim

LABEL project="deepvision-tpu"

ENV LC_ALL=C.UTF-8 \
    LANG=C.UTF-8 \
    PYTHONUNBUFFERED=TRUE \
    PYTHONDONTWRITEBYTECODE=TRUE

COPY requirements.txt /tmp/requirements.txt
RUN pip install --no-cache-dir -r /tmp/requirements.txt \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /app
COPY deepvision_tpu ./deepvision_tpu
COPY train.py predict.py bench.py ./

ENTRYPOINT ["python", "train.py"]
