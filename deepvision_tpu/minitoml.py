"""Dependency-free TOML-subset reader.

The repo's Python is 3.10 (no stdlib ``tomllib``) and the container's
dependency set is frozen, so this module carries a deliberately minimal
TOML-subset reader covering exactly what ``jaxlint.toml`` uses: comments,
``[table]`` / ``[[array-of-tables]]`` headers (dotted keys allowed),
and ``key = value`` with string / number / bool / list-of-scalars values
(lists may span lines). Anything fancier (inline tables, dates, escapes
beyond ``\\"`` and ``\\\\``) is rejected loudly rather than misread.

It lives in the library (not ``tools/``) because the declarative
``[[shardcheck.rule]]`` partition table is consumed at RUNTIME by the
sharding engine (core/sharding.py) as well as at lint time by
``tools/jaxlint/config.py`` — one reader, one dialect, no drift between
what the trainer shards and what the lint tier audits. It imports
nothing beyond the stdlib, so the AST-only jaxlint path stays free of a
jax import.
"""

from __future__ import annotations

import re


class TomlError(ValueError):
    pass


_BARE_KEY = re.compile(r"^[A-Za-z0-9_.\-]+$")


def _parse_scalar(tok: str, where: str):
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        body = tok[1:-1]
        # the only escapes jaxlint.toml needs
        return body.replace('\\"', '"').replace("\\\\", "\\")
    if tok.startswith("'") and tok.endswith("'") and len(tok) >= 2:
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        raise TomlError(f"{where}: unsupported TOML value {tok!r}") from None


def _split_list_items(body: str, where: str) -> list[str]:
    """Split a [...] body on commas that are outside quotes
    (backslash-escape aware within basic strings)."""
    items, cur, quote, escaped = [], "", None, False
    for ch in body:
        if quote:
            cur += ch
            if escaped:
                escaped = False
            elif ch == "\\" and quote == '"':
                escaped = True
            elif ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            cur += ch
        elif ch == ",":
            items.append(cur)
            cur = ""
        else:
            cur += ch
    if quote:
        raise TomlError(f"{where}: unterminated string in list")
    items.append(cur)
    return [i.strip() for i in items if i.strip()]


def _strip_comment(line: str) -> str:
    """Drop a trailing comment; '#' inside quotes (incl. after an
    escaped quote like ``"a \\" # b"``) is content, not a comment."""
    quote, escaped = None, False
    for i, ch in enumerate(line):
        if quote:
            if escaped:
                escaped = False
            elif ch == "\\" and quote == '"':
                escaped = True
            elif ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def loads_toml(text: str) -> dict:
    """Parse the TOML subset described in the module docstring."""
    root: dict = {}
    current = root
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        raw = _strip_comment(lines[i]).strip()
        i += 1
        if not raw:
            continue
        where = f"line {i}"
        if raw.startswith("[["):  # array of tables
            if not raw.endswith("]]"):
                raise TomlError(f"{where}: malformed table header {raw!r}")
            name = raw[2:-2].strip()
            parent = _descend(root, name, where)
            arr = parent.setdefault(name.split(".")[-1], [])
            if not isinstance(arr, list):
                raise TomlError(f"{where}: {name!r} redefined as an array")
            current = {}
            arr.append(current)
        elif raw.startswith("["):
            if not raw.endswith("]"):
                raise TomlError(f"{where}: malformed table header {raw!r}")
            name = raw[1:-1].strip()
            parent = _descend(root, name, where)
            current = parent.setdefault(name.split(".")[-1], {})
            if not isinstance(current, dict):
                raise TomlError(f"{where}: {name!r} redefined as a table")
        else:
            if "=" not in raw:
                raise TomlError(f"{where}: expected key = value, got {raw!r}")
            key, _, val = raw.partition("=")
            key, val = key.strip(), val.strip()
            if not _BARE_KEY.match(key):
                raise TomlError(f"{where}: unsupported key {key!r}")
            if val.startswith("["):
                # accumulate a possibly multiline list
                while val.count("[") > val.count("]"):
                    if i >= len(lines):
                        raise TomlError(f"{where}: unterminated list")
                    val += " " + _strip_comment(lines[i]).strip()
                    i += 1
                body = val.strip()[1:-1]
                current[key] = [
                    _parse_scalar(t, where)
                    for t in _split_list_items(body, where)
                ]
            else:
                current[key] = _parse_scalar(val, where)
    return root


def _descend(root: dict, dotted: str, where: str) -> dict:
    node = root
    parts = dotted.split(".")
    for part in parts[:-1]:
        node = node.setdefault(part, {})
        if not isinstance(node, dict):
            raise TomlError(f"{where}: {part!r} is not a table")
    return node
