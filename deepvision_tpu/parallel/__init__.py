"""Explicit-collective parallelism patterns (shard_map / ppermute).

The framework's default scaling path is GSPMD: annotate shardings, let
XLA insert collectives (core/mesh.py, core/step.py). This package holds
the EXPLICIT versions of those patterns for the cases where manual
scheduling matters — ring halo exchange for spatially-partitioned
convolutions (the CNN analog of ring attention's neighbor exchange over
ICI; SURVEY §5.7), written with ``jax.shard_map`` + ``lax.ppermute``.
"""

from deepvision_tpu.parallel.constraint import (
    guard_thin_h,
    spatial_model_shards,
)
from deepvision_tpu.parallel.spatial import (
    halo_exchange,
    spatial_conv2d,
)

__all__ = ["guard_thin_h", "halo_exchange", "spatial_conv2d",
           "spatial_model_shards"]
