"""Spatial-sharding guard for thin feature maps.

Round-5 finding (EVIDENCE.md): under GSPMD spatial partitioning (input
H sharded over the ``model`` mesh axis), XLA's SPMD partitioner
miscomputes the BACKWARD of strided-conv → residual-block chains once a
feature map's H shard thins to a single row — the forward is exact
(loss matches to 1e-16 in f64) but parameter gradients diverge by up to
68x. Minimal repro: three [ConvBN(stride 2) → DarknetBlock] stages on a
(8, 16, 8, 4) f64 input over a 4x2 (data x model) CPU mesh vs the same
step on 8x1; rel grad error 1.3 at 1-row shards. YOLO's FPN
(upsample+concat) shows the same class of error even at 2-row shards,
so the guard threshold carries a 2x margin.

The guard re-shards thin maps to data-only: :func:`guard_thin_h`
inserts a ``with_sharding_constraint`` dropping the H sharding when
``H // model_shards < min_rows``. This is also the PERFORMANT choice —
at a few rows per shard the halo exchange dominates the conv compute,
so deep low-resolution stages want data-only sharding regardless; the
spatial mesh axis earns its keep on the high-resolution stages.

The mesh is communicated via a TRACE-TIME thread-local
(:func:`spatial_mesh_scope`): the compiled-step factories in core/step
enter it around the traced step function, so every model traced through
them sees the mesh, while execution-time behavior (argument resharding,
donation) is completely untouched. Raw ``jax.jit`` users wrap their
step function body in ``with spatial_mesh_scope(mesh): ...``. Without
a scope the guard is a no-op, so annotated models remain valid
single-device programs.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepvision_tpu.core.mesh import AXIS_DATA, AXIS_MODEL

_tls = threading.local()


@contextmanager
def spatial_mesh_scope(mesh: Mesh):
    """Expose ``mesh`` to :func:`guard_thin_h` for the duration of a
    trace. Nestable; re-entrant per thread."""
    prev = getattr(_tls, "mesh", None)
    _tls.mesh = mesh
    try:
        yield
    finally:
        _tls.mesh = prev


def current_spatial_mesh() -> Mesh | None:
    return getattr(_tls, "mesh", None)


def spatial_model_shards() -> int:
    """Size of the scoped mesh's ``model`` axis (1 when no scope is
    active or the mesh has no model axis)."""
    mesh = current_spatial_mesh()
    if mesh is not None and AXIS_MODEL in mesh.axis_names:
        return int(mesh.shape[AXIS_MODEL])
    return 1


# Minimum H rows per model-axis shard before a map is forced back to
# data-only sharding. 1-row shards are the proven-broken regime; 2-row
# shards measured exact in plain chains but NOT in the YOLO FPN's
# upsample+concat graph (f64 parity harness, EVIDENCE.md r5) — 4 holds
# across every architecture tested and doubles as the point where halo
# overhead stops paying for itself anyway.
MIN_ROWS_PER_SHARD = 4


def guard_thin_h(x, min_rows: int = MIN_ROWS_PER_SHARD):
    """Constrain ``x`` (NHWC) to data-only sharding when H-sharding it
    over the scoped mesh's model axis would leave < ``min_rows`` rows
    per shard (the XLA SPMD backward-miscomputation regime). No-op
    outside a :func:`spatial_mesh_scope`."""
    mesh = current_spatial_mesh()
    shards = spatial_model_shards()
    if mesh is None or shards <= 1 or x.ndim < 3:
        return x
    if x.shape[1] // shards >= min_rows:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(AXIS_DATA, *([None] * (x.ndim - 1)))))
