"""Spatial partitioning with explicit ring halo exchange.

Shards the image-height dim of NHWC activations across a mesh axis and
runs convolutions locally, exchanging ``halo`` boundary rows with ring
neighbors via ``lax.ppermute`` — one hop over ICI per direction, exactly
the neighbor-exchange schedule ring attention uses for sequence shards
(SURVEY §5.7: spatial partitioning is the CNN analog of
sequence/context parallelism).

The framework's default path lets GSPMD infer these halos from a
``NamedSharding`` (tests/test_spatial.py); this module is the explicit
form for when the schedule must be controlled (e.g. overlapping the two
halo sends with interior compute) and as the documented pattern for
porting ring algorithms. Numerics vs the unsharded conv are pinned by
tests/test_parallel.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepvision_tpu.core.mesh import AXIS_DATA, AXIS_MODEL

# shard_map graduated from jax.experimental.shard_map to jax.shard_map
# across the jaxlib builds this repo runs on; resolve the newest name
# first so both work (same env-skew class as tests/conftest.py probes)
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:  # pre-graduation jaxlib (e.g. 0.4.x)
    from jax.experimental.shard_map import shard_map


def _axis_size(axis_name: str) -> int:
    """Static size of the mapped axis: ``lax.axis_size`` where it
    exists (newer jax), else the constant-folded ``psum(1)`` idiom the
    older builds document for the same purpose."""
    size_fn = getattr(lax, "axis_size", None)
    if size_fn is not None:
        return int(size_fn(axis_name))
    # psum of a literal constant-folds at trace time on the builds this
    # branch serves — static by construction, not a host sync
    return int(lax.psum(1, axis_name))  # jaxlint: disable=JX101


def halo_exchange(x: jax.Array, halo: int, axis_name: str) -> jax.Array:
    """Concatenate ``halo`` rows from the ring neighbors onto a local
    H-shard (B, H_local, W, C) → (B, H_local + 2·halo, W, C).

    Boundary shards receive zero rows (SAME zero-padding semantics).
    Runs inside ``shard_map`` over ``axis_name``; each direction is one
    ``ppermute`` hop (nearest-neighbor over ICI on a real ring).
    """
    if halo == 0:  # 1x1 kernels need no neighbor rows
        return x
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    zeros = jnp.zeros_like(x[:, :halo])
    if n == 1:
        return jnp.concatenate([zeros, x, zeros], axis=1)
    # my bottom rows become the NEXT shard's top halo
    from_prev = lax.ppermute(
        x[:, -halo:], axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    # my top rows become the PREVIOUS shard's bottom halo
    from_next = lax.ppermute(
        x[:, :halo], axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    top = jnp.where(idx == 0, zeros, from_prev)
    bottom = jnp.where(idx == n - 1, zeros, from_next)
    return jnp.concatenate([top, x, bottom], axis=1)


def _local_conv(x_local, kernel, axis_name: str):
    """Per-shard body: halo exchange + VALID-in-H / SAME-in-W conv."""
    kh, kw = kernel.shape[0], kernel.shape[1]
    halo = (kh - 1) // 2
    x_ext = halo_exchange(x_local, halo, axis_name)
    return lax.conv_general_dilated(
        x_ext,
        kernel,
        window_strides=(1, 1),
        padding=((0, 0), ((kw - 1) // 2, kw // 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def spatial_conv2d(
    x: jax.Array,
    kernel: jax.Array,
    mesh: Mesh,
    *,
    spatial_axis: str = AXIS_MODEL,
) -> jax.Array:
    """Stride-1 SAME conv with H sharded over ``mesh[spatial_axis]`` and
    batch over the ``data`` axis; halos move by explicit ring ppermute.

    x: (B, H, W, C) with H divisible by the spatial axis size and the
    kernel (KH, KW, C, O) with odd KH; returns (B, H, W, O) with the
    same sharding as the input.
    """
    spec = P(AXIS_DATA, spatial_axis)
    shmap = shard_map(
        partial(_local_conv, axis_name=spatial_axis),
        mesh=mesh,
        in_specs=(spec, P()),
        out_specs=spec,
    )
    return shmap(
        jax.device_put(x, NamedSharding(mesh, spec)),
        jax.device_put(kernel, NamedSharding(mesh, P())),
    )
