from deepvision_tpu.core.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    create_mesh,
    data_sharding,
    replicated_sharding,
    shard_batch,
)
from deepvision_tpu.core.precision import Precision, get_precision
from deepvision_tpu.core.prng import KeySeq, fold_host, split_like
from deepvision_tpu.core.step import compile_train_step, TrainStepFn

__all__ = [
    "AXIS_DATA",
    "AXIS_MODEL",
    "create_mesh",
    "data_sharding",
    "replicated_sharding",
    "shard_batch",
    "Precision",
    "get_precision",
    "KeySeq",
    "fold_host",
    "split_like",
    "compile_train_step",
    "TrainStepFn",
]
