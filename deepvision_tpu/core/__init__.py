import jax

# Partitionable threefry, repo-wide: counter-based PRNG sampling that
# partitions with the data it feeds, so per-example key splits over a
# sharded batch no longer compile to cross-shard collective-permutes of
# key counters (the ~9 [[shardcheck.reshard]] RNG waivers this flag
# retired — probe: dcgan 14 permutes -> 0). Bit-behavior contract
# (tests/test_sharding.py pins it): seed->key construction and fold_in
# (the epoch/host derivations) produce identical key_data; split-derived
# subkeys (KeySeq draws) and every sampled stream re-roll — the one-time
# re-roll accepted when the flag flipped (jax upstream flips the same
# default in 0.5).
jax.config.update("jax_threefry_partitionable", True)

from deepvision_tpu.core.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    create_mesh,
    data_sharding,
    replicated_sharding,
    shard_batch,
)
from deepvision_tpu.core.precision import Precision, get_precision
from deepvision_tpu.core.prng import KeySeq, fold_host, split_like
from deepvision_tpu.core.step import compile_train_step, TrainStepFn

__all__ = [
    "AXIS_DATA",
    "AXIS_MODEL",
    "create_mesh",
    "data_sharding",
    "replicated_sharding",
    "shard_batch",
    "Precision",
    "get_precision",
    "KeySeq",
    "fold_host",
    "split_like",
    "compile_train_step",
    "TrainStepFn",
]
