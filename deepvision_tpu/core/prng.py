"""PRNG key discipline.

The reference relies on framework-global RNG (torch/tf seeds). JAX requires
explicit keys; the rules here are:

- one root key per run, derived from the integer seed in the model config;
- ``fold_host`` folds in the process index so multi-host data augmentation
  streams are distinct;
- ``KeySeq`` hands out one subkey per step — never reuse, never rely on
  global state (replaces e.g. torch's implicit per-worker RNG in
  ``DataLoader(num_workers=16)`` — ref: ResNet/pytorch/train.py:229-234).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fold_host(key: jax.Array) -> jax.Array:
    return jax.random.fold_in(key, jax.process_index())


def split_like(key: jax.Array, tree):
    """One independent key per leaf of ``tree``."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(treedef, list(keys))


class KeySeq:
    """Stateful host-side key sequence: ``next(seq)`` -> fresh subkey.

    This is the ONE blessed manual-threading idiom (jaxlint JX103
    treats ``next(KeySeq)`` as minting a fresh key): epoch loops build
    ``KeySeq(jax.random.fold_in(base, epoch))`` and draw one subkey per
    step instead of open-coding ``key, sub = jax.random.split(key)``.
    """

    def __init__(self, seed_or_key):
        if isinstance(seed_or_key, int):
            seed_or_key = jax.random.key(seed_or_key)
        self._key = seed_or_key

    def __next__(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def take(self, n: int) -> jax.Array:
        self._key, *subs = jax.random.split(self._key, n + 1)
        return jnp.stack(subs)

    def skip(self, n: int) -> "KeySeq":
        """Advance past ``n`` draws without returning them — replays the
        split chain to a mid-epoch resume point bit-identically to the
        uninterrupted run (each skipped position advances the chain
        exactly as ``next`` would)."""
        for _ in range(n):
            self._key, _ = jax.random.split(self._key)
        return self
