"""Train-step compilation: jit + NamedSharding + donated buffers.

This replaces the reference's three generations of step machinery
(eager PT loop — ref: ResNet/pytorch/train.py:431-485; Keras ``model.fit`` —
ref: ResNet/tensorflow/train.py:283-297; ``@tf.function`` +
``strategy.experimental_run_v2`` — ref: YOLO/tensorflow/train.py:125-180)
with ONE mechanism: a pure ``step_fn(state, batch, key) -> (state, metrics)``
traced once under ``jax.jit`` with explicit shardings over the mesh. Gradient
all-reduce is implicit: the loss is computed on batch-sharded activations and
the grads of replicated params come out replicated (XLA inserts the psum over
ICI), which is exactly the MirroredStrategy sum-reduce the reference does by
hand (ref: YOLO/tensorflow/train.py:131-151).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Protocol

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepvision_tpu.core.mesh import AXIS_DATA


def compiler_options() -> dict | None:
    """Per-compile XLA option overrides from ``DVT_COMPILER_OPTIONS``
    (``k=v,k=v`` or a JSON object), applied to every compiled step.

    Exists because some XLA knobs are DebugOptions fields that are NOT
    registered as ``XLA_FLAGS`` env flags — e.g. the test harness raises
    ``xla_cpu_collective_call_terminate_timeout_seconds`` this way: on a
    loaded shared host the 8 virtual CPU devices can reach a collective
    >40s apart and XLA hard-aborts the whole process (rendezvous.cc
    "Exiting to ensure a consistent program state")."""
    raw = os.environ.get("DVT_COMPILER_OPTIONS")
    if not raw:
        return None
    if raw.lstrip().startswith("{"):
        return json.loads(raw)
    return dict(kv.split("=", 1) for kv in raw.split(",") if kv)


class TrainStepFn(Protocol):
    def __call__(self, state: Any, batch: Any, key: jax.Array) -> tuple[Any, Any]:
        ...


def compile_train_step(
    step_fn: TrainStepFn,
    mesh: Mesh,
    *,
    state_spec: P | None = None,
    batch_spec: P | None = None,
    donate_state: bool = True,
) -> Callable:
    """Compile ``step_fn`` over ``mesh``.

    - ``state_spec`` defaults to fully replicated parameters/optimizer state
      (pure data parallelism). Model/spatial-parallel trainers pass a pytree
      of PartitionSpecs instead.
    - ``batch_spec`` defaults to leading-dim sharding over the ``data`` axis.
    - The input state buffer is donated: the optimizer update reuses the
      parameter HBM in place.
    """
    if batch_spec is None:
        batch_spec = P(AXIS_DATA)
    state_sh = _state_shardings(mesh, state_spec)
    batch_sh = NamedSharding(mesh, batch_spec)
    key_sh = NamedSharding(mesh, P())

    return jax.jit(
        _in_spatial_scope(step_fn, mesh),
        in_shardings=(state_sh, batch_sh, key_sh),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate_state else (),
        compiler_options=compiler_options(),
    )


def _in_spatial_scope(step_fn, mesh: Mesh):
    """Expose ``mesh`` to the thin-H spatial guard
    (parallel/constraint.guard_thin_h) while ``step_fn`` TRACES. The
    scope is a plain thread-local set around the Python body, so it
    runs during tracing only — execution-time jit behavior (argument
    resharding of restored checkpoints, donation) is untouched."""
    import functools

    from deepvision_tpu.parallel.constraint import spatial_mesh_scope

    @functools.wraps(step_fn)
    def scoped(*args):
        with spatial_mesh_scope(mesh):
            return step_fn(*args)

    return scoped


def _state_shardings(mesh: Mesh, state_spec):
    """None -> replicated; single spec -> uniform; pytree of specs (e.g.
    weight_update_sharding) -> leaf-wise NamedShardings."""
    if state_spec is None:
        return NamedSharding(mesh, P())
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        state_spec,
        is_leaf=lambda s: isinstance(s, P),
    )


def compile_eval_step(step_fn, mesh: Mesh, *, batch_spec: P | None = None,
                      state_spec=None):
    """Like :func:`compile_train_step` but read-only state, nothing donated.

    ``state_spec`` must match the train step's (a sharded opt_state pinned
    to replicated here would all-gather it on every eval call)."""
    if batch_spec is None:
        batch_spec = P(AXIS_DATA)
    return jax.jit(
        _in_spatial_scope(step_fn, mesh),
        in_shardings=(
            _state_shardings(mesh, state_spec),
            NamedSharding(mesh, batch_spec),
        ),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=compiler_options(),
    )


def checkify_error_cls() -> type[BaseException]:
    """The exception class the NaN/Inf tripwire raises
    (``checkify.check_error`` inside :func:`compile_checked_train_step`'s
    runner) — the one symbol recovery code needs to catch it NARROWLY.
    Catching broadly around a compiled step would also swallow real
    device/runtime failures (jaxlint JX111 flags exactly that), so the
    class is exported here instead of every consumer reaching into
    ``jax.experimental.checkify``. Resolved lazily: except clauses
    evaluate their expression only while an exception is in flight, so
    the unchecked hot path never pays the import."""
    from jax.experimental import checkify as ck

    return ck.JaxRuntimeError


def compile_checked_train_step(
    step_fn: TrainStepFn,
    mesh: Mesh,
    *,
    batch_spec: P | None = None,
    state_spec=None,
):
    """Numerics-checked variant (SURVEY §5.2): the step runs under
    ``checkify`` with float error checks, so NaN/Inf anywhere in the
    forward/backward raises a host-side error naming the failing op
    instead of silently corrupting training — the debugging story the
    reference lacks (its only guard is a NaN-batch skip in one val loop,
    ref: Hourglass/tensorflow/train.py:126-130).

    ~2× slower than :func:`compile_train_step`; enable via
    ``train.py --check-numerics`` when chasing instabilities.
    """
    from jax.experimental import checkify as ck

    checked = ck.checkify(_in_spatial_scope(step_fn, mesh),
                          errors=ck.float_checks)
    batch_spec = batch_spec if batch_spec is not None else P(AXIS_DATA)
    state_sh = _state_shardings(mesh, state_spec)
    # out structure is (error, (state, metrics)) — shardings inferred;
    # nothing donated (the debug path keeps inputs alive for inspection).
    compiled = jax.jit(
        checked,
        in_shardings=(
            state_sh,
            NamedSharding(mesh, batch_spec),
            NamedSharding(mesh, P()),
        ),
        compiler_options=compiler_options(),
    )

    def run(state, batch, key):
        err, (new_state, metrics) = compiled(state, batch, key)
        ck.check_error(err)  # raises JaxRuntimeError on NaN/Inf
        return new_state, metrics

    return run


def weight_update_sharding(state, mesh: Mesh):
    """ZeRO-1-style optimizer-state sharding spec for ``state``.

    Implements the TPU technique from "Automatic Cross-Replica Sharding
    of Weight Update in Data-Parallel Training" (Xu et al., 2020,
    arXiv:2004.13336): parameters stay replicated (forward/backward
    unchanged), but optimizer state — and with it the weight-update
    computation — is sharded across the data axis; XLA re-gathers the
    updated parameters, turning the all-reduce of gradients into
    reduce-scatter + all-gather and cutting optimizer memory per chip by
    the axis size.

    Thin wrapper over the partition-rule engine: the specs come from the
    ``[[shardcheck.rule]]`` table with its ``largest(data)`` rows active
    (``core.sharding.state_partition_specs(zero1=True)``) — the same
    table shardcheck audits and the same interpreter checkpoint restore
    re-shards with, so there is exactly one answer to "how does this
    state shard".

    Returns a pytree of PartitionSpecs shaped like ``state`` for
    ``compile_train_step(state_spec=...)``: each optimizer-state leaf
    sharded on its largest data-divisible dimension; params /
    batch_stats / step stay replicated.
    """
    from deepvision_tpu.core.sharding import state_partition_specs

    return state_partition_specs(state, mesh, zero1=True)
