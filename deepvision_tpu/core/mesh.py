"""Device mesh + sharding helpers.

The reference scales only via single-host data parallelism
(``nn.DataParallel`` — ref: ResNet/pytorch/train.py:352-355;
``tf.distribute.MirroredStrategy`` — ref: YOLO/tensorflow/train.py:281-296).
Here the equivalent is a ``jax.sharding.Mesh`` with a ``data`` axis (and an
optional ``model`` axis for tensor/spatial parallelism, which the reference
never had but this framework supports first-class). XLA inserts the
all-reduce collectives over ICI/DCN; there is no user-visible NCCL analog.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_MODEL = "model"
MESH_AXES = (AXIS_DATA, AXIS_MODEL)


def create_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, str] = (AXIS_DATA, AXIS_MODEL),
) -> Mesh:
    """Build a 2-D ``(data, model)`` mesh over the available devices.

    ``n_data=None`` uses every device not consumed by the model axis.
    A single-chip mesh is a valid degenerate case (the reference's
    "CPU or single GPU also works" story — ref: YOLO/tensorflow/README.md:2).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_model:
            raise ValueError(
                f"{len(devices)} devices not divisible by model axis {n_model}"
            )
        n_data = len(devices) // n_model
    need = n_data * n_model
    if need > len(devices):
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, "
                         f"have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, axis_names)


def axis_size(mesh: Mesh, axis: str = AXIS_DATA) -> int:
    """Extent of ``axis`` on ``mesh`` (1 when the axis is absent — a
    degenerate 1-D mesh still divides by it cleanly). The one blessed
    way to ask "how wide is data parallelism?": callers must not spell
    the axis-name literal themselves (JX124)."""
    return int(mesh.shape.get(axis, 1))


def data_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Batch-dim sharding for an activation of rank ``ndim`` (NHWC default)."""
    spec = P(AXIS_DATA, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Place a per-process pytree of numpy arrays onto the mesh, batch-
    sharded over the ``data`` axis.

    Multi-process (multi-host) runs assemble a GLOBAL array from each
    process's local shard via ``jax.make_array_from_process_local_data``
    (the reference's ``experimental_distribute_dataset`` analog —
    ref: YOLO/tensorflow/train.py:291-294); single-process runs take the
    plain sharded ``device_put`` path. Same call either way — the Trainer
    never branches. Re-exported as ``data.device_put.shard_by_process``.
    """
    multi = jax.process_count() > 1

    def put(x):
        x = np.asarray(x)
        sharding = data_sharding(mesh, x.ndim)
        if multi:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, batch)
