"""Partition-rule sharding engine: the ``[[shardcheck.rule]]`` table, executed.

The declarative table in ``jaxlint.toml`` (enforced leaf-by-leaf over the
whole registry by tools/jaxlint/shardcheck.py's coverage audit) maps
regexes over '/'-joined state-leaf paths (``params/Conv_0/kernel``,
``opt_state/0/mu/Dense_0/bias`` …) to a tiny PartitionSpec DSL. This
module is the one interpreter of that DSL — trainer, checkpoint
restore/re-shard, the lint tier and bench all get their specs here, so
"what shards how" is a single reviewed table instead of per-model
surgery (the declarative-rules playbook of the pjit pod papers,
arXiv:2204.06514).

DSL, per matched leaf:

- ``"replicated"``            — ``P()``
- ``"data"`` / ``"data,*"`` … — per-dim axis entries (``*`` = None);
  a named dim that doesn't divide by its axis extent falls back to
  ``P()`` (replicating a ragged leaf beats a partitioner error)
- ``"largest(data)"``         — shard the LARGEST axis-divisible dim:
  the ZeRO-1 weight-update rule ("Automatic Cross-Replica Sharding of
  Weight Update in Data-Parallel Training", Xu et al. 2020,
  arXiv:2004.13336). Renders ``P()`` while ``zero1=False`` — the row
  stays a declared WORKLIST (what shardcheck --zero1-ready quantifies)
  until the trainer turns the flag on.

On top rides :class:`Zero1Plan`: the hashable (static-field-safe)
carrier :meth:`TrainState.apply_gradients` uses to place the
reduce-scatter (grads constrained to the weight-update sharding), run
the optimizer shard-local, and all-gather the updated params — params
stay replicated for forward/backward, optimizer state + f32 master
update shard over the data axis.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepvision_tpu.minitoml import loads_toml

# env override for where the rule table lives (tests, exported bundles);
# default search: explicit arg > env > repo root (package-relative) > cwd
RULES_ENV = "DVT_PARTITION_RULES"


class RuleError(ValueError):
    """A partition-rule problem: missing/empty table, a leaf no rule
    covers, or a spec string the DSL cannot interpret."""


@dataclass(frozen=True)
class PartitionRule:
    """One row of the ``[[shardcheck.rule]]`` table: regex over leaf
    paths -> spec DSL. First match wins, like the baseline ledger."""

    pattern: str
    spec: str
    reason: str = ""

    def matches(self, leaf_path: str) -> bool:
        return re.search(self.pattern, leaf_path) is not None


# --------------------------------------------------------------- leaf paths


def leaf_paths(tree) -> list[tuple[str, object]]:
    """('/'-joined path, leaf) pairs for a state pytree —
    ``params/Conv_0/kernel``, ``opt_state/0/mu/Dense_0/bias`` — the
    path strings the ``[[shardcheck.rule]]`` regexes match against."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(_join_path(path), leaf) for path, leaf in flat]


def _join_path(path) -> str:
    return "/".join(_seg(k) for k in path)


def _seg(k) -> str:
    for attr in ("name", "key", "idx"):
        v = getattr(k, attr, None)
        if v is not None:
            return str(v)
    return str(k)


# ------------------------------------------------------------ rule loading


def load_partition_rules(path: str | Path | None = None
                         ) -> tuple[PartitionRule, ...]:
    """The ``[[shardcheck.rule]]`` rows of ``jaxlint.toml`` as engine
    rules. Missing table / malformed rows fail loudly: a trainer
    silently falling back to all-replicated would un-declare every
    sharding decision the table exists to declare."""
    p = _find_rule_table(path)
    data = loads_toml(p.read_text())
    entries = data.get("shardcheck", {}).get("rule", [])
    if not entries:
        raise RuleError(
            f"no [[shardcheck.rule]] rows in {p} — the sharding engine "
            "has nothing to interpret")
    rules = []
    for e in entries:
        for req in ("pattern", "spec"):
            if req not in e:
                raise RuleError(f"shardcheck.rule entry needs {req!r}: {e!r}")
        try:
            re.compile(str(e["pattern"]))
        except re.error as exc:
            raise RuleError(
                f"shardcheck.rule pattern {e['pattern']!r} is not a valid "
                f"regex: {exc}") from None
        rules.append(PartitionRule(
            pattern=str(e["pattern"]), spec=str(e["spec"]),
            reason=str(e.get("reason", ""))))
    return tuple(rules)


def _find_rule_table(path: str | Path | None) -> Path:
    if path is not None:
        p = Path(path)
        if not p.exists():
            raise RuleError(f"partition-rule table {p} does not exist")
        return p
    env = os.environ.get(RULES_ENV)
    if env:
        p = Path(env)
        if not p.exists():
            raise RuleError(f"${RULES_ENV}={env} does not exist")
        return p
    # repo root relative to this file, then cwd (tests launched elsewhere)
    for cand in (Path(__file__).resolve().parents[2] / "jaxlint.toml",
                 Path("jaxlint.toml")):
        if cand.exists():
            return cand
    raise RuleError(
        "jaxlint.toml (the [[shardcheck.rule]] table) not found next to "
        f"the package or in the cwd — set ${RULES_ENV} to point at it")


# ---------------------------------------------------------- DSL interpreter


_LARGEST_RE = re.compile(r"^largest\(([A-Za-z_][A-Za-z0-9_]*)\)$")


def parse_leaf_spec(spec: str, shape: Sequence[int], mesh: Mesh, *,
                    zero1: bool = True) -> P:
    """Interpret one DSL string for one leaf shape (module docstring
    has the grammar). ``zero1=False`` renders ``largest(...)`` rows as
    ``P()`` — declared worklist, not yet enabled."""
    spec = spec.strip()
    if spec == "replicated":
        return P()
    m = _LARGEST_RE.match(spec)
    if m:
        axis = m.group(1)
        n = _axis_extent(mesh, axis, spec)
        if not zero1:
            return P()
        best = None
        for dim, extent in enumerate(shape):
            # shard the LARGEST divisible dim (same tie-break as the
            # pre-engine core/step.weight_update_sharding)
            if extent >= n and extent % n == 0 and \
                    (best is None or extent > shape[best]):
                best = dim
        if best is None:
            return P()
        return P(*([None] * best), axis,
                 *([None] * (len(shape) - best - 1)))
    entries = [e.strip() for e in spec.split(",")]
    if len(entries) > len(shape):
        raise RuleError(
            f"spec {spec!r} names {len(entries)} dims for a rank-"
            f"{len(shape)} leaf — the rule matches a leaf it was not "
            "written for")
    axes: list[Any] = []
    for dim, e in enumerate(entries):
        if e == "*":
            axes.append(None)
            continue
        n = _axis_extent(mesh, e, spec)
        if shape[dim] % n != 0:
            # ragged: replicate the whole leaf rather than hand the
            # partitioner an undivisible split (SNIPPETS naive-shard
            # fallback semantics)
            return P()
        axes.append(e)
    while axes and axes[-1] is None:
        axes.pop()
    return P(*axes)


def _axis_extent(mesh: Mesh, axis: str, spec: str) -> int:
    if axis not in mesh.shape:
        raise RuleError(
            f"spec {spec!r} names mesh axis {axis!r} but the mesh has "
            f"axes {tuple(mesh.shape)}")
    return mesh.shape[axis]


# ----------------------------------------------------------- spec pytrees


def match_partition_rules(rules: Iterable[PartitionRule], tree, mesh: Mesh,
                          *, zero1: bool = False):
    """PartitionSpec pytree for ``tree``: every leaf's first matching
    rule, interpreted against the leaf's shape. Raises listing every
    uncovered leaf — the runtime twin of shardcheck's coverage audit."""
    rules = tuple(rules)
    unmatched: list[str] = []

    def one(key_path, leaf):
        path = _join_path(key_path)
        for r in rules:
            if r.matches(path):
                return parse_leaf_spec(
                    r.spec, tuple(getattr(leaf, "shape", ())), mesh,
                    zero1=zero1)
        unmatched.append(path)
        return P()

    specs = jax.tree_util.tree_map_with_path(one, tree)
    if unmatched:
        shown = ", ".join(unmatched[:4])
        more = f" (+{len(unmatched) - 4} more)" if len(unmatched) > 4 else ""
        raise RuleError(
            f"{len(unmatched)} state leaves match no [[shardcheck.rule]] "
            f"row: {shown}{more} — add a rule (or extend one) so every "
            "leaf's sharding is a declared decision")
    return specs


def state_partition_specs(state, mesh: Mesh, *, zero1: bool = False,
                          rules: Iterable[PartitionRule] | None = None):
    """The spec pytree for a whole train state, straight from the
    table. ``zero1=True`` activates the ``largest(...)`` rows (the
    weight-update sharding); ``False`` keeps them replicated, so a
    non-ZeRO trainer and shardcheck's default compile see the same
    all-replicated program as before the engine existed."""
    if rules is None:
        rules = load_partition_rules()
    return match_partition_rules(rules, state, mesh, zero1=zero1)


def named_shardings(specs, mesh: Mesh):
    """Leaf-wise ``NamedSharding`` pytree for a spec pytree."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda s: isinstance(s, P))


def make_shard_and_gather_fns(specs, mesh: Mesh):
    """The SNIPPETS make_shard_and_gather_fns pattern: ``shard_fn``
    places a matching pytree onto the mesh per ``specs`` (checkpoint
    restore, elastic re-shard at a different host count); ``gather_fn``
    pulls fully-replicated host copies (single-controller semantics —
    multi-host persistence goes through Orbax, which writes each
    host's local shards)."""
    shs = named_shardings(specs, mesh)
    rep = NamedSharding(mesh, P())

    def shard_fn(tree):
        return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shs)

    def gather_fn(tree):
        return jax.tree.map(
            lambda x: np.asarray(jax.device_put(x, rep)), tree)

    return shard_fn, gather_fn


# ------------------------------------------------------------------ ZeRO-1


@dataclass(frozen=True)
class Zero1Plan:
    """The weight-update sharding, packaged for the compiled step.

    Frozen/hashable so it rides a ``flax.struct`` STATIC field (jit
    cache keys hash it); the mesh is embedded so the constraints need
    no ambient mesh context. ``spec`` is the DSL string of the
    table row that prescribed ZeRO-1 (``largest(data)``) — the plan
    interprets it per leaf shape, which makes it tree-structure
    agnostic: the same plan serves TrainState grads and either GAN
    subtree."""

    mesh: Mesh
    spec: str

    def leaf_sharding(self, shape: Sequence[int]) -> NamedSharding:
        return NamedSharding(
            self.mesh,
            parse_leaf_spec(self.spec, tuple(shape), self.mesh, zero1=True))

    def shard_update(self, tree):
        """The reduce-scatter point: constrain a params-shaped tree
        (unscaled grads, then the optax updates) to the weight-update
        sharding, so XLA reduces each gradient straight into its local
        shard instead of materializing the full all-reduce."""
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, self.leaf_sharding(jax.numpy.shape(x))), tree)

    def replicate(self, tree):
        """The all-gather point: updated params back to replicated for
        the next forward/backward."""
        rep = NamedSharding(self.mesh, P())
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, rep), tree)


def zero1_plan(mesh: Mesh, *,
               rules: Iterable[PartitionRule] | None = None
               ) -> Zero1Plan | None:
    """The plan the trainer attaches to the state when ZeRO-1 is on —
    derived from the rule matching the ``opt_state`` root. Returns
    ``None`` when that rule is not a ``largest(...)`` row: the table
    does not prescribe weight-update sharding, so there is nothing to
    plan (and the trainer should refuse a --zero1 ask rather than
    invent a sharding the table never declared)."""
    if rules is None:
        rules = load_partition_rules()
    for r in rules:
        if r.matches("opt_state"):
            if _LARGEST_RE.match(r.spec.strip()):
                return Zero1Plan(mesh=mesh, spec=r.spec.strip())
            return None
    return None
