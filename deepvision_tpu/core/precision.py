"""Numerics policy: bf16 compute on the MXU, f32 params/reductions.

The reference trains everything in f32 (cuDNN-era defaults). On TPU the MXU
natively multiplies bf16 with f32 accumulation, so the framework-wide policy
is: parameters and optimizer state in f32, matmul/conv inputs cast to bf16,
batch-norm statistics and losses in f32. Models take ``dtype``/``param_dtype``
in the Flax convention so tests can force full f32 for parity checks against
the PyTorch reference.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Precision:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    # BN statistics / softmax / loss accumulation dtype.
    reduce_dtype: jnp.dtype = jnp.float32

    def cast_compute(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )


_F32 = Precision(compute_dtype=jnp.float32)
_BF16 = Precision()


def get_precision(name: str = "bf16") -> Precision:
    """``bf16`` (TPU default) or ``f32`` (parity testing)."""
    if name in ("bf16", "bfloat16", "mixed"):
        return _BF16
    if name in ("f32", "float32", "full"):
        return _F32
    raise ValueError(f"unknown precision policy {name!r}")
