"""Numerics-policy engine: bf16 compute on the MXU, f32 master state,
dynamic loss scaling — the framework-wide mixed-precision contract.

The reference trains everything in f32 (cuDNN-era defaults). On TPU the
MXU natively multiplies bf16 with f32 accumulation, so the policy every
training surface threads through here is:

- **f32 master weights**: parameters and optimizer state live in f32
  (the Flax ``param_dtype`` default). Layers cast params to the compute
  dtype AT USE (linen's cast-at-use convention via the module ``dtype``
  attribute), so the forward/backward runs bf16 activations and
  gradients while the optimizer update happens against full-precision
  masters — the grads flow back up through the per-param cast as f32.
- **bf16 activations/gradients**: the model ``dtype`` (``compute_dtype``
  here) is what the HBM-resident activation tensors carry; BN
  statistics, softmax and loss accumulation stay in ``reduce_dtype``
  (f32) — the ``force_float32_reductions`` linen default.
- **dynamic loss scaling** (:class:`DynamicLossScale`): a pytree-borne
  scale multiplied into the loss before the backward and divided back
  out of the grads before the update, grown every ``growth_interval``
  clean steps and backed off on non-finite grads — a backoff SKIPS the
  update (master weights and optimizer state untouched) instead of
  corrupting training, and is reported through ``mp_*`` step metrics so
  the PR 10 sentinel treats it as handled, not as a trip. bf16 shares
  f32's exponent range, so scaling exists as a guard for the loss
  surfaces with wide dynamic range (heatmap MSE, GAN couplings), not as
  the fp16 necessity.

Models take ``dtype``/``param_dtype`` in the Flax convention so tests
can force full f32 for parity checks against the PyTorch reference.
Per-model remat policies (the other half of the HBM diet) are declared
in ``models/registry.py`` and threaded by ``train/configs.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Precision:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    # BN statistics / softmax / loss accumulation dtype.
    reduce_dtype: jnp.dtype = jnp.float32

    def cast_compute(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )


@flax.struct.dataclass
class DynamicLossScale:
    """Loss-scale state carried in the train-state pytree (it must ride
    the donated step and the checkpoint manifest like any other state).

    ``adjust(grads_finite)`` implements the standard grow/backoff
    schedule: ``growth_interval`` consecutive finite-grad steps double
    the scale (capped at ``max_scale``); any non-finite grad halves it
    (floored at ``min_scale``) and resets the streak. The caller skips
    the parameter update on the non-finite step —
    :meth:`train.state.TrainState.apply_gradients` owns that select.
    """

    scale: jax.Array  # f32 scalar
    good_steps: jax.Array  # i32 scalar, finite-grad streak length
    # 1.0/0.0 verdict of the LAST adjust() — carried in the state so
    # step metrics can report the skip/backoff without a second grad
    # reduction (and without mis-reading scale transitions at the
    # min/max-scale clamps, where a backoff/growth leaves scale equal)
    last_finite: jax.Array = flax.struct.field(
        default_factory=lambda: jnp.float32(1.0))
    growth_interval: int = flax.struct.field(pytree_node=False,
                                             default=200)
    growth_factor: float = flax.struct.field(pytree_node=False,
                                             default=2.0)
    backoff_factor: float = flax.struct.field(pytree_node=False,
                                              default=0.5)
    min_scale: float = flax.struct.field(pytree_node=False, default=1.0)
    max_scale: float = flax.struct.field(pytree_node=False,
                                         default=float(2 ** 24))

    @classmethod
    def create(cls, init_scale: float = float(2 ** 15),
               **kw) -> "DynamicLossScale":
        return cls(scale=jnp.float32(init_scale),
                   good_steps=jnp.zeros((), jnp.int32), **kw)

    def scale_loss(self, loss: jax.Array) -> jax.Array:
        return loss * self.scale.astype(loss.dtype)

    def unscale(self, grads):
        """Grads divided by the scale AND cast up to f32 — the 'grads
        cast back up into the f32 update' half of the policy."""
        inv = (1.0 / self.scale).astype(jnp.float32)
        return jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)

    def adjust(self, grads_finite: jax.Array) -> "DynamicLossScale":
        grew = self.good_steps + 1 >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grew,
                      jnp.minimum(self.scale * self.growth_factor,
                                  self.max_scale),
                      self.scale),
            jnp.maximum(self.scale * self.backoff_factor,
                        self.min_scale),
        )
        new_good = jnp.where(grads_finite & ~grew,
                             self.good_steps + 1,
                             jnp.zeros((), jnp.int32))
        return self.replace(scale=new_scale, good_steps=new_good,
                            last_finite=grads_finite.astype(jnp.float32))


def all_finite(tree) -> jax.Array:
    """Scalar bool: every float leaf of ``tree`` is finite. ONE fused
    reduction over the grad pytree — the overflow check dynamic loss
    scaling keys the skip/backoff decision on. (Branch-free: an empty
    float tree sums zero non-finite counts and reads True.)"""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
    nonfinite = sum(jnp.sum(~jnp.isfinite(l)) for l in leaves)
    return jnp.asarray(nonfinite) == 0


def tree_select(pred: jax.Array, on_true, on_false):
    """Leaf-wise ``where(pred, a, b)`` over two same-structure pytrees —
    the skipped-update select (non-finite grads leave masters alone)."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false)


@dataclasses.dataclass(frozen=True)
class MixedPolicy(Precision):
    """The full numerics policy: :class:`Precision`'s dtype triple plus
    the loss-scaling configuration. Build one with :func:`get_policy`
    from a config/CLI precision name; thread it through
    ``create_train_state(policy=...)`` (which attaches the
    :class:`DynamicLossScale` to the state when scaling is on) — the
    compiled steps key their scaling behavior off the presence of
    ``state.loss_scale``, so one traced program serves both modes per
    configuration with zero retrace churn."""

    loss_scaling: bool = False
    init_scale: float = float(2 ** 15)
    growth_interval: int = 200
    growth_factor: float = 2.0
    backoff_factor: float = 0.5

    @property
    def name(self) -> str:
        if self.compute_dtype == jnp.float32:
            return "f32"
        return "bf16_scaled" if self.loss_scaling else "bf16"

    def cast_to_param(self, tree):
        """Cast a (grad) tree up to the master ``param_dtype``."""
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def make_loss_scale(self) -> DynamicLossScale | None:
        if not self.loss_scaling:
            return None
        return DynamicLossScale.create(
            init_scale=self.init_scale,
            growth_interval=self.growth_interval,
            growth_factor=self.growth_factor,
            backoff_factor=self.backoff_factor,
        )


_F32 = MixedPolicy(compute_dtype=jnp.float32)
_BF16 = MixedPolicy()
_BF16_SCALED = MixedPolicy(loss_scaling=True)

_ALIASES = {
    "bf16": _BF16, "bfloat16": _BF16, "mixed": _BF16,
    "f32": _F32, "float32": _F32, "full": _F32,
    "bf16_scaled": _BF16_SCALED, "bfloat16_scaled": _BF16_SCALED,
    "mixed_scaled": _BF16_SCALED,
}

PRECISION_NAMES = ("bf16", "bf16_scaled", "f32")


def get_policy(name: str = "bf16") -> MixedPolicy:
    """``bf16`` (TPU default), ``bf16_scaled`` (bf16 + dynamic loss
    scaling) or ``f32`` (parity testing / precision-floor configs)."""
    try:
        return _ALIASES[name]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {name!r} "
            f"(known: {sorted(set(_ALIASES))})") from None


def get_precision(name: str = "bf16") -> Precision:
    """Back-compat alias of :func:`get_policy` (pre-policy callers only
    consume the dtype triple)."""
    return get_policy(name)


def precision_metrics(new_state) -> dict:
    """The ``mp_*`` step metrics when loss scaling is active, ``{}``
    otherwise — read off the POST-update state. ``mp_grads_finite`` is
    the in-graph verdict ``adjust()`` recorded for this step — the
    PR 10 sentinel consumes it to treat a scale backoff as handled
    rather than as a trip."""
    ls_new = getattr(new_state, "loss_scale", None)
    if ls_new is None:
        return {}
    return {
        "mp_loss_scale": ls_new.scale,
        "mp_grads_finite": ls_new.last_finite,
    }
