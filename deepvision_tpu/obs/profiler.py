"""Opt-in ``jax.profiler`` sessions + device-memory gauges.

Two hooks the loops consume:

- :class:`ProfileWindow` — the ``train.py --profile-steps A:B``
  mechanism: start a ``jax.profiler`` trace just before global step A,
  stop it after step B, exactly once per run. Profiling every step of a
  long run is useless (gigabytes of XPlane) — the window captures the
  handful of steady-state steps that actually get read. All profiler
  errors degrade to a one-line warning, never a crashed run.
- :func:`profile_session` — whole-process bracket for ``serve.py
  --profile-dir`` (start at boot, stop at shutdown).
- :func:`device_memory_stats` / :func:`sample_memory_gauges` — HBM
  accounting from ``jax.local_devices()[i].memory_stats()``, surfaced
  as ``mem_*`` gauges in the obs registry and as per-epoch ``mem_*``
  logged metrics. CPU backends report no memory_stats — the samplers
  return ``{}`` there (graceful no-op; the gauges only exist where a
  real device backs them, so the driver's on-chip run is where these
  numbers appear).
"""

from __future__ import annotations

import contextlib
import sys
from pathlib import Path

from deepvision_tpu.obs.metrics import Registry, default_registry

__all__ = [
    "ProfileWindow",
    "device_memory_stats",
    "profile_session",
    "sample_memory_gauges",
]

# memory_stats() fields promoted to metrics (names vary by backend;
# these three are the PJRT-stable core: live HBM, high-water mark, cap)
_MEM_FIELDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats() -> dict[str, float]:
    """``{"mem_bytes_in_use_dev0": ..., ...}`` across local devices;
    ``{}`` when the backend exposes no memory stats (CPU)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {}
    out: dict[str, float] = {}
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for field in _MEM_FIELDS:
            if field in stats:
                out[f"mem_{field}_dev{i}"] = float(stats[field])
    return out


def sample_memory_gauges(registry: Registry | None = None) -> dict:
    """Sample device memory into ``mem_*`` gauges on ``registry``
    (default: the process registry) and return the sampled dict — the
    same dict the Trainer logs per epoch as ``mem_*`` metrics."""
    stats = device_memory_stats()
    if stats:
        reg = registry if registry is not None else default_registry()
        for name, value in stats.items():
            reg.gauge(name).set(value)
    return stats


@contextlib.contextmanager
def profile_session(logdir: str | Path | None):
    """Bracket a whole region with one ``jax.profiler`` trace; yields
    True while a trace is live, False when disabled/unavailable."""
    if not logdir:
        yield False
        return
    started = False
    try:
        import jax

        Path(logdir).mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(str(logdir))
        started = True
        print(f"[obs] jax.profiler trace -> {logdir}", file=sys.stderr,
              flush=True)
    except Exception as e:
        print(f"[obs] profiler unavailable ({e!r}); continuing without",
              file=sys.stderr, flush=True)
    try:
        yield started
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                print(f"[obs] profiler stop failed ({e!r})",
                      file=sys.stderr, flush=True)


class ProfileWindow:
    """``--profile-steps A:B``: profile global steps A..B (inclusive),
    once. ``on_step(step)`` is called with the 0-based global index of
    the step ABOUT to run; the trace starts when ``step == A`` arrives
    and stops as soon as a step past B is seen (or at :meth:`close`)."""

    def __init__(self, spec: str, logdir: str | Path):
        try:
            a, _, b = spec.partition(":")
            self.start, self.stop = int(a), int(b)
        except ValueError:
            raise ValueError(
                f"--profile-steps wants 'A:B' (ints), got {spec!r}"
            ) from None
        if self.start < 0 or self.stop < self.start:
            raise ValueError(
                f"--profile-steps wants 0 <= A <= B, got {spec!r}")
        self.logdir = Path(logdir)
        self.active = False
        self.done = False

    def on_step(self, step: int) -> None:
        if self.done:
            return
        if not self.active and step >= self.start:
            self.active = self._start()
            self.done = not self.active  # profiler unavailable: give up
        elif self.active and step > self.stop:
            self._stop()

    def close(self) -> None:
        """Stop a still-open window (run ended inside [A, B])."""
        if self.active:
            self._stop()
        self.done = True

    def _start(self) -> bool:
        try:
            import jax

            self.logdir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(self.logdir))
            print(f"[obs] profiling steps {self.start}..{self.stop} -> "
                  f"{self.logdir}", flush=True)
            return True
        except Exception as e:
            print(f"[obs] profiler unavailable ({e!r}); --profile-steps "
                  "ignored", flush=True)
            return False

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
            print(f"[obs] profile window closed -> {self.logdir}",
                  flush=True)
        except Exception as e:
            print(f"[obs] profiler stop failed ({e!r})", flush=True)
        self.active = False
        self.done = True
