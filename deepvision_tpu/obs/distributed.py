"""Fleet-wide observability: trace propagation, span spools, federated
metrics, and the crash flight recorder.

The PR 5 obs stack (``metrics.py``/``trace.py``) is strictly
in-process, but everything built since is multi-process: the serving
fleet is a router over N replica processes, training is a supervisor
over N hosts plus decode-worker pools, and the SDC machinery
quarantines hosts whose last moments nobody could replay from
telemetry. This module is the cross-process layer on top of the same
primitives — four cooperating pieces:

**Trace-context propagation.** Every routed request gets a trace id at
the router (:func:`new_trace_id`), carried over the HTTP hop in the
``X-DVTPU-Trace`` header (:data:`TRACE_HEADER`; the stdin-JSONL surface
takes a ``"trace"`` field) into the replica's queue/device/postprocess
spans — so one request's spans share one id across processes. Cluster
jobs stamp their tracer with ``(host, generation)`` labels
(:func:`cluster_labels_from_env`), so one training step is correlatable
across hosts of any generation.

**Per-process span spools.** :class:`SpanSpool` attaches to the tracer
as a sink and appends every completed span to a crash-safe JSONL file
(one complete record per line — a SIGKILL can tear at most the final
line, which the reader tolerates), bounded by two-file rotation so a
long run's spool is a ring, not a leak. The header line calibrates the
tracer's monotonic clock against this process's wall clock
(``epoch_wall``), which is what lets ``tools/trace_merge.py`` assemble
spools from N processes into ONE Perfetto timeline with correct
cross-process ordering.

**Federated metrics.** A parent (the fleet router, the cluster
supervisor) scrapes its children's typed registry dumps
(:meth:`Registry.dump` — histogram RESERVOIRS included, not lossy
quantiles) and :func:`render_federated` re-exports one aggregated
Prometheus surface: exact sums for counters, sample-merged reservoirs
for histogram quantiles, per-child series labelled
``{replica="r1"}`` / ``{host="0"}`` — one ``curl :PORT/metrics``
describes the whole fleet.

**Flight recorder.** :class:`FlightRecorder` keeps an always-on bounded
ring of recent spans (a tracer sink — no export machinery needed) plus
metric-delta notes, and dumps it to the workdir on SIGTERM, dispatcher
crash, sentinel trip, or SDC divergence — so every PR 10 verdict ships
with a black box of the culprit's last K steps. For a SIGKILLed process
(no handler can run) the spool IS the surviving black box: the cluster
supervisor extracts the culprit's spool tail into a quarantine dump.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import uuid
from collections import deque
from pathlib import Path

from deepvision_tpu.obs.metrics import (
    Registry,
    default_registry,
    histogram_export,
    render_family,
)
from deepvision_tpu.obs.trace import Tracer, get_tracer

__all__ = [
    "ENV_SPOOL",
    "TRACE_HEADER",
    "FlightRecorder",
    "SpanSpool",
    "cluster_labels_from_env",
    "enable_spool_from_env",
    "flight_dump",
    "get_flight_recorder",
    "install_flight_recorder",
    "merge_histograms",
    "new_trace_id",
    "parse_prometheus",
    "read_spool",
    "render_federated",
    "spool_paths",
]

# the spool directory hand-off: a parent (serve.py --trace-spool, the
# cluster supervisor) exports this; children attach a SpanSpool there
ENV_SPOOL = "DVTPU_TRACE_SPOOL"
# the HTTP hop carrier of the trace id (router -> replica)
TRACE_HEADER = "X-DVTPU-Trace"
_SPOOL_PREFIX = "trace-spool-"


def new_trace_id() -> str:
    """Fleet-unique request trace id (128-bit uuid, 16 hex chars is
    plenty at serving rates)."""
    return uuid.uuid4().hex[:16]


def cluster_labels_from_env(environ=os.environ) -> dict:
    """Process identity labels from the cluster launch env: the stable
    ORIGINAL host id (not the generation-local index) and the
    generation, so spans from any relaunch correlate to the same
    physical host row."""
    out: dict = {}
    host = environ.get("DVTPU_CLUSTER_ORIG_HOST",
                       environ.get("DVTPU_CLUSTER_HOST"))
    if host is not None:
        out["host"] = int(host)
    gen = environ.get("DVTPU_CLUSTER_GEN")
    if gen is not None:
        try:
            out["generation"] = int(gen)
        except ValueError:
            out["generation"] = gen  # "gen-003" / "replay-001" names
    return out


# --------------------------------------------------------------- spools


class SpanSpool:
    """Crash-safe per-process span spool: a tracer sink appending one
    JSON record per completed span.

    - **crash-safe append**: every line is a complete record written in
      one ``write`` + flush; a SIGKILL tears at most the final line and
      :func:`read_spool` tolerates it. This is what makes the spool the
      surviving black box of a killed process.
    - **bounded**: at ``max_bytes`` the file rotates to ``<name>.1``
      (previous ``.1`` dropped) — a two-file ring, so long training
      runs spool forever in bounded disk.
    - **calibrated**: header lines record ``epoch_wall`` — the wall
      time of the tracer's monotonic zero — re-emitted whenever the
      tracer is re-epoched (``clear()``), so the merger can place every
      span on the fleet-wide wall timeline.
    """

    def __init__(self, directory: str | Path, *, role: str | None = None,
                 tracer: Tracer | None = None,
                 max_bytes: int = 8 << 20):
        self._tracer = tracer if tracer is not None else get_tracer()
        self._dir = Path(directory)
        self._dir.mkdir(parents=True, exist_ok=True)
        self.role = role or self._tracer.labels.get("role") or "proc"
        safe = "".join(c if c.isalnum() or c in "-_." else "-"
                       for c in str(self.role))
        self.path = self._dir / f"{_SPOOL_PREFIX}{safe}-{os.getpid()}.jsonl"
        self._max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._file = open(self.path, "a", encoding="utf-8")
        self._size = self.path.stat().st_size
        self._epoch_wall = None
        self._write_header()
        self._tracer.add_sink(self._sink)

    def _write_header(self) -> None:
        self._epoch_wall = self._tracer.epoch_wall
        self._write_line({
            "spool": 1, "pid": os.getpid(), "role": self.role,
            "labels": self._tracer.labels,
            "epoch_wall": self._epoch_wall, "time": time.time(),
        })

    def _write_line(self, rec: dict) -> None:
        line = json.dumps(rec) + "\n"
        self._file.write(line)
        self._file.flush()
        self._size += len(line)

    def _sink(self, rec: dict) -> None:
        with self._lock:
            if self._file.closed:
                return
            if self._tracer.epoch_wall != self._epoch_wall:
                self._write_header()  # tracer re-epoched: recalibrate
            self._write_line(rec)
            if self._size > self._max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        self._file.close()
        os.replace(self.path, self.path.with_name(self.path.name + ".1"))
        self._file = open(self.path, "a", encoding="utf-8")
        self._size = 0
        self._write_header()

    def close(self) -> None:
        self._tracer.remove_sink(self._sink)
        with self._lock:
            if not self._file.closed:
                self._file.close()

    def __enter__(self) -> "SpanSpool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def enable_spool_from_env(role: str | None = None,
                          labels: dict | None = None,
                          environ=os.environ) -> SpanSpool | None:
    """The child-process hook: when :data:`ENV_SPOOL` names a
    directory, label the process tracer and attach a spool there (spans
    then record via the sink path even with the in-memory ring off).
    Returns the spool, or None when spooling is not requested."""
    d = environ.get(ENV_SPOOL)
    if not d:
        return None
    tracer = get_tracer()
    merged = {**cluster_labels_from_env(environ), **(labels or {})}
    if role is not None:
        merged.setdefault("role", role)
    tracer.set_labels(**merged)
    return SpanSpool(d, role=merged.get("role"), tracer=tracer)


def spool_paths(root: str | Path) -> list[Path]:
    """Every spool file (rotated ``.1`` halves included) under
    ``root``, recursively — the merger's collection sweep."""
    root = Path(root)
    if root.is_file():
        return [root]
    return sorted(p for p in root.rglob(f"{_SPOOL_PREFIX}*.jsonl*")
                  if p.is_file())


def read_spool(path: str | Path) -> dict:
    """Parse one spool file -> ``{"headers": [...], "events": [...]}``.
    Every event carries ``wall`` (seconds, wall clock) computed from
    the governing calibration header, so events from different
    processes are directly comparable. A torn final line (the process
    was SIGKILLed mid-write) is dropped silently — by construction it
    is the only possible damage."""
    headers: list[dict] = []
    events: list[dict] = []
    cur: dict | None = None
    try:
        text = Path(path).read_text(encoding="utf-8", errors="replace")
    except OSError:
        return {"headers": [], "events": []}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail line
        if rec.get("spool") == 1:
            headers.append(rec)
            cur = rec
            continue
        if cur is not None and "ts" in rec:
            rec = dict(rec)
            rec["wall"] = cur.get("epoch_wall", 0.0) + rec["ts"]
            rec["pid"] = cur.get("pid")
            rec["role"] = cur.get("role")
            rec["labels"] = cur.get("labels", {})
            events.append(rec)
    return {"headers": headers, "events": events}


# --------------------------------------------------- metric federation


def merge_histograms(dumps: list[dict]) -> dict:
    """Merge N histogram dumps into one: exact summed count/total and
    the CONCATENATED reservoirs, so federated quantiles are computed
    over every child's samples rather than averaged from per-child
    quantiles (which is not a meaningful statistic)."""
    samples: list[float] = []
    count, total = 0, 0.0
    for d in dumps:
        count += int(d.get("count", 0))
        total += float(d.get("total", 0.0))
        samples.extend(d.get("samples") or [])
    return {"type": "histogram", "count": count, "total": total,
            "samples": samples}


def render_federated(children: dict[str, dict], *,
                     own: Registry | None = None,
                     label: str = "replica",
                     own_label: str = "parent") -> str:
    """One aggregated Prometheus text surface over N children.

    ``children`` maps a label VALUE (replica id, host id) to that
    child's :meth:`Registry.dump`. Per metric family:

    - **counters**: one ``{label="child"}`` sample per child plus the
      unlabelled EXACT sum — ``serve_completed_total`` on the router is
      precisely the fleet's completed count;
    - **gauges**: per-child samples only (summing a queue depth across
      replicas is occasionally meaningful, averaging a ratio never is —
      the reader picks the aggregation);
    - **histograms**: reservoir-merged quantiles + summed
      ``_sum``/``_count``, with per-child ``_count`` samples so a
      lopsided fleet is visible.

    ``own`` adds the parent's OWN registry (router_* / cluster_*
    families): families whose names don't collide with any child render
    unlabelled as usual; a colliding family (both sides count
    ``trace_dropped_spans``) folds the parent in as one more child
    under ``own_label`` so no name is emitted twice."""
    table: dict[str, dict] = {}  # name -> {"type", "series": {label: payload}}
    for child, dump in children.items():
        for name, payload in (dump or {}).items():
            fam = table.setdefault(
                name, {"type": payload.get("type"), "series": {}})
            if fam["type"] == payload.get("type"):
                fam["series"][str(child)] = payload
    own_plain: list[tuple[str, dict]] = []
    if own is not None:
        for name, payload in own.dump().items():
            if name in table:
                if table[name]["type"] == payload.get("type"):
                    table[name]["series"][own_label] = payload
            else:
                own_plain.append((name, payload))

    lines: list[str] = []

    def fmt(v) -> str:
        return f"{float(v):.9g}"

    for name, fam in sorted({**dict(own_plain), **table}.items()):
        if name not in table:
            # non-colliding parent family: the standard unlabelled
            # format, from the same renderer metrics.py uses
            lines.extend(render_family(name, dict(own_plain)[name]))
            continue
        t, series = fam["type"], fam["series"]
        if t == "counter":
            lines.append(f"# TYPE {name}_total counter")
            for child in sorted(series):
                lines.append(
                    f'{name}_total{{{label}="{child}"}} '
                    f"{int(series[child]['value'])}")
            lines.append(f"{name}_total "
                         f"{sum(int(p['value']) for p in series.values())}")
        elif t == "gauge":
            lines.append(f"# TYPE {name} gauge")
            for child in sorted(series):
                lines.append(f'{name}{{{label}="{child}"}} '
                             f"{fmt(series[child]['value'])}")
        elif t == "histogram":
            merged = merge_histograms(list(series.values()))
            ex = histogram_export(merged)
            lines.append(f"# TYPE {name} summary")
            for q, v in ex["quantiles"].items():
                lines.append(f'{name}{{quantile="{q:g}"}} {fmt(v)}')
            for child in sorted(series):
                lines.append(f'{name}_count{{{label}="{child}"}} '
                             f"{int(series[child].get('count', 0))}")
            lines.append(f"{name}_sum {fmt(ex['sum'])}")
            lines.append(f"{name}_count {ex['count']}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Parse the text exposition this module (and ``metrics.py``)
    renders: ``{series_name: [(labels_dict, value), ...]}``. The
    verification half of federation — smokes and tests re-derive the
    sums from the scraped text instead of trusting the renderer."""
    out: dict[str, list[tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(None, 1)
            value = float(val)
        except ValueError:
            continue
        labels: dict = {}
        name = key
        if "{" in key and key.endswith("}"):
            name, _, rest = key.partition("{")
            for pair in rest[:-1].split(","):
                if "=" in pair:
                    k, _, v = pair.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
        out.setdefault(name, []).append((labels, value))
    return out


# ------------------------------------------------------ flight recorder


class FlightRecorder:
    """Always-on bounded black box: the last ``capacity`` span records
    (a tracer sink — active even when the export ring is off) plus
    metric-delta notes, dumped to the workdir when the process dies
    loudly enough to tell someone.

    ``note(label, step=...)`` appends a marker carrying the counter
    DELTAS since the previous note (gauges ride as absolute values) —
    called on cheap existing cadences (the cluster heartbeat, the serve
    dispatch loop), it turns the ring into "what the process was doing,
    step by step, right before the end".

    ``dump(reason)`` writes one atomic JSON file
    (``flightrec-<tag>-<reason>.json``) with the ring, the full
    registry snapshot, and the tracer labels/calibration —
    ``tools/trace_merge.py`` folds these into a merged timeline like
    any spool. Triggers wired by the callers: SIGTERM
    (:meth:`install_signals`), dispatcher crash (serve engine), sentinel
    trip / SDC divergence (cluster member). SIGKILL runs no handler by
    definition — the spool tail is the surviving record there, and the
    cluster supervisor extracts it at quarantine time."""

    def __init__(self, directory: str | Path | None = None, *,
                 capacity: int = 512,
                 registry: Registry | None = None,
                 tracer: Tracer | None = None,
                 meta: dict | None = None):
        self._dir = Path(directory) if directory is not None else None
        self._registry = (registry if registry is not None
                          else default_registry())
        self._tracer = tracer if tracer is not None else get_tracer()
        self.meta = dict(meta or {})
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._last_counters: dict[str, float] = {}
        self._dumps = 0
        self._tracer.add_sink(self._sink)

    def _sink(self, rec: dict) -> None:
        with self._lock:
            self._ring.append({"kind": "span", **rec})

    def note(self, label: str, step: int | None = None, **fields) -> None:
        """Append a marker with metric deltas since the last note.
        Scalars only — copying histogram reservoirs on a heartbeat
        cadence would make the black box the overhead story."""
        deltas: dict = {}
        for name, kind, payload in self._registry.collect(
                scalars_only=True):
            if kind == "counter":
                d = payload - self._last_counters.get(name, 0)
                self._last_counters[name] = payload
                if d:
                    deltas[name] = d
            elif kind == "gauge" and payload:
                deltas[name] = payload
        rec = {"kind": "note", "t": time.time(), "label": label,
               "metrics": deltas, **fields}
        if step is not None:
            rec["step"] = int(step)
        with self._lock:
            self._ring.append(rec)

    def dump(self, reason: str, directory: str | Path | None = None
             ) -> Path | None:
        """Atomically write the black box; returns the path (None when
        no directory was ever configured). Never raises — a failing
        dump must not mask the failure being recorded."""
        try:
            d = Path(directory) if directory is not None else self._dir
            if d is None:
                return None
            d.mkdir(parents=True, exist_ok=True)
            labels = self._tracer.labels
            tag = labels.get("role") or self.meta.get("role") or "proc"
            if labels.get("host") is not None:
                tag = f"host{labels['host']}"
            self._dumps += 1
            safe_reason = "".join(c if c.isalnum() or c in "-_" else "-"
                                  for c in reason)
            path = d / (f"flightrec-{tag}-{safe_reason}-"
                        f"{os.getpid()}-{self._dumps}.json")
            with self._lock:
                events = list(self._ring)
            body = {
                "flightrec": 1,
                "reason": reason,
                "time": time.time(),
                "pid": os.getpid(),
                "meta": self.meta,
                "labels": labels,
                "epoch_wall": self._tracer.epoch_wall,
                "events": events,
                "snapshot": self._registry.snapshot(),
            }
            tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
            tmp.write_text(json.dumps(body))
            os.replace(tmp, path)
            return path
        except Exception:
            return None

    def install_signals(self, *signums) -> None:
        """Dump on delivery of ``signums`` (default SIGTERM), then
        CHAIN to the previous disposition — the preemption handler a
        trainer already installed still runs; a default disposition is
        re-raised so the process still dies. Main thread only (a
        CPython constraint on ``signal.signal``)."""
        for signum in (signums or (signal.SIGTERM,)):
            prev = signal.getsignal(signum)

            def _handler(sig, frame, prev=prev):
                self.dump(f"signal-{sig}")
                if callable(prev):
                    prev(sig, frame)
                elif prev == signal.SIG_DFL:
                    signal.signal(sig, signal.SIG_DFL)
                    os.kill(os.getpid(), sig)
                # SIG_IGN: swallow, as before

            signal.signal(signum, _handler)

    def close(self) -> None:
        self._tracer.remove_sink(self._sink)


_FLIGHT: FlightRecorder | None = None


def install_flight_recorder(directory: str | Path | None, *,
                            capacity: int = 512,
                            meta: dict | None = None,
                            signals: tuple = (),
                            registry: Registry | None = None
                            ) -> FlightRecorder | None:
    """Create + register the process flight recorder (replacing any
    previous one). ``signals`` additionally installs dump-on-signal
    handlers (main thread only). A ``None`` directory uninstalls
    instead: a recorder with nowhere to dump would still pay the
    span-recording hot path (its tracer sink activates tracing) for a
    black box that can never be written."""
    global _FLIGHT
    if _FLIGHT is not None:
        _FLIGHT.close()
        _FLIGHT = None
    if directory is None:
        return None
    _FLIGHT = FlightRecorder(directory, capacity=capacity, meta=meta,
                             registry=registry)
    if signals:
        _FLIGHT.install_signals(*signals)
    return _FLIGHT


def get_flight_recorder() -> FlightRecorder | None:
    return _FLIGHT


def flight_dump(reason: str) -> Path | None:
    """Dump the process flight recorder if one is installed — the
    one-liner failure paths call (dispatcher crash, sentinel trip, SDC
    divergence) without caring whether observability is wired."""
    rec = _FLIGHT
    return rec.dump(reason) if rec is not None else None
