"""Process-wide metric registry: counters, gauges, reservoir histograms.

Before this module, four telemetry objects each reinvented the same
primitives — ``serve/telemetry.LatencyStats`` (lock + deque + numpy
percentiles), ``data/prefetch.FeedTelemetry`` (bare float accumulators,
explicitly documented as racing their own ``reset``),
``resilience.RecoveryCounters`` (lock + dict of ints), and the
``train/loggers`` metric history — with four naming schemes and four
export paths, none of which could be read as ONE view of the process.

Here the primitives live once:

- :class:`Counter` / :class:`Gauge` — lock-guarded scalars;
- :class:`Histogram` — bounded-reservoir series (most recent ``maxlen``
  samples for p50/p95/p99) with EXACT lifetime ``count``/``total``.
  Every read of the (count, total, samples) triple happens under the
  histogram's own lock, so a reader can never see a torn count/total
  pair no matter which thread it runs on — the serve ``/stats`` path
  previously only got that guarantee when callers remembered to hold
  the outer telemetry lock;
- :class:`Registry` — a thread-safe name->metric table with a stable
  ``namespace_name`` naming scheme (``serve_e2e_latency``,
  ``input_h2d_wait``, ``recovery_rollbacks``, ``mem_bytes_in_use_dev0``),
  one merged JSON :meth:`~Registry.snapshot`, and a Prometheus text
  exposition renderer (:meth:`~Registry.render_prometheus`) for the
  ``serve.py GET /metrics`` surface.

The process-wide default registry (:func:`default_registry`) is what the
existing telemetry objects register into at construction; re-registering
a name replaces the previous owner (latest wins — telemetry objects are
long-lived per-process singletons in production, and tests that build
many engines sequentially must not accrete stale series).

Units: histograms record SECONDS. The JSON snapshot reports derived
milliseconds (``*_ms`` keys, matching the pre-existing ``/stats`` and
``input_*`` shapes); the Prometheus rendering reports base-unit seconds
(quantile samples + ``_sum``), per Prometheus convention.
"""

from __future__ import annotations

import re
import threading
from collections import deque

import numpy as np

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "default_registry",
    "histogram_export",
    "histogram_summary",
    "render_family",
    "start_exposition_server",
]

_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class Counter:
    """Monotonic (in normal use) integer counter; ``inc`` from any
    thread, ``value`` reads are consistent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def __repr__(self) -> str:
        return f"Counter({self.value})"


class Gauge:
    """Last-written float value (memory in use, queue depth, ...)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def __repr__(self) -> str:
        return f"Gauge({self.value})"


class Histogram:
    """Bounded-reservoir time series with percentile snapshots.

    ``observe`` takes seconds; :meth:`summary` reports milliseconds in
    the exact shape ``serve/telemetry.LatencyStats.summary`` has always
    produced (``/stats`` JSON contract). The reservoir keeps the most
    recent ``maxlen`` samples (enough for stable p99 at serving rates)
    while ``count``/``total`` stay exact over the metric's lifetime.

    All three of (samples, count, total) mutate and read under ONE
    internal lock: ``summary()`` computes ``mean_ms`` from a coherent
    (count, total) pair even while writers are mid-``observe``.
    """

    def __init__(self, maxlen: int = 8192):
        self._lock = threading.Lock()
        self._samples: deque[float] = deque(maxlen=maxlen)
        self._count = 0
        self._total = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self._samples.append(value)
            self._count += 1
            self._total += value

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._count = 0
            self._total = 0.0

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def total(self) -> float:
        with self._lock:
            return self._total

    def dump(self) -> dict:
        """Typed raw view INCLUDING the reservoir samples — the wire
        format of cross-process metric federation
        (``obs/distributed.py``): a parent merges children's reservoirs
        sample-for-sample instead of trying to average quantiles, so
        the federated percentiles are exactly what one process
        observing every sample would report."""
        with self._lock:
            return {"type": "histogram", "count": self._count,
                    "total": self._total,
                    "samples": [float(s) for s in self._samples]}

    def export(self, qs=(0.5, 0.95, 0.99)) -> dict:
        """Base-unit (seconds) view for the Prometheus rendering: one
        locked read yields a coherent (count, sum, quantiles) triple."""
        return histogram_export(self.dump(), qs)

    def summary(self) -> dict:
        return histogram_summary(self.dump())

    def __repr__(self) -> str:
        return f"Histogram(count={self.count})"


_METRIC_TYPES = (Counter, Gauge, Histogram)


class Registry:
    """Thread-safe name -> metric table with one merged snapshot.

    Names follow ``namespace_name`` (``serve_completed``,
    ``input_h2d_wait``); :meth:`register` replaces an existing owner
    (latest wins), the get-or-create helpers (:meth:`counter`,
    :meth:`gauge`, :meth:`histogram`) return the existing metric — and
    refuse a type change, which is always a naming-collision bug.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    # -- registration ----------------------------------------------------
    def register(self, name: str, metric):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r} (want "
                             "[a-zA-Z_][a-zA-Z0-9_]*)")
        if not isinstance(metric, _METRIC_TYPES):
            raise TypeError(f"not a metric: {metric!r}")
        with self._lock:
            self._metrics[name] = metric
        return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def _get_or_create(self, name: str, cls, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}, not {cls.__name__}")
                return m
        # create outside the lock, register() re-takes it (a racing
        # duplicate create is harmless: last registration wins)
        return self.register(name, factory())

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge, Gauge)

    def histogram(self, name: str, maxlen: int = 8192) -> Histogram:
        return self._get_or_create(name, Histogram,
                                   lambda: Histogram(maxlen=maxlen))

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def value_of(self, name: str, default: float = 0.0) -> float:
        """Scalar read of a counter/gauge by name (``default`` when the
        metric is absent or a histogram) — the one-liner signal readers
        like the serving autoscaler use to consume registry gauges."""
        m = self.get(name)
        if isinstance(m, (Counter, Gauge)):
            return float(m.value)
        return default

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    # -- export ----------------------------------------------------------
    def collect(self, scalars_only: bool = False
                ) -> list[tuple[str, str, object]]:
        """One atomic collection pass: ``[(name, kind, payload), ...]``
        with every value read in a single tight sweep under the
        registry lock — no formatting, parsing, or I/O between family
        reads. Every renderer (``snapshot``, ``render_prometheus``,
        ``dump``) formats FROM a collect() result, so a scrape landing
        mid-update sees one point-in-time view instead of family A from
        before an event and family B from after it (the old
        render-while-reading hazard: a request completing mid-scrape
        could bump ``serve_completed`` into the text while the
        ``serve_e2e_latency`` family, rendered lines earlier, still
        predated it). ``scalars_only`` skips histograms (and their
        reservoir copies) — the flight recorder's delta notes run on
        hot cadences and only track counters/gauges."""
        out: list[tuple[str, str, object]] = []
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                if isinstance(m, Counter):
                    out.append((name, "counter", m.value))
                elif isinstance(m, Gauge):
                    out.append((name, "gauge", m.value))
                elif not scalars_only:
                    out.append((name, "histogram", m.dump()))
        return out

    def snapshot(self) -> dict:
        """One merged JSON-able view: counters -> int, gauges -> float,
        histograms -> their ``summary()`` dict (ms). Rendered from one
        :meth:`collect` pass."""
        out: dict = {}
        for name, kind, payload in self.collect():
            if kind == "histogram":
                out[name] = histogram_summary(payload)
            else:
                out[name] = payload
        return out

    def dump(self) -> dict:
        """Typed raw registry view for cross-process federation
        (``obs/distributed.py``): counters/gauges with kind tags,
        histograms with their full reservoir (see
        :meth:`Histogram.dump`). One atomic :meth:`collect` pass."""
        out: dict = {}
        for name, kind, payload in self.collect():
            if kind == "histogram":
                out[name] = payload  # already typed by Histogram.dump
            else:
                out[name] = {"type": kind, "value": payload}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format version 0.0.4): counters
        as ``<name>_total``, gauges verbatim, histograms as summaries
        (p50/p95/p99 quantile samples in seconds + ``_sum``/``_count``).
        Formats from one atomic :meth:`collect` pass, so families in
        one scrape never mix epochs."""
        lines: list[str] = []
        for name, payload in self.dump().items():
            lines.extend(render_family(name, payload))
        return "\n".join(lines) + "\n"


def histogram_export(dump: dict, qs=(0.5, 0.95, 0.99)) -> dict:
    """Seconds-unit (count, sum, quantiles) from a histogram dump —
    the pure half of :meth:`Histogram.export`, reusable on merged
    (federated) reservoirs."""
    samples = dump.get("samples") or []
    if samples:
        arr = np.asarray(samples, np.float64)
        vals = np.percentile(arr, [q * 100.0 for q in qs])
        quant = {q: float(v) for q, v in zip(qs, vals)}
    else:
        quant = {q: 0.0 for q in qs}
    return {"count": dump.get("count", 0),
            "sum": dump.get("total", 0.0), "quantiles": quant}


def histogram_summary(dump: dict) -> dict:
    """Milliseconds-unit summary (the ``/stats`` shape) from a
    histogram dump — the pure half of :meth:`Histogram.summary`."""
    samples = dump.get("samples") or []
    count = dump.get("count", 0)
    total = dump.get("total", 0.0)
    if not samples:
        return {"count": count, "mean_ms": 0.0, "p50_ms": 0.0,
                "p95_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0}
    arr = np.asarray(samples, np.float64) * 1e3
    p50, p95, p99 = np.percentile(arr, [50, 95, 99])
    return {
        "count": count,
        "mean_ms": round(total / max(1, count) * 1e3, 3),
        "p50_ms": round(float(p50), 3),
        "p95_ms": round(float(p95), 3),
        "p99_ms": round(float(p99), 3),
        "max_ms": round(float(arr.max()), 3),
    }


def _fmt(v: float) -> str:
    return f"{v:.9g}"


def render_family(name: str, payload: dict) -> list[str]:
    """Exposition lines for ONE unlabelled metric family from its
    typed :meth:`Registry.dump` payload — the single definition of the
    counter/gauge/histogram-summary text format, shared by
    :meth:`Registry.render_prometheus` and the federated renderer
    (``obs/distributed.render_federated``) so the two surfaces can
    never drift apart."""
    t = payload.get("type")
    if t == "counter":
        return [f"# TYPE {name}_total counter",
                f"{name}_total {int(payload['value'])}"]
    if t == "gauge":
        return [f"# TYPE {name} gauge", f"{name} {_fmt(payload['value'])}"]
    ex = histogram_export(payload)
    lines = [f"# TYPE {name} summary"]
    for q, v in ex["quantiles"].items():
        lines.append(f'{name}{{quantile="{q:g}"}} {_fmt(v)}')
    lines.append(f"{name}_sum {_fmt(ex['sum'])}")
    lines.append(f"{name}_count {ex['count']}")
    return lines


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-wide registry every telemetry object registers into
    by default — the single source for ``GET /metrics`` and the bench
    JSON's ``obs`` block."""
    return _DEFAULT


def start_exposition_server(port: int, registry: Registry | None = None,
                            host: str = "0.0.0.0", render_fn=None):
    """Minimal standalone Prometheus scrape surface: a daemon-threaded
    stdlib HTTP server answering ``GET /metrics`` with
    :meth:`Registry.render_prometheus` (plus ``/healthz``, plus
    ``GET /metrics.json`` — the typed :meth:`Registry.dump` the
    federation layer scrapes). Exists for processes that are NOT
    already serving HTTP — the multi-host training supervisor
    (``train_dist.py --supervise --metrics-port``) most of all;
    ``serve.py`` keeps its own integrated endpoint.

    ``render_fn`` overrides the ``/metrics`` text (the cluster
    supervisor passes its federated renderer so one scrape describes
    the whole fleet); ``/metrics.json`` always dumps the local
    registry. Returns ``(server, actual_port)``; call
    ``server.shutdown()`` to stop. ``port=0`` binds an ephemeral port
    (tests)."""
    import http.server
    import json as _json
    import threading

    reg = registry if registry is not None else default_registry()
    render = render_fn if render_fn is not None else reg.render_prometheus

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path.split("?")[0] == "/metrics":
                body = render().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/metrics.json":
                body = _json.dumps(reg.dump()).encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/healthz":
                body, ctype = b"ok\n", "text/plain"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes are not log events
            pass

    server = http.server.ThreadingHTTPServer((host, int(port)), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="metrics-exposition")
    thread.start()
    return server, server.server_address[1]
