"""Lightweight span tracing with Chrome-trace-format export.

``jax.profiler`` answers "what did XLA do" at op granularity; this
module answers the coarser operator question the epoch/request loops
need — *what did step 1432 spend its time on* — with host-side spans
cheap enough to leave compiled into every loop:

    from deepvision_tpu.obs.trace import span, get_tracer

    get_tracer().enable()
    with span("h2d"):
        batch = next(feed)
    with span("step") as sp:
        out = compiled(state, batch)
        sp.device_sync(out)   # block_until_ready BEFORE the end stamp
    get_tracer().export("trace.json")   # chrome://tracing / Perfetto

Design points:

- **disabled-by-default, near-zero cost**: ``span()`` returns a shared
  no-op context manager unless the tracer is enabled, so the feed and
  step loops carry their spans unconditionally;
- **monotonic clock** (``time.perf_counter``) — wall-clock steps from
  NTP can never produce negative spans;
- **thread-aware**: every span records its thread id/name and its
  nesting depth (a thread-local stack), so the producer thread's
  ``host_next``/``shard`` spans land on their own track;
- **explicit ``device_sync``**: JAX dispatch is asynchronous — a span
  closed right after a compiled call measures *enqueue*, not compute
  (the same lie jaxlint JX112 flags for ad-hoc ``time.perf_counter()``
  deltas). ``device_sync=`` (ctor kwarg) or ``sp.device_sync(out)``
  inserts ``jax.block_until_ready`` before the end timestamp;
- **ring buffer**: the most recent ``capacity`` spans are kept (bounded
  memory on long runs); export writes Chrome trace format JSON that
  loads directly in ``chrome://tracing`` and Perfetto. Overflow is
  never silent: evicted spans are counted (``dropped_spans``, the
  ``trace_dropped_spans`` obs counter) and the export carries the
  count in its metadata, so a truncated trace can't masquerade as a
  complete one;
- **sinks**: ``add_sink(fn)`` registers a per-span callback (the
  distributed spool writer and the flight recorder,
  ``obs/distributed.py``). Spans record whenever the tracer is enabled
  OR a sink is attached, so an always-on flight recorder doesn't
  require the in-memory ring/export machinery to be on;
- **retroactive spans**: :meth:`Tracer.record_span` records a span
  from explicit ``perf_counter`` stamps — for code that already times
  a region with its own clock reads (the serve engine's per-request
  queue-wait, measured as ``t_dispatch - t_submit``) and wants the
  interval on the trace without restructuring into a ``with`` block;
- **wall-clock calibration**: ``epoch_wall`` records the wall time of
  the monotonic trace zero, so a cross-process merger
  (``tools/trace_merge.py``) can align rings/spools from many
  processes onto one timeline;
- **process labels**: ``set_labels(role=..., host=..., generation=...)``
  stamps exports and spool headers so a merged fleet/cluster trace
  names its pid rows (``replica r1``, ``host 0 gen 2``).

:func:`summarize_chrome` turns an exported trace back into per-span
totals + a wall-time-attribution figure; ``tools/trace_summary.py`` is
its CLI.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

__all__ = ["Span", "Tracer", "format_labels", "get_tracer", "span",
           "summarize_chrome"]


class _NoopSpan:
    """Shared do-nothing span handed out while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def device_sync(self, value):
        return value


_NOOP = _NoopSpan()


class Span:
    """One live ``with`` region; created by :meth:`Tracer.span`."""

    __slots__ = ("_tracer", "name", "cat", "args", "_sync", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: dict | None, device_sync):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._sync = device_sync

    def device_sync(self, value):
        """Mark ``value`` (array/pytree) to be ``block_until_ready``-ed
        before the span's end timestamp, so the span measures compute
        rather than async dispatch. Returns ``value`` for chaining."""
        self._sync = value
        return value

    def __enter__(self):
        self._tracer._push()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sync is not None:
            try:
                import jax

                jax.block_until_ready(self._sync)
            except Exception:
                pass  # a failed sync must not mask the body's exception
        t1 = time.perf_counter()
        depth = self._tracer._pop()
        self._tracer._record(self.name, self.cat, self._t0,
                             t1 - self._t0, depth, self.args)
        return False


class Tracer:
    """Ring buffer of completed spans + Chrome-trace export."""

    def __init__(self, capacity: int = 65536):
        self._lock = threading.Lock()
        self._events: deque[tuple] = deque(maxlen=capacity)
        self._capacity = capacity
        self._enabled = False
        self._epoch = time.perf_counter()  # trace time zero
        self.epoch_wall = time.time()      # wall clock of that zero
        self._local = threading.local()
        self._sinks: list = []
        self._dropped = 0          # ring evictions since clear()
        self._drop_counter = None  # lazily bound obs counter
        self._labels: dict = {}

    # -- lifecycle -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def active(self) -> bool:
        """Spans record when the ring is enabled OR a sink is attached
        (a spool/flight-recorder sink keeps spans flowing without the
        in-memory export machinery)."""
        return self._enabled or bool(self._sinks)

    @property
    def dropped_spans(self) -> int:
        """Spans evicted from the ring since the last :meth:`clear` —
        the count the export metadata reports so truncation is never
        silent."""
        with self._lock:
            return self._dropped

    def enable(self, clear: bool = True) -> "Tracer":
        if clear:
            self.clear()
        self._enabled = True
        return self

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0
            self._epoch = time.perf_counter()
            self.epoch_wall = time.time()

    def add_sink(self, fn) -> None:
        """Register ``fn(record: dict)`` called (under the tracer lock,
        in recording order) for every completed span. Keep sinks cheap:
        they run on the recording thread."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def set_labels(self, **labels) -> None:
        """Stamp process identity (``role`` / ``host`` / ``generation``)
        onto exports and spool headers; a cross-process merge uses them
        to name this process's pid row."""
        self._labels.update({k: v for k, v in labels.items()
                             if v is not None})

    @property
    def labels(self) -> dict:
        return dict(self._labels)

    # -- recording -------------------------------------------------------
    def span(self, name: str, cat: str = "app", args: dict | None = None,
             device_sync=None):
        """Context manager timing its body; no-op while inactive."""
        if not self.active:
            return _NOOP
        return Span(self, name, cat, args, device_sync)

    def _push(self) -> None:
        self._local.depth = getattr(self._local, "depth", 0) + 1

    def _pop(self) -> int:
        depth = getattr(self._local, "depth", 1) - 1
        self._local.depth = depth
        return depth  # 0 for outermost spans

    def record_span(self, name: str, t0: float, t1: float,
                    cat: str = "app", args: dict | None = None) -> None:
        """Retroactively record a completed span from explicit
        ``time.perf_counter()`` stamps (same clock as live spans).
        Used where the timing already exists as stamps — the serve
        engine's per-request queue-wait/device/postprocess intervals —
        so the trace carries them without a ``with`` rewrite."""
        if not self.active:
            return
        self._emit(name, cat, t0, max(0.0, t1 - t0), 0, args)

    def _record(self, name: str, cat: str, t0: float, dur: float,
                depth: int, args: dict | None) -> None:
        if not self.active:
            return  # deactivated while the span was open: drop it
        self._emit(name, cat, t0, dur, depth, args)

    def _emit(self, name: str, cat: str, t0: float, dur: float,
              depth: int, args: dict | None) -> None:
        thread = threading.current_thread()
        event = (name, cat, t0 - self._epoch, dur,
                 thread.ident, thread.name, depth, args)
        with self._lock:
            if self._enabled:
                if len(self._events) >= self._capacity:
                    # the deque evicts silently; the count keeps the
                    # truncation honest ("no silent caps")
                    self._dropped += 1
                    self._inc_drop_counter()
                self._events.append(event)
            if self._sinks:
                rec = self._sink_record(event)
                for sink in self._sinks:
                    try:
                        sink(rec)
                    except Exception:
                        pass  # a broken sink must never fail the loop

    @staticmethod
    def _sink_record(event: tuple) -> dict:
        name, cat, ts, dur, tid, tname, depth, args = event
        rec = {"name": name, "cat": cat, "ts": ts, "dur": dur,
               "tid": tid, "tname": tname, "depth": depth}
        if args:
            rec["args"] = args
        return rec

    def _inc_drop_counter(self) -> None:
        if self._drop_counter is None:
            from deepvision_tpu.obs.metrics import default_registry

            self._drop_counter = default_registry().counter(
                "trace_dropped_spans")
        self._drop_counter.inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- export ----------------------------------------------------------
    def chrome_events(self) -> list[dict]:
        """Chrome trace event dicts ("X" complete events, ts/dur in
        microseconds) + thread-name metadata events."""
        with self._lock:
            events = list(self._events)
        pid = os.getpid()
        out: list[dict] = []
        threads: dict[int, str] = {}
        for name, cat, ts, dur, tid, tname, depth, args in events:
            threads.setdefault(tid, tname)
            out.append({
                "name": name, "cat": cat, "ph": "X",
                "ts": round(ts * 1e6, 3), "dur": round(dur * 1e6, 3),
                "pid": pid, "tid": tid,
                "args": {**(args or {}), "depth": depth},
            })
        for tid, tname in threads.items():
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": tname}})
        if self._labels:
            out.append({"ph": "M", "name": "process_name", "pid": pid,
                        "tid": 0, "args": {"name": format_labels(
                            self._labels)}})
        return out

    def export(self, path: str | Path) -> int:
        """Write ``{"traceEvents": [...]}`` (loads in chrome://tracing
        and Perfetto); returns the number of span events written. The
        ``metadata`` block carries ``trace_dropped_spans`` — how many
        spans the ring evicted since the last clear — so a truncated
        trace is labelled as such instead of silently passing for the
        whole story."""
        events = self.chrome_events()
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        meta = {"trace_dropped_spans": self.dropped_spans,
                "complete": self.dropped_spans == 0,
                "pid": os.getpid(), "epoch_wall": self.epoch_wall}
        if self._labels:
            meta["labels"] = dict(self._labels)
        path.write_text(json.dumps(
            {"traceEvents": events, "displayTimeUnit": "ms",
             "metadata": meta}))
        return sum(1 for e in events if e.get("ph") == "X")


def format_labels(labels: dict) -> str:
    """Human row name for a labelled process: ``role`` first, then the
    cluster identity — ``"replica r1"``, ``"host 0 gen 2"``."""
    parts = []
    role = labels.get("role")
    if role:
        parts.append(str(role))
    host = labels.get("host")
    if host is not None and (not role or str(role) != f"host{host}"):
        parts.append(f"host {host}")
    gen = labels.get("generation")
    if gen is not None:
        g = str(gen)
        parts.append(g if g.startswith(("gen", "replay"))
                     else f"gen {g}")
    for k in sorted(labels):
        if k not in ("role", "host", "generation"):
            parts.append(f"{k}={labels[k]}")
    return " ".join(parts) or "process"


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer the loops' ``span(...)`` calls feed."""
    return _TRACER


def span(name: str, cat: str = "app", args: dict | None = None,
         device_sync=None):
    """``with span("step"): ...`` against the default tracer."""
    return _TRACER.span(name, cat=cat, args=args, device_sync=device_sync)


# ------------------------------------------------------- trace analysis


def _merge(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Union of possibly-overlapping [start, end) intervals."""
    out: list[tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _clip(intervals, windows) -> list[tuple[float, float]]:
    """Intersect merged ``intervals`` with merged ``windows``."""
    out = []
    for s, e in intervals:
        for ws, we in windows:
            lo, hi = max(s, ws), min(e, we)
            if lo < hi:
                out.append((lo, hi))
    return _merge(out)


def summarize_chrome(trace: dict | list, wall_span: str = "epoch") -> dict:
    """Per-span time attribution from a Chrome-trace event list.

    ``wall_span`` names the enclosing span whose total duration is the
    wall clock being attributed (default ``"epoch"`` — the trainer's
    outermost per-epoch span). Attribution is the UNION of the other
    spans' intervals on the wall spans' threads, clipped to the wall
    windows — nesting and overlap never double-count. When no
    ``wall_span`` events exist, the full [first start, last end) extent
    of the trace is the wall.

    Returns ``{"spans": {name: {count,total_ms,mean_ms,max_ms,
    pct_of_wall}}, "wall_ms", "attributed_ms", "coverage", "wall_span"}``.
    """
    events = trace.get("traceEvents", []) if isinstance(trace, dict) \
        else trace
    xs = [e for e in events if e.get("ph") == "X"]
    per: dict[str, dict] = {}
    for e in xs:
        d = per.setdefault(e["name"], {"count": 0, "total_us": 0.0,
                                       "max_us": 0.0})
        d["count"] += 1
        d["total_us"] += e["dur"]
        d["max_us"] = max(d["max_us"], e["dur"])

    walls = [e for e in xs if e["name"] == wall_span]
    if walls:
        wall_tids = {(e.get("pid"), e.get("tid")) for e in walls}
        windows = _merge([(e["ts"], e["ts"] + e["dur"]) for e in walls])
    elif xs:
        wall_tids = {(e.get("pid"), e.get("tid")) for e in xs}
        windows = _merge([(min(e["ts"] for e in xs),
                           max(e["ts"] + e["dur"] for e in xs))])
    else:
        wall_tids, windows = set(), []
    wall_us = sum(e - s for s, e in windows)
    leaves = _merge([(e["ts"], e["ts"] + e["dur"]) for e in xs
                     if e["name"] != wall_span
                     and (e.get("pid"), e.get("tid")) in wall_tids])
    attributed_us = sum(e - s for s, e in _clip(leaves, windows))

    spans = {}
    for name, d in sorted(per.items(), key=lambda kv: -kv[1]["total_us"]):
        spans[name] = {
            "count": d["count"],
            "total_ms": round(d["total_us"] / 1e3, 3),
            "mean_ms": round(d["total_us"] / d["count"] / 1e3, 3),
            "max_ms": round(d["max_us"] / 1e3, 3),
            "pct_of_wall": (round(d["total_us"] / wall_us * 100.0, 1)
                            if wall_us else 0.0),
        }
    return {
        "spans": spans,
        "wall_span": wall_span,
        "wall_ms": round(wall_us / 1e3, 3),
        "attributed_ms": round(attributed_us / 1e3, 3),
        "coverage": round(attributed_us / wall_us, 4) if wall_us else 0.0,
    }
