"""deepvision_tpu.obs — unified observability for training and serving.

One subsystem replacing four ad-hoc telemetry implementations
(``train/loggers``, ``data/prefetch.FeedTelemetry``,
``serve/telemetry.ServeTelemetry``, ``resilience.RecoveryCounters``
each had its own locks, deques, naming, and export path):

- ``metrics``  : process-wide thread-safe registry — counters, gauges,
                 bounded-reservoir histograms (p50/p95/p99) — with a
                 stable ``namespace_name`` scheme, one merged JSON
                 ``snapshot()``, and Prometheus text exposition for
                 ``serve.py GET /metrics``.
- ``trace``    : lightweight span tracing (``with span("h2d")``),
                 thread-aware, monotonic-clock, explicit
                 ``device_sync=`` to measure compute instead of async
                 dispatch; ring buffer + Chrome-trace-format export
                 (chrome://tracing / Perfetto) + ``summarize_chrome``
                 (CLI: ``tools/trace_summary.py``).
- ``profiler`` : opt-in ``jax.profiler`` windows (``train.py
                 --profile-steps A:B``, ``serve.py --profile-dir``) and
                 ``mem_*`` device-memory gauges from
                 ``memory_stats()`` (graceful no-op on CPU).
- ``distributed``: the fleet/cluster layer — trace-id propagation over
                 the HTTP hop (``X-DVTPU-Trace``), crash-safe
                 per-process span spools merged by
                 ``tools/trace_merge.py`` into one Perfetto timeline,
                 federated Prometheus rendering (exact counter sums +
                 reservoir-merged histograms with per-child labels),
                 and the always-on crash flight recorder.

The four telemetry objects now register their metrics here at
construction, so train-feed, serve-latency, recovery, and memory
metrics all render from the SAME registry — while every pre-existing
metric name, ``/stats`` JSON key, and grep-stable log line stays
byte-compatible.
"""

from deepvision_tpu.obs.distributed import (
    TRACE_HEADER,
    FlightRecorder,
    SpanSpool,
    enable_spool_from_env,
    flight_dump,
    get_flight_recorder,
    install_flight_recorder,
    new_trace_id,
    parse_prometheus,
    read_spool,
    render_federated,
)
from deepvision_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
)
from deepvision_tpu.obs.profiler import (
    ProfileWindow,
    device_memory_stats,
    profile_session,
    sample_memory_gauges,
)
from deepvision_tpu.obs.trace import (
    Span,
    Tracer,
    get_tracer,
    span,
    summarize_chrome,
)

__all__ = [
    "TRACE_HEADER",
    "FlightRecorder",
    "SpanSpool",
    "enable_spool_from_env",
    "flight_dump",
    "get_flight_recorder",
    "install_flight_recorder",
    "new_trace_id",
    "parse_prometheus",
    "read_spool",
    "render_federated",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "default_registry",
    "ProfileWindow",
    "device_memory_stats",
    "profile_session",
    "sample_memory_gauges",
    "Span",
    "Tracer",
    "get_tracer",
    "span",
    "summarize_chrome",
]
