from deepvision_tpu.data.mnist import load_mnist_idx, synthetic_mnist

__all__ = ["load_mnist_idx", "synthetic_mnist"]
