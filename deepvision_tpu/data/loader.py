"""Multi-process host decode: N spawned workers feed one merged stream.

The other half of the input wall (ISSUE 7 / BENCH_r04): the 2-core host
caps JPEG decode at ~693 img/s on ONE core because the whole tf.data
pipeline lives in a single process (tf.data threads help with I/O but
the Python feed loop and decode contend with the training process's own
runtime threads). This module generalizes the spawn-pool machinery of
``data/builders/shard_writer.py`` — spawn (never fork: forking after
TF/JAX initialized clones held locks into the child, the PR 2 deadlock)
— into a streaming loader:

- each worker runs a user factory ``factory(worker_id, num_workers) ->
  iterable of batches`` in a fresh interpreter and pushes batches into
  its own bounded queue (backpressure per worker);
- the parent merges the per-worker queues ROUND-ROBIN (w0, w1, …, w0,
  …), so the merged order is a pure function of the per-worker streams:
  **deterministic** — same factory + same worker count ⇒ the same batch
  sequence on every run and every resume (the epoch-seeded restore
  contract survives; the order differs from the 1-worker serial order,
  exactly like changing the file-shard layout does);
- a worker exception is re-raised in the parent at the point of the
  failed batch (with the worker traceback in the message);
- ``close()`` stops and joins the workers; leaked children die with
  the parent anyway (daemon processes);
- batch PAYLOADS cross through a fixed RING of reusable
  ``multiprocessing.shared_memory`` segments per worker (``depth+2``
  slots, sized from the first batch with 1.5x headroom); the control
  queue carries only slot metadata, and the parent returns freed slots
  on a per-worker free queue. Why not just ``mp.Queue`` the batches? A
  224² uint8 batch is ~1.2 MB, and the queue pickles it through a pipe
  that measures ~63 MB/s on this class of host (~19 ms/batch — 2.3x
  slower than not spawning at all) vs ~5 GB/s through /dev/shm; and
  why a ring instead of a fresh segment per batch? shm_open/mmap/
  unlink cost milliseconds each under a syscall-intercepting sandbox,
  so segments are created once and reused, zero steady-state syscalls.
  Ownership is one-way: workers only create and write (their resource
  tracker is detached from shm so the handoff prints no bogus leak
  warnings), the parent attaches lazily and unlinks everything at
  ``close()``. Non-dict/no-array/oversize batches, and hosts where shm
  creation fails, fall back to queue pickling transparently.

The factory must be PICKLABLE (a module-level class instance — see
``data/imagenet._TrainShardFactory``); spawned workers start from a
clean interpreter, so the factory's imports (TF included) load in the
child, off the training process's cores.
"""

from __future__ import annotations

import multiprocessing as mp
import traceback
from queue import Empty, Full
from itertools import islice
from typing import Callable, Iterator

import numpy as np

__all__ = ["MultiProcessLoader", "WorkerError", "mp_batches"]

_BATCH, _DONE, _ERROR, _RING = "batch", "done", "error", "ring"
# payload encodings inside a _BATCH message
_SHM, _PICKLE = "shm", "pickle"
# ring slots beyond the control queue's depth: one being written by the
# worker + one being read by the parent while `depth` sit queued
_RING_EXTRA = 2
# first-batch headroom so minor geometry growth doesn't force fallback
_RING_HEADROOM = 1.5


class WorkerError(RuntimeError):
    """A loader worker died; carries the child traceback."""


def _untrack_shm() -> None:
    """Detach THIS (worker) process from shm resource tracking: the
    segments it creates are owned by the PARENT (which attaches and
    unlinks them at close), and the shared tracker daemon would both
    print spurious "leaked shared_memory" warnings and unlink
    still-live segments at child exit. Python 3.13 grew a per-segment
    ``track=False`` for exactly this; do it process-wide here."""
    from multiprocessing import resource_tracker

    orig_register = resource_tracker.register
    orig_unregister = resource_tracker.unregister

    def register(name, rtype):  # pragma: no cover - runs in the child
        if rtype != "shared_memory":
            orig_register(name, rtype)

    def unregister(name, rtype):  # pragma: no cover - runs in the child
        if rtype != "shared_memory":
            orig_unregister(name, rtype)

    resource_tracker.register = register
    resource_tracker.unregister = unregister


class _Ring:
    """Worker-side slot pool: K reusable segments + a free-slot queue
    the parent returns consumed slot indices on."""

    def __init__(self, nbytes: int, k: int, free_q):
        from multiprocessing import shared_memory

        cap = int(nbytes * _RING_HEADROOM)
        self.cap = cap
        self.segs = [shared_memory.SharedMemory(create=True, size=cap)
                     for _ in range(k)]
        self.free = list(range(k))
        self.free_q = free_q

    def names(self) -> list:
        return [s.name for s in self.segs]

    def acquire(self, stop) -> int | None:
        """Next free slot index; blocks on the parent's returns (stop-
        responsive), None when stopped."""
        while True:
            try:
                while True:  # drain all returned slots
                    self.free.append(self.free_q.get_nowait())
            except Empty:
                pass
            if self.free:
                return self.free.pop()
            if stop.is_set():
                return None
            try:
                self.free.append(self.free_q.get(timeout=0.1))
            except Empty:
                continue

    def dump(self, idx: int, arrays) -> list:
        seg, meta, off = self.segs[idx], [], 0
        for k, v in arrays:
            np.ndarray(v.shape, v.dtype, buffer=seg.buf,
                       offset=off)[...] = v
            meta.append((k, v.shape, v.dtype.str, off))
            off += v.nbytes
        return meta


def _split_batch(batch):
    """-> (array_leaves [(key, ndarray)...], extras dict, total_bytes),
    or None when the batch is not a dict of arrays (pickle fallback)."""
    if not isinstance(batch, dict):
        return None
    arrays, extras, total = [], {}, 0
    for k, v in batch.items():
        if isinstance(v, np.ndarray) and v.nbytes:
            arrays.append((k, v))
            total += v.nbytes
        else:
            extras[k] = v
    if not arrays:
        return None
    return arrays, extras, total


def _worker_main(factory, worker_id: int, num_workers: int, queue,
                 free_q, stop, depth: int, skip: int = 0) -> None:
    """Child entry point (module-level: must be picklable for spawn).
    ``skip`` > 0 is a RESPAWN resuming a dead worker at its shard
    position: the factory stream is deterministic, so skipping the
    batches the parent already merged replays the incarnation to
    exactly where its predecessor died."""
    _untrack_shm()
    ring = None
    ring_sent = False

    def put(item) -> bool:
        while not stop.is_set():
            try:
                queue.put(item, timeout=0.1)
                return True
            except Full:
                continue  # bounded queue: retry until stopped
        return False

    def encode(batch):
        nonlocal ring, ring_sent
        split = _split_batch(batch)
        if split is None:
            return (_PICKLE, batch)
        arrays, extras, total = split
        if ring is None:
            try:
                ring = _Ring(total, depth + _RING_EXTRA, free_q)
            except (OSError, ValueError):  # no /dev/shm: stay on pickle
                ring = False
            if ring:
                if not put((_RING, ring.names())):
                    return None
                ring_sent = True
        if not ring or total > ring.cap:
            return (_PICKLE, batch)
        idx = ring.acquire(stop)
        if idx is None:
            return None  # stopped while waiting for a slot
        return (_SHM, (idx, ring.segs[idx].name,
                       ring.dump(idx, arrays), extras))

    # distributed tracing (obs/distributed.py): a spawned decode worker
    # is its own process, invisible to the parent's tracer — when the
    # launch env names a spool dir (DVTPU_TRACE_SPOOL, exported by the
    # cluster supervisor / serve fleet / an operator), its host_decode
    # spans spool there and tools/trace_merge.py gives the worker pool
    # its own pid rows on the merged timeline. No env, no cost.
    spool = None
    try:
        from deepvision_tpu.obs.distributed import enable_spool_from_env
        from deepvision_tpu.obs.trace import span as _span

        spool = enable_spool_from_env(role=f"decode-w{worker_id}")
    except Exception:  # observability must never kill a decode worker
        def _span(*a, **kw):
            from contextlib import nullcontext

            return nullcontext()
    try:
        stream = factory(worker_id, num_workers)
        if skip:
            stream = islice(stream, skip, None)
        it = iter(stream)
        while True:
            try:
                with _span("host_decode", cat="feed",
                           args={"worker": worker_id}):
                    batch = next(it)
            except StopIteration:
                break
            encoded = encode(batch)
            if encoded is None or not put((_BATCH, encoded)):
                return
        put((_DONE, None))
    except BaseException:
        put((_ERROR, f"loader worker {worker_id}/{num_workers} died:\n"
             + traceback.format_exc()))
    finally:
        if spool is not None:
            spool.close()
        if ring and not ring_sent:
            # the parent never learned these names (stopped before the
            # handshake landed): still ours, reclaim them here
            for s in ring.segs:
                s.close()
                try:
                    s.unlink()
                except FileNotFoundError:  # pragma: no cover
                    pass
        queue.close()


class MultiProcessLoader:
    """Iterator over the round-robin merge of ``num_workers`` spawned
    factory streams; ``depth`` bounds each worker's ready-batch queue
    (host-memory backpressure, same contract as the device prefetcher's
    ``depth``).

    ``max_restarts`` > 0 turns a dead worker (SIGKILL/OOM, torn pipe,
    or a factory exception) from an epoch-fatal :class:`WorkerError`
    into bounded self-healing: the worker is respawned resuming at its
    shard position (``skip`` = batches the parent already merged from
    it, deterministic factory replay), the round-robin merge retries
    the SAME rotation slot, so the merged stream is byte-identical to
    an undisturbed run. Each restart counts into the obs registry
    (``loader_worker_restarts``); ``max_restarts`` CONSECUTIVE deaths
    of one worker without a delivered batch in between fail fast — a
    deterministic fault (bad shard, systematic decode error) replays
    to the same death and must still kill the run loudly.
    ``fault_injector`` consults the ``worker_kill`` chaos site once per
    merged batch (``resilience/faults.py``)."""

    def __init__(self, factory: Callable, num_workers: int, *,
                 depth: int = 2, max_restarts: int = 0,
                 fault_injector=None):
        if num_workers < 1:
            raise ValueError(
                f"need at least 1 worker, got {num_workers}")
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}")
        ctx = mp.get_context("spawn")
        self._ctx = ctx
        self._factory = factory
        self._num_workers = num_workers
        self._depth = depth
        self._max_restarts = int(max_restarts)
        self._injector = fault_injector
        from deepvision_tpu.obs.metrics import default_registry

        self._restarts = default_registry().counter(
            "loader_worker_restarts")
        self._stop = ctx.Event()
        self._queues = [ctx.Queue(maxsize=depth)
                        for _ in range(num_workers)]
        self._free_qs = [ctx.Queue(maxsize=depth + _RING_EXTRA)
                         for _ in range(num_workers)]
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(factory, w, num_workers, self._queues[w],
                      self._free_qs[w], self._stop, depth),
                daemon=True,
                name=f"host-loader-{w}",
            )
            for w in range(num_workers)
        ]
        for p in self._procs:
            p.start()
        self._live = list(range(num_workers))
        self._cursor = 0
        self._consumed = [0] * num_workers  # batches merged per worker
        self._deaths = [0] * num_workers    # consecutive, reset on batch
        self._closed = False
        self._ring_names: set = set()  # every segment any worker made
        self._segs: dict = {}          # name -> attached SharedMemory

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        import os
        import signal

        while self._live:
            if self._cursor >= len(self._live):
                self._cursor = 0
            w = self._live[self._cursor]
            if self._injector is not None \
                    and self._injector.check_worker_kill() \
                    and self._procs[w].is_alive():
                print(f"[fault] SIGKILLing loader worker {w}",
                      flush=True)
                os.kill(self._procs[w].pid, signal.SIGKILL)
            kind, payload = self._get(w)
            if kind == _RING:
                self._adopt_ring(payload)
                continue  # control message: same worker's turn again
            if kind == _BATCH:
                self._cursor += 1
                enc, body = payload
                batch = self._load(w, body) if enc == _SHM else body
                self._consumed[w] += 1
                self._deaths[w] = 0  # a delivered batch ends the streak
                return batch
            if kind == _ERROR:
                if self._deaths[w] < self._max_restarts:
                    self._respawn(w, payload)
                    continue  # same rotation slot: merge order preserved
                self._live.pop(self._cursor)
                self.close()
                raise WorkerError(
                    payload if not self._deaths[w] else
                    f"{payload}\n(gave up after {self._deaths[w]} "
                    f"consecutive restarts of worker {w}; "
                    f"max_restarts={self._max_restarts})")
            self._live.pop(self._cursor)  # done: drop from rotation
        raise StopIteration

    def _respawn(self, w: int, why: str) -> None:
        """Bounded self-heal: fresh queues (a SIGKILLed child can leave
        a torn pickle in the old pipe), fresh process resuming at the
        shard position already merged; ring segments the dead
        incarnation announced stay adopted and unlink at close()."""
        self._deaths[w] += 1
        self._restarts.inc()
        head = why.strip().splitlines()[0] if why else "died"
        print(f"[loader] worker {w} died ({head}); respawning at shard "
              f"position {self._consumed[w]} "
              f"(restart {self._deaths[w]}/{self._max_restarts})",
              flush=True)
        p = self._procs[w]
        if p.is_alive():
            p.terminate()
        p.join(5.0)
        for q in (self._queues[w], self._free_qs[w]):
            try:
                while True:
                    msg = q.get_nowait()
                    if isinstance(msg, tuple) and msg[0] == _RING:
                        self._adopt_ring(msg[1])
            except Exception:
                pass
            q.close()
            q.cancel_join_thread()
        self._queues[w] = self._ctx.Queue(maxsize=self._depth)
        self._free_qs[w] = self._ctx.Queue(
            maxsize=self._depth + _RING_EXTRA)
        p = self._ctx.Process(
            target=_worker_main,
            args=(self._factory, w, self._num_workers, self._queues[w],
                  self._free_qs[w], self._stop, self._depth,
                  self._consumed[w]),
            daemon=True,
            name=f"host-loader-{w}r{self._deaths[w]}",
        )
        p.start()
        self._procs[w] = p

    def _adopt_ring(self, names) -> None:
        """Adopt just-announced worker segments into THIS process's
        resource tracker immediately. Workers are untracked by design
        (``_untrack_shm``), so until the parent registers a name a
        SIGKILLed/OOM-killed parent (the preemption/chaos scenario)
        would leak every slot that never carried a batch; registering
        at the handshake makes the tracker's shutdown sweep reclaim
        them all. (Attaching registers too, but a slot may never be
        attached.) Registration is idempotent — a later attach or the
        close-time sweep re-registering the same name is harmless."""
        from multiprocessing import resource_tracker

        for name in names:
            self._ring_names.add(name)
            resource_tracker.register(
                name if name.startswith("/") else "/" + name,
                "shared_memory")

    def _load(self, w: int, body):
        """Copy a ring slot out and hand the slot back to worker ``w``."""
        from multiprocessing import shared_memory

        idx, name, meta, extras = body
        seg = self._segs.get(name)
        if seg is None:
            # already tracker-registered at the _RING handshake
            seg = shared_memory.SharedMemory(name=name)
            self._segs[name] = seg
        batch = {k: np.array(np.ndarray(shape, dtype, buffer=seg.buf,
                                        offset=off))
                 for k, shape, dtype, off in meta}
        batch.update(extras)
        try:
            self._free_qs[w].put_nowait(idx)
        except Full:  # impossible by slot accounting; never wedge on it
            pass
        return batch

    def _get(self, w: int):
        q = self._queues[w]
        while True:
            try:
                return q.get(timeout=0.5)
            except Empty:
                if self._closed:
                    raise StopIteration from None
                p = self._procs[w]
                if not p.is_alive():
                    # dead child: one last grace read (its feeder thread
                    # may still be flushing the pipe), then — a child
                    # that died without a sentinel was SIGKILLed/OOMed
                    try:
                        return q.get(timeout=0.5)
                    except Empty:
                        return (_ERROR,
                                f"loader worker {w} exited uncleanly "
                                f"(exitcode {p.exitcode}) with no "
                                "sentinel")
                    except Exception as e:  # torn pickle post-SIGKILL
                        return (_ERROR,
                                f"loader worker {w} left a torn "
                                f"message in its pipe "
                                f"({type(e).__name__}: {e})")
            except Exception as e:
                # a child killed mid-pipe-write leaves a partial pickle
                # the parent's get() chokes on — that's a death, not a
                # parent crash
                return (_ERROR,
                        f"loader worker {w} stream corrupted "
                        f"({type(e).__name__}: {e})")

    def close(self, timeout: float = 5.0) -> None:
        """Idempotent: stop workers, drain queues (a child blocked on a
        full queue cannot exit), join, terminate stragglers, then unlink
        every ring segment (the parent owns shm cleanup — see
        ``_untrack_shm``)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._drain()
        for p in self._procs:
            p.join(timeout)
            if p.is_alive():
                p.terminate()
                p.join(1.0)
        # post-join second drain: a worker's feeder thread flushes its
        # pipe as the process exits, so a _RING handshake that was in
        # flight during the first drain is only visible NOW — and a
        # missed handshake would leak the whole ring permanently
        self._drain()
        self._unlink_rings()
        for q in (*self._queues, *self._free_qs):
            q.close()
            q.cancel_join_thread()

    def _drain(self) -> None:
        """Discard queued messages (unblocking any child wedged on a
        full pipe), recording ring handshakes on the way past."""
        for q in self._queues:
            try:
                while True:
                    kind, payload = q.get_nowait()
                    if kind == _RING:
                        self._adopt_ring(payload)
            except Empty:
                pass

    def _unlink_rings(self) -> None:
        from multiprocessing import resource_tracker, shared_memory

        for name in self._ring_names:
            seg = self._segs.get(name)
            try:
                if seg is None:
                    seg = shared_memory.SharedMemory(name=name)
                seg.close()
                seg.unlink()  # unregisters the handshake registration
            except FileNotFoundError:
                # already gone: balance the handshake registration or
                # the tracker warns "leaked shared_memory" at exit
                resource_tracker.unregister(
                    name if name.startswith("/") else "/" + name,
                    "shared_memory")
        self._ring_names.clear()
        self._segs.clear()

    def __enter__(self) -> "MultiProcessLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        stop = getattr(self, "_stop", None)
        if stop is not None:
            stop.set()  # daemon children exit; never join in a finalizer


def mp_batches(factory: Callable, num_workers: int,
               limit: int | None = None, *, depth: int = 2,
               max_restarts: int = 0, fault_injector=None):
    """Generator over a bounded slice of the merged worker stream that
    closes the pool on EVERY exit (exhaustion, break, GC) — the shape
    ``make_imagenet_data`` hands the Trainer: worker streams may
    ``repeat()`` forever, the parent's ``limit`` is the epoch length.
    ``max_restarts``/``fault_injector`` pass through to the loader's
    bounded worker respawn + ``worker_kill`` chaos site."""
    loader = MultiProcessLoader(factory, num_workers, depth=depth,
                                max_restarts=max_restarts,
                                fault_injector=fault_injector)
    try:
        src = loader if limit is None else islice(loader, limit)
        yield from src
    finally:
        loader.close()
