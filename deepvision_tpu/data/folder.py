"""Flattened-folder ImageNet dataset + parallel host loader.

Mirrors ``ImageNet2012Dataset`` (ref: ResNet/pytorch/data_load.py:14-69):
a flattened directory of ``<synset>_<name>.JPEG`` files, label↔index maps
built from ``synsets.txt``, cv2 JPEG decode + transform per sample. The
reference parallelizes with ``DataLoader(num_workers=16)`` forked workers
(ref: ResNet/pytorch/train.py:229-234); here a ``multiprocessing.Pool``
maps the decode+augment over each batch's files with per-sample seeded RNG
(deterministic under any worker count — the reference's loader was not).
"""

from __future__ import annotations

import multiprocessing as mp
from pathlib import Path

import numpy as np

from deepvision_tpu.data import transforms as T


def load_synset_maps(synsets_file: str | Path):
    """synsets.txt (one WNID per line, index order) -> (wnid->idx, idx->wnid)."""
    wnids = [l.strip() for l in Path(synsets_file).read_text().splitlines()
             if l.strip()]
    return {w: i for i, w in enumerate(wnids)}, wnids


class ImageNetFolderDataset:
    def __init__(self, image_dir: str | Path, synsets_file: str | Path,
                 transform: T.Compose, *, seed: int = 0):
        self.image_dir = Path(image_dir)
        self.wnid_to_idx, self.wnids = load_synset_maps(synsets_file)
        self.transform = transform
        self.seed = seed
        # filename 'n01440764_10026.JPEG' -> synset prefix
        # (ref: data_load.py:49-69)
        self.files = sorted(self.image_dir.glob("*.JPEG"))
        self.labels = np.array(
            [self.wnid_to_idx[f.name.split("_")[0]] for f in self.files],
            np.int32,
        )

    def __len__(self):
        return len(self.files)

    def load(self, i: int, epoch: int = 0) -> tuple[np.ndarray, int]:
        import cv2

        img = cv2.imread(str(self.files[i]))  # BGR
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        rng = np.random.default_rng(
            (self.seed * 1_000_003 + epoch) * 1_000_003 + i
        )
        return self.transform(rng, img), int(self.labels[i])


# Worker-process dataset handle: shipped ONCE via the pool initializer
# instead of pickling the (potentially 1.28M-file) dataset per sample.
_WORKER_DS: ImageNetFolderDataset | None = None


def _init_worker(ds: ImageNetFolderDataset):
    global _WORKER_DS
    _WORKER_DS = ds


def _load_one(args):
    i, epoch = args
    return _WORKER_DS.load(i, epoch)


class FolderLoader:
    """Batched parallel loader over an ImageNetFolderDataset."""

    def __init__(self, dataset: ImageNetFolderDataset, batch_size: int,
                 *, shuffle: bool = True, num_workers: int = 8,
                 drop_remainder: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.drop_remainder = drop_remainder
        self._pool = None

    def _get_pool(self):
        """Lazily create — and then REUSE across epochs — the worker
        pool. spawn, never the platform-default fork (jaxlint JX121):
        this loader runs inside training processes where jax/tf
        runtime threads already hold internal mutexes — a forked child
        inherits them locked with no owner thread and wedges on first
        use (the PR 2 tier-1 deadlock). spawn startup is seconds (a
        fresh interpreter per worker + the pickled dataset handle:
        paths + numpy labels + module-level transform classes, shipped
        once via the initializer), which is why the pool persists for
        the loader's lifetime instead of being rebuilt per epoch —
        work items carry (index, epoch), so workers are epoch-blind."""
        if self._pool is None:
            self._pool = mp.get_context("spawn").Pool(
                self.num_workers, initializer=_init_worker,
                initargs=(self.dataset,))
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool.close()
            self._pool.join()
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            if self._pool is not None:
                self._pool.terminate()
        except Exception:
            pass  # interpreter teardown: best-effort only

    def epoch(self, epoch: int = 0):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            np.random.default_rng(epoch).shuffle(order)
        end = n - n % self.batch_size if self.drop_remainder else n
        pool = self._get_pool() if self.num_workers > 1 else None
        for s in range(0, end, self.batch_size):
            idx = order[s : s + self.batch_size]
            work = [(int(i), epoch) for i in idx]
            if pool is not None:
                samples = pool.map(_load_one, work)
            else:
                samples = [self.dataset.load(i, e) for i, e in work]
            images = np.stack([im for im, _ in samples])
            labels = np.array([lb for _, lb in samples], np.int32)
            yield {"image": images, "label": labels}
