# jaxlint: disable-file=JX107
"""Device-side augmentation: jittable ops that run INSIDE the compiled
train step.

BENCH_r04 measured the system ~7x input-bound: the chip sustains 2579
img/s while the fed pipeline delivers ~358, because the host decodes,
augments, and normalizes to f32 before ``device_put`` — 4-byte pixels
over a 0.073 GB/s link from a 2-core host whose decode already caps at
~693 img/s. The fix is the TPU-pod playbook (PAPERS.md: MLPerf TPU-v3
pods, arXiv:1909.09756; pjit TPUv4, arXiv:2204.06514): the host does
pure I/O — decode + resize to **uint8 HWC** — and every per-element
math op (crop, flip, color jitter, normalize, mixup) moves into the
compiled step, where it is fused with the forward pass and costs HBM
bandwidth instead of host cycles and wire bytes.

Layout:

- deterministic cores (``crop``/``flip``/``color_jitter``/``mixup`` and
  the target twins ``flip_boxes``/``crop_boxes``/``flip_keypoints``/
  ``crop_keypoints``) take EXPLICIT decision arrays, so host-vs-device
  parity is testable op by op: sample decisions once, apply both the
  numpy f32 reference path (data/transforms.py) and this module, pin
  the difference (tests/test_device_aug.py);
- ``*_params`` samplers draw those decisions from a JAX PRNG key — the
  step threads its ``core.prng.KeySeq`` subkey through
  :func:`augment_step`, so chaos/preemption bit-determinism holds: the
  resumed run replays the same split chain and re-draws the SAME crops
  and flips (KeySeq.skip — the contract the Trainer's mid-epoch resume
  already relies on for dropout);
- :class:`DeviceAugment` composes the ops per model family
  (classification / detection / pose / gan), transforming detection
  boxes and pose keypoints CONSISTENTLY with the image crop/flip.

Color-jitter semantics are factor-for-factor identical to the PIL-
enhance twins (``transforms.apply_color_jitter`` / the tf.data
``imagenet.color_jitter``), including the round-through-uint8 step, so
the three implementations stay parity-testable against each other.
Normalization stays in ``ops/normalize.maybe_normalize`` (the steps
already call it); this module only re-rounds to uint8 after float ops
so the wire dtype contract ("uint8 in, normalize on device") survives
augmentation. (This file lives in ``data/`` for discoverability next
to its host twins, but it is DEVICE code called from inside the jitted
step — the JX107 jnp-in-data rule is disabled file-wide by design.)
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from deepvision_tpu.ops.normalize import maybe_normalize

__all__ = [
    "crop", "crop_params", "random_crop",
    "flip", "flip_params", "random_flip",
    "color_jitter", "jitter_params",
    "mixup_params", "mixup",
    "flip_boxes", "crop_boxes",
    "flip_keypoints", "crop_keypoints", "MPII_FLIP_PERM",
    "DeviceAugment", "augment_step",
]

# PIL/ITU-R 601 luma coefficients — must match transforms.py and
# data/imagenet.color_jitter exactly (parity pinned in tests)
_LUMA = (0.299, 0.587, 0.114)

# MPII joint order: r-ankle..r-hip(0-2), l-hip..l-ankle(3-5), pelvis,
# thorax, neck, head(6-9), r-wrist..r-shoulder(10-12),
# l-shoulder..l-wrist(13-15). A horizontal flip swaps left/right.
MPII_FLIP_PERM = (5, 4, 3, 2, 1, 0, 6, 7, 8, 9, 15, 14, 13, 12, 11, 10)


# --------------------------------------------------------------- crop


def crop_params(key: jax.Array, n: int, in_h: int, in_w: int,
                size: int) -> tuple[jax.Array, jax.Array]:
    """Per-sample crop offsets: (tops, lefts) int32 in
    [0, in_h-size] x [0, in_w-size]."""
    if size > in_h or size > in_w:
        raise ValueError(f"crop {size} exceeds canvas {in_h}x{in_w}")
    kt, kl = jax.random.split(key)
    tops = jax.random.randint(kt, (n,), 0, in_h - size + 1)
    lefts = jax.random.randint(kl, (n,), 0, in_w - size + 1)
    return tops, lefts


def crop(images: jax.Array, tops: jax.Array, lefts: jax.Array,
         size: int) -> jax.Array:
    """Per-sample ``size``² crop of a (B,H,W,C) batch at explicit
    offsets (dtype-preserving — uint8 in, uint8 out)."""
    c = images.shape[-1]

    def one(img, t, l):  # noqa: E741 - l(eft), symmetric with t(op)
        return jax.lax.dynamic_slice(img, (t, l, 0), (size, size, c))

    return jax.vmap(one)(images, tops, lefts)


def random_crop(key: jax.Array, images: jax.Array, size: int) -> jax.Array:
    b, h, w, _ = images.shape
    tops, lefts = crop_params(key, b, h, w, size)
    return crop(images, tops, lefts, size)


# --------------------------------------------------------------- flip


def flip_params(key: jax.Array, n: int, p: float = 0.5) -> jax.Array:
    """Per-sample horizontal-flip coins, (B,) bool."""
    return jax.random.uniform(key, (n,)) < p


def flip(images: jax.Array, flips: jax.Array) -> jax.Array:
    """Horizontal flip where ``flips`` (dtype-preserving)."""
    return jnp.where(flips[:, None, None, None],
                     images[:, :, ::-1, :], images)


def random_flip(key: jax.Array, images: jax.Array,
                p: float = 0.5) -> jax.Array:
    return flip(images, flip_params(key, images.shape[0], p))


# ------------------------------------------------------- color jitter


def jitter_params(key: jax.Array, n: int, brightness: float = 0.0,
                  contrast: float = 0.0, saturation: float = 0.0
                  ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-sample PIL-enhance factors, each U[max(0, 1-a), 1+a] (the
    transforms.ColorJitter._factor distribution); amount 0 pins 1.0."""
    ks = jax.random.split(key, 3)

    def factor(k, amount):
        if not amount:
            return jnp.ones((n,), jnp.float32)
        return jax.random.uniform(
            k, (n,), minval=max(0.0, 1.0 - amount), maxval=1.0 + amount)

    return (factor(ks[0], brightness), factor(ks[1], contrast),
            factor(ks[2], saturation))


def color_jitter(images: jax.Array, fb: jax.Array, fc: jax.Array,
                 fs: jax.Array) -> jax.Array:
    """Per-sample brightness/contrast/saturation with PIL-enhance
    semantics on [0,255] pixels — the vectorized twin of
    ``transforms.apply_color_jitter`` (brightness scale, contrast blend
    with the per-image grayscale mean, saturation blend per pixel).
    uint8 in -> round-then-clip uint8 out (matches the host twins'
    round-through-uint8; plain truncation would drift 1 LSB)."""
    was_uint8 = images.dtype == jnp.uint8
    coeffs = jnp.asarray(_LUMA, jnp.float32)
    img = images.astype(jnp.float32) * fb[:, None, None, None]
    gray = img @ coeffs  # (B,H,W)
    mean = gray.mean(axis=(1, 2))[:, None, None, None]
    img = mean * (1.0 - fc[:, None, None, None]) \
        + img * fc[:, None, None, None]
    gray = (img @ coeffs)[..., None]
    img = gray * (1.0 - fs[:, None, None, None]) \
        + img * fs[:, None, None, None]
    if was_uint8:
        return jnp.clip(jnp.round(img), 0.0, 255.0).astype(jnp.uint8)
    return img


# -------------------------------------------------------------- mixup


def mixup_params(key: jax.Array, n: int, alpha: float
                 ) -> tuple[jax.Array, jax.Array]:
    """One Beta(alpha, alpha) mixing weight per batch + a partner
    permutation (Zhang et al. 2018 — per-batch lambda, the reference
    implementation's choice)."""
    kp, kl = jax.random.split(key)
    perm = jax.random.permutation(kp, n)
    lam = jax.random.beta(kl, alpha, alpha)
    return perm, lam


def mixup(images: jax.Array, perm: jax.Array, lam: jax.Array) -> jax.Array:
    """``lam * x + (1-lam) * x[perm]`` in float; uint8 in -> uint8 out
    (<=0.5-LSB rounding — mixing commutes with the affine on-device
    normalization, so rounding here is the only divergence from an f32
    host mixup)."""
    was_uint8 = images.dtype == jnp.uint8
    x = images.astype(jnp.float32)
    mixed = lam * x + (1.0 - lam) * x[perm]
    if was_uint8:
        return jnp.clip(jnp.round(mixed), 0.0, 255.0).astype(jnp.uint8)
    return mixed


# -------------------------------------------------- detection targets


def flip_boxes(boxes: jax.Array, labels: jax.Array,
               flips: jax.Array) -> jax.Array:
    """Mirror xywh-normalized boxes for flipped samples: cx -> 1-cx on
    REAL rows (label >= 0); padding rows stay all-zero so the step's
    grid encoder keeps ignoring them."""
    real = (labels >= 0) & flips[:, None]
    cx = jnp.where(real, 1.0 - boxes[..., 0], boxes[..., 0])
    return jnp.concatenate([cx[..., None], boxes[..., 1:]], axis=-1)


def crop_boxes(boxes: jax.Array, labels: jax.Array, tops: jax.Array,
               lefts: jax.Array, in_h: int, in_w: int, size: int,
               min_extent: float = 1e-3
               ) -> tuple[jax.Array, jax.Array]:
    """Re-normalize xywh boxes (relative to an ``in_h``x``in_w`` canvas)
    to a per-sample ``size``² crop window; boxes are clipped to the
    window, and a box whose CENTER leaves the window (or whose clipped
    extent collapses below ``min_extent``) is invalidated — label -1,
    box zeroed — exactly what the host pipeline's bbox-preserving crop
    guarantees by construction."""
    ty = tops[:, None].astype(jnp.float32) / size
    lx = lefts[:, None].astype(jnp.float32) / size
    sx = in_w / size
    sy = in_h / size
    cx = boxes[..., 0] * sx - lx
    cy = boxes[..., 1] * sy - ty
    w = boxes[..., 2] * sx
    h = boxes[..., 3] * sy
    x1 = jnp.clip(cx - w / 2, 0.0, 1.0)
    y1 = jnp.clip(cy - h / 2, 0.0, 1.0)
    x2 = jnp.clip(cx + w / 2, 0.0, 1.0)
    y2 = jnp.clip(cy + h / 2, 0.0, 1.0)
    valid = ((labels >= 0)
             & (cx > 0.0) & (cx < 1.0) & (cy > 0.0) & (cy < 1.0)
             & (x2 - x1 > min_extent) & (y2 - y1 > min_extent))
    new = jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                    axis=-1)
    new = jnp.where(valid[..., None], new, 0.0)
    return new, jnp.where(valid, labels, -1)


# ------------------------------------------------------- pose targets


def flip_keypoints(kx: jax.Array, ky: jax.Array, v: jax.Array,
                   flips: jax.Array, perm=None
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Mirror normalized keypoints for flipped samples: kx -> 1-kx,
    with an optional left/right joint permutation (``MPII_FLIP_PERM``
    for the MPII order) applied to kx/ky/v consistently — a mirrored
    person's left wrist IS the right-wrist channel."""
    if perm is not None:
        perm = jnp.asarray(perm)
        kx_f, ky_f, v_f = kx[:, perm], ky[:, perm], v[:, perm]
    else:
        kx_f, ky_f, v_f = kx, ky, v
    f = flips[:, None]
    return (jnp.where(f, 1.0 - kx_f, kx),
            jnp.where(f, ky_f, ky),
            jnp.where(f, v_f, v))


def crop_keypoints(kx: jax.Array, ky: jax.Array, v: jax.Array,
                   tops: jax.Array, lefts: jax.Array,
                   in_h: int, in_w: int, size: int
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Re-normalize keypoints to a per-sample crop window; joints that
    leave the window lose visibility (the heatmap rasterizer then
    skips them, the same contract the host ROI crop upholds)."""
    nkx = (kx * in_w - lefts[:, None]) / size
    nky = (ky * in_h - tops[:, None]) / size
    inside = ((nkx >= 0.0) & (nkx <= 1.0)
              & (nky >= 0.0) & (nky <= 1.0))
    return nkx, nky, jnp.where(inside, v, 0)


# ------------------------------------------------------- composition


class DeviceAugment:
    """Per-family augmentation pipeline compiled into the step.

    ``augment = DeviceAugment("classification", crop=224, flip=True)``
    then ``augment(batch, key) -> batch``: every op draws its per-sample
    decisions from subkeys of ``key`` (one ``jax.random.split`` fan-out,
    so the op set — not the batch — determines the split chain), crops
    from the host-shipped uint8 canvas when ``crop`` is set, flips
    image+targets together, jitters, mixes up (classification only —
    emits ``label_b``/``lam`` consumed by
    ``steps.classification_train_step``), and leaves normalization to
    the step's ``maybe_normalize`` unless ``normalize`` is given (the
    GAN steps don't normalize, so the "gan" family passes "tanh").

    Families and their target handling:

    - ``classification``: {'image','label'} — crop/flip/jitter/mixup;
    - ``detection``: {'image','boxes','label'} — crop and flip remap
      the xywh boxes (out-of-window boxes are invalidated to -1);
    - ``pose``: {'image','kx','ky','v'} — crop and flip remap the
      keypoints (``flip_pairs`` swaps left/right joint channels;
      off-window joints lose visibility);
    - ``gan``: {'a','b'} or {'image'} — each domain crops/flips under
      its own fold_in-derived key.
    """

    FAMILIES = ("classification", "detection", "pose", "gan")

    def __init__(self, family: str = "classification", *,
                 crop: int | None = None, flip: bool = True,
                 flip_pairs=None, jitter: float = 0.0,
                 mixup: float = 0.0, normalize: str | None = None):
        if family not in self.FAMILIES:
            raise ValueError(f"unknown family {family!r}; "
                             f"one of {self.FAMILIES}")
        if mixup and family != "classification":
            raise ValueError("mixup mixes labels pairwise — it is a "
                             "classification-only augmentation")
        self.family = family
        self.crop = crop
        self.flip = flip
        self.flip_pairs = flip_pairs
        self.jitter = float(jitter)
        self.mixup = float(mixup)
        self.normalize = normalize

    def __repr__(self):  # shows up in compiled-step debug names
        on = [f"crop={self.crop}" if self.crop else None,
              "flip" if self.flip else None,
              f"jitter={self.jitter}" if self.jitter else None,
              f"mixup={self.mixup}" if self.mixup else None,
              f"normalize={self.normalize}" if self.normalize else None]
        return (f"DeviceAugment({self.family}, "
                + ", ".join(o for o in on if o) + ")")

    # one subkey per op slot, fan-out fixed by the CONFIG (not by which
    # ops fire), so toggling e.g. jitter never re-deals the flip coins
    _SLOTS = ("crop", "flip", "jitter", "mixup")

    def _keys(self, key: jax.Array) -> dict:
        subs = jax.random.split(key, len(self._SLOTS))
        return dict(zip(self._SLOTS, subs))

    def __call__(self, batch: dict, key: jax.Array) -> dict:
        batch = dict(batch)
        if self.family == "gan":
            for i, name in enumerate(k for k in ("a", "b", "image")
                                     if k in batch):
                batch[name] = self._image_only(
                    batch[name], jax.random.fold_in(key, i))
            return batch
        k = self._keys(key)
        images = batch["image"]
        b, in_h, in_w = images.shape[:3]

        if self.crop is not None:
            tops, lefts = crop_params(k["crop"], b, in_h, in_w, self.crop)
            images = crop(images, tops, lefts, self.crop)
            if self.family == "detection":
                batch["boxes"], batch["label"] = crop_boxes(
                    batch["boxes"], batch["label"], tops, lefts,
                    in_h, in_w, self.crop)
            elif self.family == "pose":
                batch["kx"], batch["ky"], batch["v"] = crop_keypoints(
                    batch["kx"], batch["ky"], batch["v"], tops, lefts,
                    in_h, in_w, self.crop)
        if self.flip:
            flips = flip_params(k["flip"], b)
            images = flip(images, flips)
            if self.family == "detection":
                batch["boxes"] = flip_boxes(batch["boxes"],
                                            batch["label"], flips)
            elif self.family == "pose":
                batch["kx"], batch["ky"], batch["v"] = flip_keypoints(
                    batch["kx"], batch["ky"], batch["v"], flips,
                    self.flip_pairs)
        if self.jitter:
            fb, fc, fs = jitter_params(k["jitter"], b, self.jitter,
                                       self.jitter, self.jitter)
            images = color_jitter(images, fb, fc, fs)
        if self.mixup:
            perm, lam = mixup_params(k["mixup"], b, self.mixup)
            images = mixup(images, perm, lam)
            batch["label_b"] = batch["label"][perm]
            batch["lam"] = lam
        if self.normalize is not None:
            images = maybe_normalize(images, self.normalize)
        batch["image"] = images
        return batch

    def _image_only(self, images: jax.Array, key: jax.Array) -> jax.Array:
        k = self._keys(key)
        if self.crop is not None:
            images = random_crop(k["crop"], images, self.crop)
        if self.flip:
            images = random_flip(k["flip"], images)
        if self.jitter:
            fb, fc, fs = jitter_params(k["jitter"], images.shape[0],
                                       self.jitter, self.jitter,
                                       self.jitter)
            images = color_jitter(images, fb, fc, fs)
        if self.normalize is not None:
            images = maybe_normalize(images, self.normalize)
        return images


def augment_step(step_fn: Callable, augment: DeviceAugment) -> Callable:
    """Fuse ``augment`` into ``step_fn``: the wrapped step splits its
    KeySeq subkey once — augmentation stream and dropout stream stay
    independent — and runs the augmentation INSIDE the same XLA program
    as forward/backward (one fusion, zero extra host round trips).
    ``functools.wraps`` keeps the step-function name so the jaxlint
    step-naming contracts (JX111/JX112 knobs) still match."""

    @functools.wraps(step_fn)
    def step(state, batch, key):
        k_aug, k_step = jax.random.split(key)
        return step_fn(state, augment(batch, k_aug), k_step)

    return step
