"""GAN input pipelines.

- CycleGAN unpaired A/B stream: image-only TFRecords (our builders'
  schema, data/builders/gan.py) → flip / resize-286 / random-crop-256 /
  [-1, 1], A and B zipped per step — behavior parity with
  ref: CycleGAN/tensorflow/train.py:85-118.
- DCGAN uses the MNIST loaders (data/mnist.py) normalized to [-1, 1]
  (ref: DCGAN/tensorflow/main.py:24-29).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from deepvision_tpu.data.image_io import tf_wire_uint8
from deepvision_tpu.data.padding import iter_tf_batches


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    return tf


def _parse_and_augment(size: int, is_training: bool,
                       device_aug: bool = False):
    """``device_aug``: the SPLIT-pipeline host stage — decode + resize
    to the ``size+30`` canvas only, **uint8 out**; flip, random crop,
    and the [-1,1] scale run inside the compiled GAN step
    (``device_aug.DeviceAugment("gan", crop=size, normalize="tanh")``,
    wired by train.py ``--device-aug``). ~1.3x more spatial wire pixels
    (canvas vs crop) but 4x fewer bytes each — a ~3x net wire win plus
    the host offload."""
    tf = _tf()

    def prep(serialized):
        feats = tf.io.parse_single_example(
            serialized,
            {"image/encoded": tf.io.FixedLenFeature([], tf.string)},
        )
        image = tf.io.decode_jpeg(feats["image/encoded"], channels=3)
        if is_training and device_aug:
            image = tf.image.resize(
                tf.cast(image, tf.float32), [size + 30, size + 30]
            )
            return tf_wire_uint8(tf, image)
        if is_training:
            image = tf.image.random_flip_left_right(image)
            image = tf.image.resize(
                tf.cast(image, tf.float32), [size + 30, size + 30]
            )
            image = tf.image.random_crop(image, [size, size, 3])
        else:
            image = tf.image.resize(tf.cast(image, tf.float32),
                                    [size, size])
        return image / 127.5 - 1.0

    return prep


def make_cyclegan_dataset(
    pattern_a: str,
    pattern_b: str,
    batch_size: int,
    size: int = 256,
    *,
    is_training: bool = True,
    shuffle_buffer: int = 1000,
    seed: int = 0,
    device_aug: bool = False,
):
    """Unpaired zip of the two domains. In training mode both domains
    ``repeat()``, so the shorter one cycles and an epoch covers the longer
    one (standard unpaired semantics; the ref zips raw, truncating to the
    shorter). In eval mode (``is_training=False``) the zip IS raw and
    truncates to the shorter domain — matching the reference's inference
    behavior."""
    tf = _tf()
    prep = _parse_and_augment(size, is_training, device_aug)

    def one(pattern):
        files = tf.data.Dataset.list_files(pattern, shuffle=is_training,
                                           seed=seed)
        ds = tf.data.TFRecordDataset(
            files, num_parallel_reads=tf.data.AUTOTUNE
        )
        if is_training:
            # epoch-seeded: deterministic order restore across resumes
            ds = ds.shuffle(shuffle_buffer, seed=seed).repeat()
        return ds.map(prep, num_parallel_calls=tf.data.AUTOTUNE)

    ds = tf.data.Dataset.zip((one(pattern_a), one(pattern_b)))
    ds = ds.batch(batch_size, drop_remainder=True)
    return ds.prefetch(tf.data.AUTOTUNE)


def make_cyclegan_data(
    data_dir: str, batch_size: int, size: int = 256,
    *, steps_per_epoch: int, device_aug: bool = False,
):
    """-> train_data(epoch) iterator of {'a','b'} batches."""
    d = Path(data_dir)

    def train_data(epoch: int):
        ds = make_cyclegan_dataset(
            str(d / "trainA-*"), str(d / "trainB-*"), batch_size, size,
            seed=epoch, device_aug=device_aug,
        )
        return iter_tf_batches(ds, ("a", "b"), limit=steps_per_epoch)

    return train_data


def synthetic_unpaired(n: int = 64, size: int = 64, seed: int = 0):
    """Hermetic unpaired domains with a learnable mapping: domain A =
    bright squares, domain B = the same distribution color-inverted."""
    rng = np.random.default_rng(seed)
    a = rng.normal(0.0, 0.05, (n, size, size, 3)).astype(np.float32)
    b = rng.normal(0.0, 0.05, (n, size, size, 3)).astype(np.float32)
    for i in range(n):
        x1, y1 = rng.integers(4, size // 2, 2)
        w = rng.integers(size // 4, size // 2)
        a[i, y1:y1 + w, x1:x1 + w, :] += 0.9
        x1, y1 = rng.integers(4, size // 2, 2)
        b[i, y1:y1 + w, x1:x1 + w, :] -= 0.9
    return np.clip(a, -1, 1), np.clip(b, -1, 1)
