"""Pose input pipeline: MPII keypoint TFRecords → device batches.

Behavior parity with ref: Hourglass/tensorflow/preprocess.py —

- parse the per-person keypoint Example (our builders' schema,
  data/builders/pose.py, a repaired version of the reference's
  tfrecords_mpii.py:65-84 schema),
- person ROI crop: bounding box of the visible keypoints padded by
  ``margin × body_height`` (body_height = scale × 200 px, the MPII scale
  convention; ref: preprocess.py:43-88), margin drawn U(0.1, 0.3) when
  training (ref: :18),
- resize to 256², scale to [-1, 1] (ref: :25),
- keypoints re-normalized to the crop.

TPU-first divergence: the reference rasterizes per-joint Gaussian target
heatmaps here on the host with nested TensorArray scatter loops
(ref: :91-173). We emit the (K,) normalized keypoints + visibility instead;
heatmap rasterization is a broadcasted jnp op inside the jitted train step
(ops/heatmap.gaussian_heatmaps), so host work is O(K) per sample and the
targets never cross the host↔device boundary.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from deepvision_tpu.data.image_io import tf_wire_uint8
from deepvision_tpu.data.padding import iter_array_batches, iter_tf_batches

NUM_JOINTS = 16


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    return tf


def parse_pose_example(serialized):
    """One Example -> (u8 image, kx (K,), ky (K,), v (K,), scale ())."""
    tf = _tf()
    feats = tf.io.parse_single_example(
        serialized,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/person/keypoints/x": tf.io.VarLenFeature(tf.float32),
            "image/person/keypoints/y": tf.io.VarLenFeature(tf.float32),
            "image/person/keypoints/v": tf.io.VarLenFeature(tf.int64),
            "image/person/scale": tf.io.FixedLenFeature([], tf.float32),
        },
    )
    image = tf.io.decode_jpeg(feats["image/encoded"], channels=3)
    kx = tf.sparse.to_dense(feats["image/person/keypoints/x"])
    ky = tf.sparse.to_dense(feats["image/person/keypoints/y"])
    v = tf.cast(tf.sparse.to_dense(feats["image/person/keypoints/v"]),
                tf.int32)
    return image, kx, ky, v, feats["image/person/scale"]


def crop_person_roi(image, kx, ky, v, scale, margin):
    """Crop the visible-keypoint bbox + margin×body_height padding
    (ref: preprocess.py:43-88); returns (crop, kx', ky') re-normalized."""
    tf = _tf()
    shape = tf.shape(image)
    img_h = tf.cast(shape[0], tf.float32)
    img_w = tf.cast(shape[1], tf.float32)
    px = kx * img_w
    py = ky * img_h
    vis = v > 0
    # guard: if nothing is visible keep the full frame
    any_vis = tf.reduce_any(vis)
    big = tf.float32.max
    vx = tf.where(vis, px, tf.fill(tf.shape(px), big))
    vy = tf.where(vis, py, tf.fill(tf.shape(py), big))
    xmin = tf.cond(any_vis, lambda: tf.reduce_min(vx), lambda: 0.0)
    ymin = tf.cond(any_vis, lambda: tf.reduce_min(vy), lambda: 0.0)
    vx = tf.where(vis, px, tf.fill(tf.shape(px), -big))
    vy = tf.where(vis, py, tf.fill(tf.shape(py), -big))
    xmax = tf.cond(any_vis, lambda: tf.reduce_max(vx), lambda: img_w)
    ymax = tf.cond(any_vis, lambda: tf.reduce_max(vy), lambda: img_h)

    body_height = scale * 200.0  # MPII scale convention (ref: :53)
    pad = body_height * margin
    x1 = tf.cast(tf.maximum(xmin - pad, 0.0), tf.int32)
    y1 = tf.cast(tf.maximum(ymin - pad, 0.0), tf.int32)
    x2 = tf.cast(tf.minimum(xmax + pad, img_w), tf.int32)
    y2 = tf.cast(tf.minimum(ymax + pad, img_h), tf.int32)
    x2 = tf.maximum(x2, x1 + 1)
    y2 = tf.maximum(y2, y1 + 1)

    crop = image[y1:y2, x1:x2, :]
    new_w = tf.cast(x2 - x1, tf.float32)
    new_h = tf.cast(y2 - y1, tf.float32)
    nkx = (px - tf.cast(x1, tf.float32)) / new_w
    nky = (py - tf.cast(y1, tf.float32)) / new_h
    return crop, nkx, nky


def to_model_inputs(image, kx, ky, v, size: int, as_uint8: bool = False):
    """resize to size² + [-1,1] scale; fixed (K,) keypoint shapes.

    ``as_uint8`` ships rounded uint8 pixels (4x less wire traffic); the
    steps' ``maybe_normalize(…, "tanh")`` scales on device."""
    tf = _tf()
    image = tf.image.resize(tf.cast(image, tf.float32), [size, size])
    if as_uint8:
        image = tf_wire_uint8(tf, image)
    else:
        image = image / 127.5 - 1.0

    def fix(t, dtype):
        t = t[:NUM_JOINTS]
        t = tf.pad(t, [[0, NUM_JOINTS - tf.shape(t)[0]]])
        t.set_shape([NUM_JOINTS])
        return tf.cast(t, dtype)

    return (image, fix(kx, tf.float32), fix(ky, tf.float32),
            fix(v, tf.int32))


def make_pose_dataset(
    file_pattern: str,
    batch_size: int,
    size: int = 256,
    *,
    is_training: bool,
    shuffle_buffer: int = 1000,
    num_process: int = 1,
    process_index: int = 0,
    seed: int = 0,
    as_uint8: bool = False,
):
    """``as_uint8`` ships uint8 pixels (normalize-on-device wire
    contract). The ROI crop stays host-side — its window is the
    per-person visible-keypoint bbox, dynamic-shaped by nature; the
    device stage (``DeviceAugment("pose")``, train.py ``--device-aug``)
    adds the left/right flip the reference pipeline lacks, with the
    MPII joint-channel swap applied consistently."""
    tf = _tf()
    files = tf.data.Dataset.list_files(
        file_pattern, shuffle=is_training, seed=seed
    )
    if num_process > 1:
        files = files.shard(num_process, process_index)
    ds = tf.data.TFRecordDataset(files, num_parallel_reads=tf.data.AUTOTUNE)
    if is_training:
        # epoch-seeded: deterministic order restore across resumes
        ds = ds.shuffle(shuffle_buffer, seed=seed).repeat()

    def prep(serialized):
        image, kx, ky, v, scale = parse_pose_example(serialized)
        if is_training:
            margin = tf.random.uniform([], 0.1, 0.3)  # ref: :18
        else:
            margin = tf.constant(0.2)  # ref default (ref: :43)
        image, kx, ky = crop_person_roi(image, kx, ky, v, scale, margin)
        return to_model_inputs(image, kx, ky, v, size, as_uint8)

    ds = ds.map(prep, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(batch_size, drop_remainder=is_training)
    return ds.prefetch(tf.data.AUTOTUNE)


def synthetic_pose(
    n: int = 128, size: int = 64, num_joints: int = NUM_JOINTS, seed: int = 0
):
    """Learnable synthetic pose set (hermetic tests, zero egress): each
    image carries one bright blob per visible joint in that joint's color
    channel slot; returns ({-1,1} images, kx, ky, v)."""
    rng = np.random.default_rng(seed)
    images = rng.normal(0.0, 0.05, size=(n, size, size, 3)).astype(
        np.float32
    )
    kx = rng.uniform(0.15, 0.85, size=(n, num_joints)).astype(np.float32)
    ky = rng.uniform(0.15, 0.85, size=(n, num_joints)).astype(np.float32)
    v = (rng.uniform(size=(n, num_joints)) > 0.2).astype(np.int32)
    r = max(size // 32, 1)
    for i in range(n):
        for j in range(num_joints):
            if not v[i, j]:
                continue
            cx, cy = int(kx[i, j] * size), int(ky[i, j] * size)
            images[i, max(cy - r, 0):cy + r + 1,
                   max(cx - r, 0):cx + r + 1, j % 3] = 1.0
    return images, kx, ky, v


def synthetic_pose_batches(images, kx, ky, v, batch_size, *, rng=None,
                           drop_remainder=True):
    """Epoch iterator over the synthetic arrays (mask-padded eval tail)."""
    return iter_array_batches(
        {"image": images, "kx": kx, "ky": ky, "v": v}, batch_size,
        rng=rng, drop_remainder=drop_remainder,
    )


def make_pose_data(
    data_dir: str, batch_size: int, size: int = 256,
    *, train_pattern: str = "train-*", val_pattern: str = "val-*",
    steps_per_epoch: int, device_aug: bool = False,
):
    """-> (train_data(epoch)->iter, val_data()->iter, steps_per_epoch).

    Multi-process contract = data/imagenet.make_imagenet_data's:
    ``batch_size`` is GLOBAL; training file-shards per process and
    batches the local share; validation streams the SAME full set per
    process at the global batch and slices its own row block (file
    sharding there would deadlock the collective eval on uneven
    shard sizes)."""
    import jax

    d = Path(data_dir)
    keys = ("image", "kx", "ky", "v")
    nproc = jax.process_count()
    pid = jax.process_index()
    if batch_size % nproc:
        raise ValueError(f"global batch {batch_size} not divisible by "
                         f"{nproc} processes")
    local_bs = batch_size // nproc

    def train_data(epoch: int):
        ds = make_pose_dataset(
            str(d / train_pattern), local_bs, size, is_training=True,
            num_process=nproc, process_index=pid, seed=epoch,
            as_uint8=device_aug,
        )
        return iter_tf_batches(ds, keys, limit=steps_per_epoch)

    def val_data():
        ds = make_pose_dataset(
            str(d / val_pattern), batch_size, size, is_training=False
        )
        for batch in iter_tf_batches(ds, keys, pad_to=batch_size):
            yield {k: v[pid * local_bs:(pid + 1) * local_bs]
                   for k, v in batch.items()}

    return train_data, val_data, steps_per_epoch
