"""MNIST idx-format loader + synthetic stand-in.

Parses the raw idx byte format exactly like the reference's ``MnistDataset``
(magic check, big-endian dims, 28x28 uint8 → padded 32x32 float, /255
normalize — ref: LeNet/pytorch/data_load.py:12-57), but vectorized with
numpy instead of per-sample Python. Output layout is NHWC (B, 32, 32, 1).

``synthetic_mnist`` generates a deterministic learnable toy set (class-
dependent blob positions) for hermetic tests — the environment has no
network egress, so tests never rely on downloaded data.
"""

from __future__ import annotations

import gzip
import struct
from pathlib import Path

import numpy as np

from deepvision_tpu.data.padding import pad_partial_batch


def _read_idx(path: str | Path) -> np.ndarray:
    p = Path(path)
    opener = gzip.open if p.suffix == ".gz" else open
    with opener(p, "rb") as f:
        data = f.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zeros != 0:
        raise ValueError(f"{p}: bad idx magic")
    if dtype_code != 0x08:  # uint8, the only type MNIST uses
        raise ValueError(f"{p}: unsupported idx dtype 0x{dtype_code:02x}")
    dims = struct.unpack(f">{ndim}I", data[4 : 4 + 4 * ndim])
    arr = np.frombuffer(data, np.uint8, offset=4 + 4 * ndim)
    return arr.reshape(dims)


def load_mnist_idx(images_path, labels_path,
                   pad_to_32: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """-> (images (N,32,32,1) float32 in [0,1], labels (N,) int32).

    ``pad_to_32=False`` keeps the native 28² (DCGAN geometry —
    ref: DCGAN/tensorflow/main.py:24-26).
    """
    images = _read_idx(images_path).astype(np.float32) / 255.0
    labels = _read_idx(labels_path).astype(np.int32)
    if pad_to_32:
        # pad 28 -> 32 as the reference does (ref: LeNet/pytorch/data_load.py)
        images = np.pad(images, ((0, 0), (2, 2), (2, 2)))
    return images[..., None], labels


def synthetic_mnist(
    n: int = 512, num_classes: int = 10, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic digits: one bright 8x8 blob per class position."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = rng.normal(0.1, 0.05, size=(n, 32, 32, 1)).astype(np.float32)
    # class k lights a blob at a fixed grid cell
    rows, cols = labels // 4, labels % 4
    for i in range(n):
        r, c = rows[i] * 8 + 2, cols[i] * 8 + 2
        images[i, r : r + 8, c : c + 8, 0] += 1.0
    return images, labels


def batches(images, labels, batch_size, *, rng=None, drop_remainder=True):
    """Simple epoch iterator over host arrays.

    ``drop_remainder=False`` (the eval path) pads the final partial batch to
    ``batch_size`` and attaches a 0/1 ``mask`` to every batch, so the whole
    set is evaluated under one compiled shape.
    """
    n = len(images)
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    end = n - n % batch_size if drop_remainder else n
    for s in range(0, end, batch_size):
        sel = idx[s : s + batch_size]
        batch = {"image": images[sel], "label": labels[sel]}
        if not drop_remainder:
            batch = pad_partial_batch(batch, batch_size)
        yield batch
