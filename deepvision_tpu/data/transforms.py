"""Numpy augmentation library — parity with the reference's PT transforms.

The reference defines sample-dict transforms (Rescale/RandomCrop/CenterCrop/
RandomHorizontalFlip/ToTensor/Normalize/ColorJitter —
ref: ResNet/pytorch/data_load.py:72-296). Here they are pure numpy callables
``(rng, image) -> image`` over HWC uint8/f32 arrays, composable with
``Compose``; used by the folder-dataset path (data/folder.py) and by
converter-parity tests. The hot TPU path uses the tf.data twin
(data/imagenet.py) — these exist for semantic parity checking and CPU-side
tooling, not for feeding pods.

Two-stage split (ISSUE 7): the ``imagenet_*_transform`` composes below
are the FULL host pipeline (decode -> ... -> normalized f32) — the
reference-parity path, 4-byte pixels on the wire. The
``imagenet_host_transform`` compose is the HOST STAGE of the split
pipeline: decode + resize + center canvas crop, **uint8 HWC out**
(1-byte pixels, 4x less H2D traffic); every remaining op — random
crop, flip, color jitter, normalize, mixup — runs inside the compiled
step via the device twin (``data/device_aug.py``), keyed through
``core.prng.KeySeq``. The numpy ops here double as the parity oracle:
``tests/test_device_aug.py`` pins host-vs-device agreement op by op at
tolerance, with shared explicit decisions.

Divergence note (documented, ref parity kept where it matters): the PT
ColorJitter does a PIL round-trip (ref: data_load.py:278-296); here the
equivalent brightness/contrast/saturation jitters are computed directly in
float, which matches PIL's enhance semantics.
"""

from __future__ import annotations

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover
    cv2 = None


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, rng: np.random.Generator, image: np.ndarray):
        for t in self.transforms:
            image = t(rng, image)
        return image


class Rescale:
    """Aspect-preserving resize of the SHORTER side to ``size``
    (ref: data_load.py Rescale)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, rng, image):
        h, w = image.shape[:2]
        scale = self.size / min(h, w)
        new_h, new_w = int(round(h * scale)), int(round(w * scale))
        if cv2 is not None:
            return cv2.resize(image, (new_w, new_h),
                              interpolation=cv2.INTER_LINEAR)
        # nearest-neighbor numpy fallback
        ys = (np.arange(new_h) * h / new_h).astype(int)
        xs = (np.arange(new_w) * w / new_w).astype(int)
        return image[ys][:, xs]


class RandomCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, rng, image):
        h, w = image.shape[:2]
        top = int(rng.integers(0, h - self.size + 1))
        left = int(rng.integers(0, w - self.size + 1))
        return image[top : top + self.size, left : left + self.size]


class CenterCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, rng, image):
        h, w = image.shape[:2]
        top, left = (h - self.size) // 2, (w - self.size) // 2
        return image[top : top + self.size, left : left + self.size]


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, rng, image):
        if rng.random() < self.p:
            return image[:, ::-1]
        return image


class ToFloat:
    """uint8 HWC -> float32 [0,1]; grayscale -> 3 channels
    (ref: data_load.py ToTensor :183-189 minus the CHW transpose — the
    framework is NHWC)."""

    def __call__(self, rng, image):
        if image.ndim == 2:
            image = np.stack([image] * 3, axis=-1)
        elif image.shape[-1] == 1:
            image = np.repeat(image, 3, axis=-1)
        return image.astype(np.float32) / 255.0


class EnsureRGB:
    """Grayscale -> 3 channels, dtype preserved (the channel repair
    ToFloat performs, split out so the uint8 host stage can use it
    without the f32 conversion)."""

    def __call__(self, rng, image):
        if image.ndim == 2:
            image = np.stack([image] * 3, axis=-1)
        elif image.shape[-1] == 1:
            image = np.repeat(image, 3, axis=-1)
        return image


class ToUint8:
    """Round-then-clip to uint8 (identity on uint8 input) — the wire
    dtype contract of the split pipeline's host stage; matches the
    tf.data twin's ``tf.round`` + cast and PIL's own quantization."""

    def __call__(self, rng, image):
        if image.dtype == np.uint8:
            return image
        return np.clip(np.round(image), 0, 255).astype(np.uint8)


class Normalize:
    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, rng, image):
        return (image - self.mean) / self.std


def apply_color_jitter(img: np.ndarray, fb: float, fc: float, fs: float):
    """Deterministic PIL-enhance-semantics core on f32: brightness scale,
    contrast blend with the grayscale mean, saturation blend per pixel.
    The tf.data twin (data/imagenet.color_jitter) mirrors this
    factor-for-factor — parity pinned in tests."""
    coeffs = np.array([0.299, 0.587, 0.114], np.float32)
    img = img * fb
    gray = img @ coeffs
    img = gray.mean() * (1 - fc) + img * fc
    gray = (img @ coeffs)[..., None]
    img = gray * (1 - fs) + img * fs
    return img


class ColorJitter:
    """brightness/contrast/saturation jitter with PIL-enhance semantics
    (factor sampled in [max(0, 1-x), 1+x])."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    @staticmethod
    def _factor(rng, amount):
        return float(rng.uniform(max(0.0, 1 - amount), 1 + amount))

    def __call__(self, rng, image):
        fb = self._factor(rng, self.brightness) if self.brightness else 1.0
        fc = self._factor(rng, self.contrast) if self.contrast else 1.0
        fs = self._factor(rng, self.saturation) if self.saturation else 1.0
        img = apply_color_jitter(image.astype(np.float32), fb, fc, fs)
        if image.dtype == np.uint8:
            # round-then-clip matches the tf.data twin (tf.round) and PIL;
            # plain astype would truncate and drift 1 LSB
            return np.clip(np.round(img), 0, 255).astype(np.uint8)
        return img


# Standard train/eval pipelines matching the ref's Compose stacks
# (ref: ResNet/pytorch/train.py:315-331). The resize floor scales with the
# crop (0.875 rule) so >256 crops (Inception V3) work.
def _resize_min(size: int) -> int:
    return max(256, round(size / 0.875))


def imagenet_train_transform(size: int = 224) -> Compose:
    return Compose([
        Rescale(_resize_min(size)),
        RandomCrop(size),
        RandomHorizontalFlip(),
        ColorJitter(0.4, 0.4, 0.4),
        ToFloat(),
        Normalize((0.485, 0.456, 0.406), (0.229, 0.224, 0.225)),
    ])


def imagenet_eval_transform(size: int = 224) -> Compose:
    return Compose([
        Rescale(_resize_min(size)),
        CenterCrop(size),
        ToFloat(),
        Normalize((0.485, 0.456, 0.406), (0.229, 0.224, 0.225)),
    ])


def imagenet_host_transform(size: int = 224) -> Compose:
    """HOST STAGE of the split pipeline, numpy twin: decode-side work
    only — resize the shorter side and center-crop the fixed square
    **canvas** (``_resize_min(size)``², uint8 HWC). Everything
    stochastic (random ``size``² crop, flip, jitter, normalize, mixup)
    runs on device from this canvas
    (``device_aug.DeviceAugment("classification", crop=size)`` — the
    composition train.py's ``--device-aug`` builds), so the host stays
    pure I/O and the wire carries 1-byte pixels. The tf.data twin is
    ``imagenet.make_dataset(host_stage="canvas")``; pass this as the
    folder dataset's transform (data/folder.py) for the same split on
    the cv2 path, and the parity tests use it as the host-stage
    oracle's input producer."""
    return Compose([
        Rescale(_resize_min(size)),
        CenterCrop(_resize_min(size)),
        EnsureRGB(),
        ToUint8(),
    ])
