"""Dependency-free TFRecord + tf.train.Example codec.

The reference's entire dataset layer is TFRecord-based (ImageNet builder —
ref: Datasets/ILSVRC2012/build_imagenet_tfrecord.py:216-231; VOC/COCO/MPII —
ref: Datasets/VOC2007/tfrecords.py:70-95). The training hot path reads these
through ``tf.data`` (data/imagenet.py), but the framework also carries this
pure-Python codec so that builders, tests, and tools work without TensorFlow
and so the on-disk format is a documented contract rather than an opaque
dependency.

Formats implemented from their public specs:
- TFRecord framing: ``<u64 len><u32 masked-crc32c(len)><bytes><u32
  masked-crc32c(bytes)>`` with the masked Castagnoli CRC.
- ``tf.train.Example`` protobuf wire format (varint/length-delimited
  fields only; FloatList/Int64List accept both packed and unpacked).
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Iterator

# --------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven
# --------------------------------------------------------------------------

try:  # fast C path (bundled with TF distributions); pure-python fallback
    import google_crc32c as _gcrc
except ImportError:  # pragma: no cover
    _gcrc = None

_CRC_TABLES = None


def _crc_tables():
    """Slicing-by-8 tables (8x256) for the pure-python fallback."""
    global _CRC_TABLES
    if _CRC_TABLES is None:
        poly = 0x82F63B78
        base = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            base.append(c)
        tables = [base]
        for k in range(1, 8):
            prev = tables[k - 1]
            tables.append([base[prev[n] & 0xFF] ^ (prev[n] >> 8)
                           for n in range(256)])
        _CRC_TABLES = tables
    return _CRC_TABLES


def crc32c(data: bytes) -> int:
    if _gcrc is not None:
        return _gcrc.value(data)
    t = _crc_tables()
    crc = 0xFFFFFFFF
    mv = memoryview(data)
    n8 = len(mv) - len(mv) % 8
    for i in range(0, n8, 8):
        b0, b1, b2, b3, b4, b5, b6, b7 = mv[i : i + 8]
        crc ^= b0 | b1 << 8 | b2 << 16 | b3 << 24
        crc = (t[7][crc & 0xFF] ^ t[6][(crc >> 8) & 0xFF]
               ^ t[5][(crc >> 16) & 0xFF] ^ t[4][crc >> 24]
               ^ t[3][b4] ^ t[2][b5] ^ t[1][b6] ^ t[0][b7])
    for b in mv[n8:]:
        crc = t[0][(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------
# TFRecord framing
# --------------------------------------------------------------------------


def write_records(path: str | Path, records: list[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


def read_records(path: str | Path, *, verify: bool = True,
                 fault_injector=None) -> Iterator[bytes]:
    """``fault_injector`` (``resilience.FaultInjector``): the ``data_io``
    chaos site is consulted before every record read, so TFRecord-fed
    pipelines get the same deterministic transient-failure drills as the
    in-memory paths (the retry lives in the consumer — a generator that
    raised cannot be resumed, so injection happens per-record here and
    recovery wraps the pull, e.g. ``data/prefetch.DevicePrefetcher``)."""
    with open(path, "rb") as f:
        while True:
            if fault_injector is not None:
                fault_injector.check_io(what=f"record read ({path})")
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise IOError(f"{path}: truncated length header")
            (length,) = struct.unpack("<Q", header)
            (len_crc,) = struct.unpack("<I", f.read(4))
            data = f.read(length)
            (data_crc,) = struct.unpack("<I", f.read(4))
            if verify:
                if _masked_crc(header) != len_crc:
                    raise IOError(f"{path}: length CRC mismatch")
                if _masked_crc(data) != data_crc:
                    raise IOError(f"{path}: data CRC mismatch")
            yield data


# --------------------------------------------------------------------------
# Minimal protobuf wire codec for tf.train.Example
# --------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _field(num: int, wire: int) -> bytes:
    return _varint(num << 3 | wire)


def _ld(num: int, payload: bytes) -> bytes:  # length-delimited field
    return _field(num, 2) + _varint(len(payload)) + payload


class FloatList(list):
    """Typed wrapper: encodes as FloatList even when empty."""


class Int64List(list):
    """Typed wrapper: encodes as Int64List even when empty."""


class BytesList(list):
    """Typed wrapper: encodes as BytesList even when empty."""


def _encode_feature(value) -> bytes:
    """value: list of bytes/str -> BytesList; float -> FloatList;
    int -> Int64List.

    The typed wrappers (``FloatList``/``Int64List``/``BytesList``) are
    authoritative: they fix the wire type regardless of element Python
    types (``FloatList([3, 5])`` still encodes floats) and they are the
    only way to encode an intentionally-empty feature — an empty untyped
    list raises instead of guessing, so ``tf.io.parse`` with a typed
    feature spec never sees a wire-type flip between records.
    """
    if not isinstance(value, (list, tuple)):
        value = [value]

    def as_bytes():
        items = b"".join(
            _ld(1, v.encode() if isinstance(v, str) else v) for v in value
        )
        return _ld(1, items)  # BytesList at field 1

    def as_floats():
        packed = struct.pack(f"<{len(value)}f", *map(float, value))
        return _ld(2, _ld(1, packed))  # FloatList(packed) at field 2

    def as_ints():
        packed = b"".join(
            _varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in value
        )
        return _ld(3, _ld(1, packed))  # Int64List(packed) at field 3

    if isinstance(value, BytesList):
        return as_bytes()
    if isinstance(value, FloatList):
        return as_floats()
    if isinstance(value, Int64List):
        return as_ints()
    if not value:
        raise TypeError(
            "empty untyped feature list: wrap with tfrecord.FloatList/"
            "Int64List/BytesList to fix the wire type"
        )
    first = value[0]
    if isinstance(first, (bytes, str)):
        return as_bytes()
    if isinstance(first, float):
        return as_floats()
    if isinstance(first, (int, bool)):
        return as_ints()
    raise TypeError(f"unsupported feature value type {type(first)}")


def encode_example(features: dict) -> bytes:
    """dict -> serialized tf.train.Example bytes."""
    entries = b""
    for key in sorted(features):
        feat = _encode_feature(features[key])
        entry = _ld(1, key.encode()) + _ld(2, feat)
        entries += _ld(1, entry)  # map entry, Features.feature field 1
    return _ld(1, entries)  # Example.features field 1


def _iter_fields(buf: bytes):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        num, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos : pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos : pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield num, wire, val


def _decode_feature(buf: bytes):
    for num, _, val in _iter_fields(buf):
        if num == 1:  # BytesList
            return [v for n, _, v in _iter_fields(val) if n == 1]
        if num == 2:  # FloatList — packed or repeated
            floats = []
            for n, wire, v in _iter_fields(val):
                if n != 1:
                    continue
                if wire == 2:
                    floats.extend(
                        struct.unpack(f"<{len(v) // 4}f", v)
                    )
                else:  # wire 5: single fixed32
                    floats.append(struct.unpack("<f", v)[0])
            return floats
        if num == 3:  # Int64List — packed or repeated varints
            ints = []
            for n, wire, v in _iter_fields(val):
                if n != 1:
                    continue
                if wire == 2:
                    p = 0
                    while p < len(v):
                        x, p = _read_varint(v, p)
                        if x >= 1 << 63:
                            x -= 1 << 64
                        ints.append(x)
                else:
                    x = v if isinstance(v, int) else 0
                    if x >= 1 << 63:
                        x -= 1 << 64
                    ints.append(x)
            return ints
    return []


def decode_example(data: bytes) -> dict:
    """serialized tf.train.Example -> {key: list of values}."""
    out = {}
    for num, _, features_buf in _iter_fields(data):
        if num != 1:
            continue
        for n2, _, entry in _iter_fields(features_buf):
            if n2 != 1:
                continue
            key = None
            feat = b""
            for n3, _, v in _iter_fields(entry):
                if n3 == 1:
                    key = v.decode()
                elif n3 == 2:
                    feat = v
            if key is not None:
                out[key] = _decode_feature(feat)
    return out
