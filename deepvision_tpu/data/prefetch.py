"""Async device feed: threaded host prefetch, overlapped H2D
double-buffering, and per-stage input-wait telemetry.

The synchronous ``device_prefetch`` generator this replaces ran
``shard_batch`` on the CONSUMER thread, so the host copy + H2D dispatch
of batch N+1 serialized against step N instead of overlapping it — the
chip idled ~86% of every pipeline-fed step on the r4 bench (358 img/s
fed vs 2579 synthetic, below even the 483 img/s link ceiling). Here a
background producer thread pulls host batches, shards them onto the
mesh (``core.mesh.shard_batch`` — ``jax.device_put`` is asynchronous,
so the wire transfer is in flight the moment the call returns), and
keeps up to ``depth`` ready batches queued ahead of the consumer: the
classic MLPerf TPU input overlap (PAPERS.md "Scale MLPerf-0.6 models on
Google TPU-v3 Pods"), host-side analog of the reference's
``prefetch(1)`` (ref: ResNet/tensorflow/train.py:195-204).

Guarantees:

- **deterministic ordering** — one producer thread + a FIFO queue:
  batches come out exactly in upstream order (bit-exact resume and the
  epoch-seeded data order are unaffected);
- **bounded memory** — the producer blocks once ``depth`` batches wait
  unconsumed (backpressure, not unbounded staging);
- **exception propagation** — an upstream/producer exception is
  re-raised in the consumer at the point of the failed batch;
- **clean shutdown** — ``close()`` (also the generator-``close`` path
  of the compat wrapper and abandoning the iterator mid-epoch) stops
  and joins the producer thread; no threads leak across epochs.

:class:`FeedTelemetry` attributes wall time to the three pipeline
stages — producer host-wait (upstream iterator), consumer H2D-wait
(blocked on a ready device batch), and step-compute (consumer time
between batches) — so a fed-throughput gap is attributable to the
host pipeline, the wire, or the step instead of mysterious.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator

from deepvision_tpu.obs.metrics import (
    Counter,
    Histogram,
    Registry,
    default_registry,
)
from deepvision_tpu.obs.trace import span

__all__ = ["DevicePrefetcher", "FeedTelemetry", "device_prefetch"]

# pipeline stages, in snapshot()/summary() field order
_STAGES = ("host_wait", "shard", "h2d_wait", "step")


class FeedTelemetry:
    """Per-stage wall-time accounting for one feed run.

    Totals are in seconds; :meth:`summary` reports per-batch
    milliseconds plus ``input_wait_frac`` — the fraction of consumer
    wall time spent waiting on input rather than stepping (the number
    that says "link-bound" vs "compute-bound" at a glance).

    Each stage accumulator is an :class:`obs.metrics.Histogram` (one
    sample per accumulation, so the registry also serves per-batch
    stage quantiles) registered into ``registry`` under
    ``<namespace>_<stage>`` names (``input_host_wait`` …) — the same
    ``input_`` namespace ``train/loggers.input_wait_metrics`` has
    always used for the logged per-epoch means. The legacy attribute
    surface (``tel.h2d_wait_s += dt``, plain assignment included) is
    kept via properties over the histogram totals, and
    ``snapshot()``/``summary()`` are byte-compatible with the pre-obs
    shapes.
    """

    def __init__(self, registry: Registry | None = None,
                 namespace: str = "input"):
        reg = registry if registry is not None else default_registry()
        self._h = {s: reg.register(f"{namespace}_{s}", Histogram())
                   for s in _STAGES}
        self._batches = reg.register(f"{namespace}_batches", Counter())
        # wire accounting (ISSUE 7): what actually crosses the host->
        # device link, so the bench can PROVE the uint8 wire (4x fewer
        # bytes than f32 pixels) instead of asserting it. Bytes/images
        # are obs counters (`input_h2d_bytes`/`input_h2d_images` in the
        # registry snapshot); the wire dtype is the image leaf's dtype
        # string (not a metric — carried on the summary).
        self._h2d_bytes = reg.register(f"{namespace}_h2d_bytes", Counter())
        self._h2d_images = reg.register(f"{namespace}_h2d_images",
                                        Counter())
        self.wire_dtype: str | None = None

    def record_wire(self, batch) -> None:
        """Account one host batch about to cross the wire: total bytes
        over every leaf, image count, and the image leaves' dtype (the
        wire contract this PR's bench gates on). Called by the producer
        BEFORE ``shard_fn`` — these are the bytes ``device_put`` ships.

        "Images" are every (B,H,W,C) leaf's batch rows SUMMED — a
        CycleGAN batch carries TWO canvases ('a' and 'b'), and counting
        only one would double the reported bytes/image. Target leaves
        (labels (B,), boxes (B,M,4), keypoints (B,K)) are sub-4-D and
        never counted as images (their bytes still count — they cross
        the wire too)."""
        if isinstance(batch, dict):
            raw = list(batch.values())
        elif isinstance(batch, (list, tuple)):
            raw = list(batch)
        else:
            raw = [batch]
        leaves = [v for v in raw if hasattr(v, "nbytes")]
        if not leaves:
            return
        self._h2d_bytes.inc(int(sum(v.nbytes for v in leaves)))
        images = [v for v in leaves if getattr(v, "ndim", 0) >= 4]
        if not images:  # imageless batch: fall back to the lead leaf
            images = leaves[:1]
        self._h2d_images.inc(int(sum(len(v) for v in images)))
        self.wire_dtype = str(images[0].dtype)

    @property
    def h2d_bytes(self) -> int:
        return self._h2d_bytes.value

    @property
    def h2d_images(self) -> int:
        return self._h2d_images.value

    @property
    def h2d_bytes_per_image(self) -> float:
        """Measured wire bytes per image (0.0 until a batch crossed);
        constant across warmup/steady state for fixed batch geometry,
        so it needs no snapshot-delta scoping."""
        n = self._h2d_images.value
        return self._h2d_bytes.value / n if n else 0.0

    def reset(self) -> None:
        """Zero all counters. NOTE: while a producer thread is live this
        WRITE races its ``+=`` accumulations (a straddling
        read-modify-write can resurrect pre-reset totals) — to scope a
        summary to the steady state of a running feed, take a
        :meth:`snapshot` and pass it to ``summary(since=...)`` instead
        (reads only, race-free)."""
        for h in self._h.values():
            h.reset()
        self._batches.reset()
        self._h2d_bytes.reset()
        self._h2d_images.reset()
        self.wire_dtype = None

    # legacy accumulator surface: `tel.host_wait_s += dt` (the producer
    # and consumer hot paths) and plain assignment both route through
    # these properties — a += lands as ONE histogram sample of dt
    def _get_stage(self, stage: str) -> float:
        return self._h[stage].total

    def _set_stage(self, stage: str, value: float) -> None:
        h = self._h[stage]
        delta = value - h.total
        if delta < 0:  # direct rewind (reset-style assignment)
            h.reset()
            delta = value
        if delta:
            h.observe(delta)

    @property
    def batches(self) -> int:
        return self._batches.value

    @batches.setter
    def batches(self, value: int) -> None:
        delta = int(value) - self._batches.value
        if delta < 0:
            self._batches.reset()
            delta = int(value)
        if delta:
            self._batches.inc(delta)

    _FIELDS = ("host_wait_s", "shard_s", "h2d_wait_s", "step_s",
               "batches")

    def snapshot(self) -> dict:
        """Raw running totals — pair with ``summary(since=snapshot)`` to
        report only the interval after a warmup boundary without ever
        writing to counters a live producer thread is updating."""
        return {k: getattr(self, k) for k in self._FIELDS}

    def summary(self, since: dict | None = None,
                batches: int | None = None) -> dict:
        """``batches`` overrides the per-batch divisor: with a ``since``
        snapshot taken at a warmup boundary the internal fetch counter
        misses the boundary batch itself (its fetch preceded the
        snapshot) while that batch's step/H2D intervals land after it —
        a caller that knows the true measured-step count (bench: exactly
        FED_STEPS steps in the timed region) passes it here so the means
        reconcile with its own wall-clock rate."""
        cur = self.snapshot()
        if since is not None:
            cur = {k: cur[k] - since.get(k, 0) for k in cur}
        if batches is not None:
            cur["batches"] = batches
        n = max(1, cur["batches"])
        wait, busy = cur["h2d_wait_s"], cur["step_s"]
        return {
            "batches": cur["batches"],
            "host_wait_ms": round(cur["host_wait_s"] / n * 1e3, 3),
            "shard_ms": round(cur["shard_s"] / n * 1e3, 3),
            "h2d_wait_ms": round(cur["h2d_wait_s"] / n * 1e3, 3),
            "step_ms": round(cur["step_s"] / n * 1e3, 3),
            "input_wait_frac": (
                round(wait / (wait + busy), 4) if wait + busy > 0 else 0.0
            ),
            # wire accounting (whole-run, not since-scoped: bytes/image
            # is geometry, constant across warmup vs steady state)
            "h2d_bytes_per_image": round(self.h2d_bytes_per_image, 1),
            "wire_dtype": self.wire_dtype,
        }


# the four stage accumulators as attribute properties:
#   host_wait_s — producer blocked on the upstream iterator
#   shard_s     — host staging + async device_put dispatch
#   h2d_wait_s  — consumer blocked on a ready device batch
#   step_s      — consumer time between batches (the step)
for _stage in _STAGES:
    setattr(FeedTelemetry, f"{_stage}_s", property(
        lambda self, _s=_stage: self._get_stage(_s),
        lambda self, v, _s=_stage: self._set_stage(_s, v)))
del _stage


# queue item kinds (first tuple element)
_BATCH, _DONE, _ERROR = "batch", "done", "error"


class DevicePrefetcher:
    """Iterator of device-resident batches fed by a background thread.

    ``depth`` ready batches are kept queued ahead of the consumer (plus
    the one being sharded), each with its ``device_put`` already
    dispatched — so H2D wire time overlaps the running step instead of
    serializing with it. ``shard_fn`` overrides the placement call
    (default: ``core.mesh.shard_batch`` onto ``mesh``).
    """

    def __init__(self, batches: Iterable, mesh, *, depth: int = 2,
                 shard_fn: Callable | None = None,
                 telemetry: FeedTelemetry | None = None,
                 fault_injector=None, retry_policy=None,
                 retry_counters=None):
        """``retry_policy`` (``resilience.RecoveryPolicy``): transient
        ``OSError`` from the upstream pull is retried with bounded
        exponential backoff (counted in ``retry_counters.data_retries``)
        instead of killing the epoch — at pod scale a blipped storage
        read is routine, not fatal. ``fault_injector`` consults the
        deterministic ``data_io`` chaos site before each pull."""
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if shard_fn is None:
            from deepvision_tpu.core.mesh import shard_batch

            shard_fn = lambda b: shard_batch(mesh, b)  # noqa: E731
        self._shard = shard_fn
        self._injector = fault_injector
        self._retry_policy = retry_policy
        self._retry_counters = retry_counters
        self._src = iter(batches)
        self.telemetry = telemetry if telemetry is not None \
            else FeedTelemetry()
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._finished = False
        self._last_yield: float | None = None
        self._thread = threading.Thread(
            target=self._produce, name="device-prefetch", daemon=True
        )
        self._thread.start()

    # -- producer (background thread) -----------------------------------
    def _produce(self) -> None:
        tel = self.telemetry
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    with span("host_next", cat="feed"):
                        batch = self._next_batch()
                except StopIteration:
                    self._put((_DONE, None))
                    return
                t1 = time.perf_counter()
                tel.host_wait_s += t1 - t0
                tel.record_wire(batch)  # bytes/dtype BEFORE device_put
                with span("shard", cat="feed",
                          args={"h2d_bytes": tel.h2d_bytes,
                                "wire_dtype": tel.wire_dtype}):
                    device_batch = self._shard(batch)  # async H2D in flight
                tel.shard_s += time.perf_counter() - t1
                if not self._put((_BATCH, device_batch)):
                    return  # closed while we waited for queue space
        except BaseException as e:  # re-raised at the consumer's next pull
            self._put((_ERROR, e))

    def _next_batch(self):
        """One upstream pull, with the chaos hook and bounded transient-
        retry semantics from the ctor docstring. The injector consult
        runs BEFORE ``next`` so an injected failure never consumes a
        batch — a retried pull preserves the deterministic data order."""
        policy = self._retry_policy
        attempt = 0
        pull_errored = False  # did an OSError come from next() itself?
        last_err: OSError | None = None

        def admit_retry(e: OSError) -> None:
            nonlocal attempt, last_err
            if policy is None or attempt >= policy.max_data_retries:
                raise e
            last_err = e
            if self._retry_counters is not None:
                self._retry_counters.inc("data_retries")
            delay = policy.backoff(attempt)
            attempt += 1
            print(f"[data-retry] transient batch read error ({e}); "
                  f"retry {attempt}/{policy.max_data_retries} "
                  f"in {delay:.2f}s", flush=True)
            # stop-responsive backoff: close()/preemption must not ride
            # out the delay (or fire one more post-stop read)
            if self._stop.wait(delay):
                raise e

        while True:
            try:
                if self._injector is not None:
                    self._injector.check_io()
            except OSError as e:
                # pre-pull failure: the source is untouched, so a retry
                # is always sound — even on the exhaustion pull (the
                # retried next() then reports a CLEAN end of epoch)
                admit_retry(e)
                continue
            try:
                return next(self._src)
            except StopIteration:
                if pull_errored:
                    # a GENERATOR source that raised inside next() is
                    # closed: the retried pull reports exhaustion, which
                    # would silently truncate the epoch and let the run
                    # train on partial data — surface the real failure
                    # (only sources whose __next__ is itself retryable
                    # can be rescued once the pull has errored)
                    raise last_err
                raise
            except OSError as e:
                pull_errored = True
                admit_retry(e)

    def _put(self, item) -> bool:
        """Backpressured enqueue that stays responsive to close()."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer --------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter()
        if self._last_yield is not None:
            self.telemetry.step_s += t0 - self._last_yield
        with span("fetch", cat="feed"):  # consumer blocked on the queue
            kind, payload = self._q.get()
        self.telemetry.h2d_wait_s += time.perf_counter() - t0
        if kind is _DONE:
            self._finished = True
            self._last_yield = None
            raise StopIteration
        if kind is _ERROR:
            self._finished = True
            self._last_yield = None
            raise payload
        self.telemetry.batches += 1
        self._last_yield = time.perf_counter()
        return payload

    def restart_clock(self) -> None:
        """Restart the between-batch timer after a deliberate
        consumer-side stall (e.g. a warmup drain), so the stall is not
        charged to the next step interval's ``step_s``. Call from the
        consumer thread, like ``__next__``."""
        if self._last_yield is not None:
            self._last_yield = time.perf_counter()

    # -- lifecycle -------------------------------------------------------
    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer and join its thread. Idempotent; safe to
        call mid-stream (abandoning an epoch) or after exhaustion."""
        self._finished = True
        self._stop.set()
        # unblock a producer waiting on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        # wake a consumer blocked in _q.get() on another thread: the
        # stopped producer exits WITHOUT a sentinel, so without this a
        # cross-thread close would strand that consumer forever. (If the
        # producer slipped one last item in after the drain the queue
        # may be full — then that item itself wakes the consumer, and
        # the _finished flag ends iteration on its next call.)
        try:
            self._q.put_nowait((_DONE, None))
        except queue.Full:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        # GC-time safety net only — don't join from a finalizer; the
        # producer is a daemon thread and exits on the stop flag.
        # (getattr: __init__ may have raised before _stop existed)
        stop = getattr(self, "_stop", None)
        if stop is not None:
            stop.set()


def device_prefetch(batches: Iterable, mesh, *, depth: int = 2,
                    shard_fn: Callable | None = None,
                    telemetry: FeedTelemetry | None = None):
    """Generator-compat wrapper preserving the old
    ``data.device_put.device_prefetch`` contract (same batches, same
    order, ``depth`` transfers in flight ahead of the consumer) over the
    async prefetcher; abandoning the generator (``close()``/GC) stops
    and joins the producer thread."""
    pf = DevicePrefetcher(batches, mesh, depth=depth, shard_fn=shard_fn,
                          telemetry=telemetry)
    try:
        yield from pf
    finally:
        pf.close()
