"""Detection input pipeline: VOC/COCO TFRecords → padded device batches.

Behavior parity with ref: YOLO/tensorflow/preprocess.py:

- parse the detection Example schema (VarLen bbox/class lists — our
  builders' schema, data/builders/detection.py, mirrors the reference's,
  ref: preprocess.py:271-285),
- label-preserving random horizontal flip (ref: :37-50),
- bbox-preserving random crop: crop bounds drawn between the union of all
  boxes and the image border, boxes renormalized (ref: :52-119),
- resize to the square output shape, scale to [-1, 1] (/127.5 - 1,
  ref: :24-25).

TPU-first divergence: the reference encodes per-scale label GRIDS here on
the host with TensorArray loops (ref: :137-224). We instead emit padded
(MAX_BOXES, 4) xywh boxes + (MAX_BOXES,) labels; grid encoding happens
inside the jitted train step (ops/yolo_encode), so host work stays O(M)
and the scatter runs vectorized on device.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from deepvision_tpu.data.image_io import tf_wire_uint8
from deepvision_tpu.data.padding import pad_partial_batch

MAX_BOXES = 100  # matches the loss's true-box cap (ref: yolov3.py:448-454)


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    return tf


def parse_detection_example(serialized):
    """One Example -> (image u8 tensor, corners (N,4) f32, labels (N,) i32).

    Labels in our records are 1-based (0 reserved); shifted to 0-based here.
    """
    tf = _tf()
    feats = tf.io.parse_single_example(
        serialized,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/object/bbox/xmin": tf.io.VarLenFeature(tf.float32),
            "image/object/bbox/ymin": tf.io.VarLenFeature(tf.float32),
            "image/object/bbox/xmax": tf.io.VarLenFeature(tf.float32),
            "image/object/bbox/ymax": tf.io.VarLenFeature(tf.float32),
            "image/object/class/label": tf.io.VarLenFeature(tf.int64),
        },
    )
    image = tf.io.decode_jpeg(feats["image/encoded"], channels=3)
    boxes = tf.stack(
        [
            tf.sparse.to_dense(feats["image/object/bbox/xmin"]),
            tf.sparse.to_dense(feats["image/object/bbox/ymin"]),
            tf.sparse.to_dense(feats["image/object/bbox/xmax"]),
            tf.sparse.to_dense(feats["image/object/bbox/ymax"]),
        ],
        axis=-1,
    )
    labels = (
        tf.cast(
            tf.sparse.to_dense(feats["image/object/class/label"]), tf.int32
        )
        - 1
    )
    return image, boxes, labels


def random_flip(image, boxes, seed=None):
    """50% horizontal flip with box x-mirroring (ref: preprocess.py:37-50)."""
    tf = _tf()
    flip = tf.random.uniform([], seed=seed) < 0.5

    def do_flip():
        flipped = tf.image.flip_left_right(image)
        xmin, ymin, xmax, ymax = tf.unstack(boxes, axis=-1)
        return flipped, tf.stack(
            [1.0 - xmax, ymin, 1.0 - xmin, ymax], axis=-1
        )

    return tf.cond(flip, do_flip, lambda: (image, boxes))


def random_crop(image, boxes, seed=None):
    """50% bbox-preserving random crop (ref: preprocess.py:52-119): margins
    drawn between the union of all boxes and the image border; boxes
    renormalized to the crop."""
    tf = _tf()
    n = tf.shape(boxes)[0]
    crop = (tf.random.uniform([], seed=seed) < 0.5) & (n > 0)

    def do_crop():
        min_xmin = tf.reduce_min(boxes[:, 0])
        min_ymin = tf.reduce_min(boxes[:, 1])
        max_xmax = tf.reduce_max(boxes[:, 2])
        max_ymax = tf.reduce_max(boxes[:, 3])
        dx1 = tf.random.uniform([], 0.0, tf.maximum(min_xmin, 1e-6))
        dy1 = tf.random.uniform([], 0.0, tf.maximum(min_ymin, 1e-6))
        dx2 = tf.random.uniform([], 0.0, tf.maximum(1.0 - max_xmax, 1e-6))
        dy2 = tf.random.uniform([], 0.0, tf.maximum(1.0 - max_ymax, 1e-6))
        sx = 1.0 - dx1 - dx2
        sy = 1.0 - dy1 - dy2
        h = tf.cast(tf.shape(image)[0], tf.float32)
        w = tf.cast(tf.shape(image)[1], tf.float32)
        oh = tf.cast(dy1 * h, tf.int32)
        ow = tf.cast(dx1 * w, tf.int32)
        th = tf.cast(tf.math.ceil(sy * h), tf.int32)
        tw = tf.cast(tf.math.ceil(sx * w), tf.int32)
        th = tf.minimum(th, tf.shape(image)[0] - oh)
        tw = tf.minimum(tw, tf.shape(image)[1] - ow)
        # Renormalize boxes to the ACTUAL pixel window, not the fractional
        # draw: floor/ceil rounding above skews the window by up to a pixel
        # vs (dx1, sx), which drifted boxes on small images. The reference
        # computes both image and boxes in pixel space
        # (ref: preprocess.py:52-119); deriving the fractions back from
        # (ow, oh, tw, th) is the same arithmetic. The floor on the offsets
        # can only move the window outward on the min side, but the ceil'd
        # extent is clamped to the image, so clip the far edge to 1.
        fx1 = tf.cast(ow, tf.float32) / w
        fy1 = tf.cast(oh, tf.float32) / h
        fsx = tf.cast(tw, tf.float32) / w
        fsy = tf.cast(th, tf.float32) / h
        new_boxes = tf.stack(
            [
                (boxes[:, 0] - fx1) / fsx,
                (boxes[:, 1] - fy1) / fsy,
                tf.minimum((boxes[:, 2] - fx1) / fsx, 1.0),
                tf.minimum((boxes[:, 3] - fy1) / fsy, 1.0),
            ],
            axis=-1,
        )
        return image[oh : oh + th, ow : ow + tw, :], new_boxes

    return tf.cond(crop, do_crop, lambda: (image, boxes))


def to_model_inputs(image, boxes, labels, size: int,
                    as_uint8: bool = False):
    """resize + [-1,1] scale + corners→xywh + pad to MAX_BOXES.

    ``as_uint8`` ships rounded uint8 pixels instead (4x less wire
    traffic); the train/eval steps' ``maybe_normalize(…, "tanh")``
    applies the /127.5 - 1 scale on device (<0.5-LSB rounding vs the
    reference's f32 path — the same contract as the ImageNet reader)."""
    tf = _tf()
    image = tf.image.resize(tf.cast(image, tf.float32), [size, size])
    if as_uint8:
        image = tf_wire_uint8(tf, image)
    else:
        image = image / 127.5 - 1.0  # ref: preprocess.py:25
    xy = (boxes[:, 0:2] + boxes[:, 2:4]) / 2.0
    wh = boxes[:, 2:4] - boxes[:, 0:2]
    xywh = tf.concat([xy, wh], axis=-1)
    n = tf.minimum(tf.shape(xywh)[0], MAX_BOXES)
    xywh = tf.pad(xywh[:n], [[0, MAX_BOXES - n], [0, 0]])
    labels = tf.pad(
        labels[:n], [[0, MAX_BOXES - n]], constant_values=-1
    )
    xywh.set_shape([MAX_BOXES, 4])
    labels.set_shape([MAX_BOXES])
    return image, xywh, labels


def make_detection_dataset(
    file_pattern: str,
    batch_size: int,
    size: int = 416,
    *,
    is_training: bool,
    shuffle_buffer: int = 1000,
    num_process: int = 1,
    process_index: int = 0,
    seed: int = 0,
    as_uint8: bool = False,
    device_aug: bool = False,
):
    """``as_uint8`` ships uint8 pixels (normalize-on-device wire
    contract); ``device_aug`` additionally moves the horizontal flip —
    image AND box mirroring together — into the compiled step
    (``device_aug.DeviceAugment("detection")``, wired by train.py
    ``--device-aug``), leaving the host with parse + bbox-preserving
    crop + resize only. The crop stays on the host: its window depends
    on the per-sample box union and reshapes the image, which needs the
    dynamic-shape freedom only the host pipeline has."""
    tf = _tf()
    files = tf.data.Dataset.list_files(
        file_pattern, shuffle=is_training, seed=seed
    )
    if num_process > 1:
        files = files.shard(num_process, process_index)
    ds = tf.data.TFRecordDataset(files, num_parallel_reads=tf.data.AUTOTUNE)
    if is_training:
        # epoch-seeded: deterministic order restore across resumes
        ds = ds.shuffle(shuffle_buffer, seed=seed).repeat()

    def prep(serialized):
        image, boxes, labels = parse_detection_example(serialized)
        if is_training:
            if not device_aug:  # flip moves into the step (with the
                image, boxes = random_flip(image, boxes)  # box mirror)
            image, boxes = random_crop(image, boxes)
        return to_model_inputs(image, boxes, labels, size,
                               as_uint8 or device_aug)

    ds = ds.map(prep, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(batch_size, drop_remainder=is_training)
    return ds.prefetch(tf.data.AUTOTUNE)


def synthetic_detection(
    n: int = 256, size: int = 128, num_classes: int = 3, seed: int = 0,
    max_boxes: int = MAX_BOXES,
):
    """Learnable synthetic detection set (hermetic tests, zero egress):
    each image carries 1-3 solid axis-aligned rectangles whose fill color
    encodes the class; returns ({-1,1} images, padded xywh boxes, labels).
    """
    rng = np.random.default_rng(seed)
    images = rng.normal(0.0, 0.05, size=(n, size, size, 3)).astype(
        np.float32
    )
    boxes = np.zeros((n, max_boxes, 4), np.float32)
    labels = np.full((n, max_boxes), -1, np.int32)
    colors = np.linspace(0.4, 1.0, num_classes)
    for i in range(n):
        for b in range(rng.integers(1, 4)):
            cls = int(rng.integers(0, num_classes))
            w, h = rng.uniform(0.2, 0.5, size=2)
            cx = rng.uniform(w / 2, 1 - w / 2)
            cy = rng.uniform(h / 2, 1 - h / 2)
            x1, y1 = int((cx - w / 2) * size), int((cy - h / 2) * size)
            x2, y2 = int((cx + w / 2) * size), int((cy + h / 2) * size)
            images[i, y1:y2, x1:x2, cls % 3] = colors[cls]
            boxes[i, b] = [cx, cy, w, h]
            labels[i, b] = cls
    return images, boxes, labels


def synthetic_batches(images, boxes, labels, batch_size, *, rng=None,
                      drop_remainder=True, augment=False):
    """Epoch iterator over the synthetic arrays (mask-padded eval tail).

    ``augment`` adds the record pipeline's horizontal flip (per-sample
    coin from ``rng``; image columns reversed, box cx -> 1-cx on real
    rows) — the r4 YOLO gates showed the un-augmented synthetic path
    overfits 2-4x sooner than the flip-augmented record path would."""
    n = len(images)
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    end = n - n % batch_size if drop_remainder else n
    for s in range(0, end, batch_size):
        sel = idx[s : s + batch_size]
        # fancy indexing yields fresh copies, so in-place flips are safe
        img, box, lbl = images[sel], boxes[sel], labels[sel]
        if augment and rng is not None:
            flip = rng.random(len(sel)) < 0.5
            img[flip] = img[flip, :, ::-1]
            real = (lbl >= 0) & flip[:, None]
            box[..., 0] = np.where(real, 1.0 - box[..., 0], box[..., 0])
        batch = {"image": img, "boxes": box, "label": lbl}
        if not drop_remainder:
            batch = pad_partial_batch(batch, batch_size)
        yield batch


def make_detection_data(
    data_dir: str, batch_size: int, size: int = 416,
    *, train_pattern: str = "train-*", val_pattern: str = "val-*",
    steps_per_epoch: int, device_aug: bool = False,
):
    """-> (train_data(epoch)->iter, val_data()->iter, steps_per_epoch).

    ``steps_per_epoch`` bounds the repeated training stream (= dataset
    size // batch for the reference's epoch semantics).

    Multi-process contract = data/imagenet.make_imagenet_data's:
    ``batch_size`` is GLOBAL; training file-shards per process and
    batches the local share; validation streams the SAME full set per
    process at the global batch and slices its own row block.
    """
    import jax

    d = Path(data_dir)
    nproc = jax.process_count()
    pid = jax.process_index()
    if batch_size % nproc:
        raise ValueError(f"global batch {batch_size} not divisible by "
                         f"{nproc} processes")
    local_bs = batch_size // nproc

    def _iter(ds, limit=None, pad_to=None):
        for i, (img, boxes, lbl) in enumerate(ds.as_numpy_iterator()):
            if limit is not None and i >= limit:
                return
            batch = {"image": img, "boxes": boxes, "label": lbl}
            if pad_to is not None:
                batch = pad_partial_batch(batch, pad_to)
            yield batch

    def train_data(epoch: int):
        ds = make_detection_dataset(
            str(d / train_pattern), local_bs, size, is_training=True,
            num_process=nproc, process_index=pid, seed=epoch,
            device_aug=device_aug,
        )
        return _iter(ds, limit=steps_per_epoch)

    def val_data():
        ds = make_detection_dataset(
            str(d / val_pattern), batch_size, size, is_training=False
        )
        for batch in _iter(ds, pad_to=batch_size):
            yield {k: v[pid * local_bs:(pid + 1) * local_bs]
                   for k, v in batch.items()}

    return train_data, val_data, steps_per_epoch
