"""Shared image normalization for the dataset builders.

One implementation of the reference's ``ImageCoder`` repair behavior
(PNG-disguised-as-JPEG and CMYK files re-encoded —
ref: Datasets/ILSVRC2012/build_imagenet_tfrecord.py:235-269, and the COCO
re-encode — ref: Datasets/MSCOCO/tfrecords.py:42-47), detection by content
instead of the reference's hardcoded filename blacklists (:272-308).
"""

from __future__ import annotations

import io


def ensure_rgb_jpeg(data: bytes) -> tuple[bytes, int, int]:
    """-> (valid RGB JPEG bytes, width, height). Raises on undecodable input
    (callers treat that as the dirty-image skip)."""
    from PIL import Image

    img = Image.open(io.BytesIO(data))
    width, height = img.size
    if data[:2] == b"\xff\xd8" and img.format == "JPEG" and img.mode == "RGB":
        return data, width, height
    buf = io.BytesIO()
    img.convert("RGB").save(buf, "JPEG", quality=95)
    return buf.getvalue(), width, height
