"""Shared image normalization for the dataset builders.

One implementation of the reference's ``ImageCoder`` repair behavior
(PNG-disguised-as-JPEG and CMYK files re-encoded —
ref: Datasets/ILSVRC2012/build_imagenet_tfrecord.py:235-269, and the COCO
re-encode — ref: Datasets/MSCOCO/tfrecords.py:42-47), detection by content
instead of the reference's hardcoded filename blacklists (:272-308).
"""

from __future__ import annotations

import io


def ensure_rgb_jpeg(data: bytes) -> tuple[bytes, int, int]:
    """-> (valid RGB JPEG bytes, width, height). Raises on undecodable input
    (callers treat that as the dirty-image skip)."""
    from PIL import Image

    img = Image.open(io.BytesIO(data))
    width, height = img.size
    if data[:2] == b"\xff\xd8" and img.format == "JPEG" and img.mode == "RGB":
        return data, width, height
    buf = io.BytesIO()
    img.convert("RGB").save(buf, "JPEG", quality=95)
    return buf.getvalue(), width, height


def tf_wire_uint8(tf, image):
    """Round-clip-cast f32 pixels to the uint8 WIRE dtype (tf graph op).

    THE canonical host-side quantization of the split input pipeline:
    every reader that ships uint8 over H2D goes through this one
    expression, because the round-then-clip semantics are what the
    device-stage parity twins pin against (``transforms.ToUint8``, the
    round-through-uint8 in ``data/device_aug.py``) — a reader
    quantizing differently (plain truncation) drifts 1 LSB from the
    tested contract. Takes the caller's lazily imported ``tf`` module
    so this module stays importable without TensorFlow."""
    return tf.cast(tf.clip_by_value(tf.round(image), 0.0, 255.0),
                   tf.uint8)
