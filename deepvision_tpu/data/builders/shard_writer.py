"""Parallel sharded TFRecord writing.

Pattern from the reference: contiguous index ranges per worker, shard files
named ``<split>-00012-of-01024`` (ref: build_imagenet_tfrecord.py:348-417,
shard naming :380-417; Ray variant ref: Datasets/VOC2007/tfrecords.py:98-121).
Workers are ``multiprocessing`` processes (no Ray dependency).
"""

from __future__ import annotations

import multiprocessing as mp
from pathlib import Path
from typing import Callable, Sequence

from deepvision_tpu.data.tfrecord import encode_example, write_records


def shard_name(output_dir: str | Path, split: str, idx: int, total: int) -> Path:
    return Path(output_dir) / f"{split}-{idx:05d}-of-{total:05d}"


def _write_one_shard(args) -> int:
    make_features, items, path = args
    records = []
    for item in items:
        feats = make_features(item)
        if feats is not None:
            records.append(encode_example(feats))
    write_records(path, records)
    return len(records)


def write_sharded(
    items: Sequence,
    make_features: Callable,
    output_dir: str | Path,
    split: str,
    *,
    num_shards: int,
    num_workers: int = 8,
) -> int:
    """Distribute ``items`` over ``num_shards`` files; returns records written.

    ``make_features(item) -> dict | None`` runs in the worker process
    (None drops the item — the reference's dirty-image skip behavior).
    """
    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    chunks = [
        (
            make_features,
            items[i::num_shards],
            shard_name(output_dir, split, i, num_shards),
        )
        for i in range(num_shards)
    ]
    if num_workers > 1:
        with mp.Pool(num_workers) as pool:
            counts = pool.map(_write_one_shard, chunks)
    else:
        counts = [_write_one_shard(c) for c in chunks]
    return sum(counts)
