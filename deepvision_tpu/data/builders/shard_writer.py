"""Parallel sharded TFRecord writing.

Pattern from the reference: contiguous index ranges per worker, shard files
named ``<split>-00012-of-01024`` (ref: build_imagenet_tfrecord.py:348-417,
shard naming :380-417; Ray variant ref: Datasets/VOC2007/tfrecords.py:98-121).
Workers are ``multiprocessing`` processes (no Ray dependency).
"""

from __future__ import annotations

import multiprocessing as mp
from pathlib import Path
from typing import Callable, Sequence

from deepvision_tpu.data.tfrecord import encode_example, write_records


def shard_name(output_dir: str | Path, split: str, idx: int, total: int) -> Path:
    return Path(output_dir) / f"{split}-{idx:05d}-of-{total:05d}"


def _write_one_shard(args) -> int:
    make_features, items, path = args
    records = []
    for item in items:
        feats = make_features(item)
        if feats is not None:
            records.append(encode_example(feats))
    write_records(path, records)
    return len(records)


def write_sharded(
    items: Sequence,
    make_features: Callable,
    output_dir: str | Path,
    split: str,
    *,
    num_shards: int,
    num_workers: int = 8,
) -> int:
    """Distribute ``items`` over ``num_shards`` files; returns records written.

    ``make_features(item) -> dict | None`` runs in the worker process
    (None drops the item — the reference's dirty-image skip behavior).
    """
    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    chunks = [
        (
            make_features,
            items[i::num_shards],
            shard_name(output_dir, split, i, num_shards),
        )
        for i in range(num_shards)
    ]
    if num_workers > 1:
        # "spawn", not the platform-default fork: builders run inside
        # processes that already initialized TensorFlow (and often the
        # JAX client) — train.py data prep, bench.py, the test suite —
        # and fork clones a multi-threaded runtime's held locks into
        # the child. The observed failure is a silent pool deadlock:
        # the tier-1 suite wedged at the first num_workers>1 builder
        # test until the CI timeout killed it. Spawned workers start
        # from a clean interpreter; the worker fn and items are
        # picklable by construction (module-level fns / partials /
        # _FeatureMaker instances).
        ctx = mp.get_context("spawn")
        with ctx.Pool(num_workers) as pool:
            counts = pool.map(_write_one_shard, chunks)
    else:
        counts = [_write_one_shard(c) for c in chunks]
    return sum(counts)
