"""ILSVRC2012 TFRecord builder.

Rebuilds ref: Datasets/ILSVRC2012/build_imagenet_tfrecord.py (710 LoC of
TF1 Session threading) as a multiprocessing tool over the pure codec:

- input: the flattened layout the reference's shell prep produces
  (``<synset>_<name>.JPEG`` in one dir — ref: DATASET.md:73-118),
- schema parity with ``_convert_to_example`` (ref: :216-231): image/encoded,
  height/width, colorspace/channels/format, class/label (1-based!)/synset/
  text, optional bbox lists, filename,
- image repair: PNG-disguised-as-JPEG and CMYK files are detected and
  re-encoded via PIL (replacing the ``ImageCoder`` TF-session pipeline and
  its hardcoded dirty-file blacklists — ref: :235-308; detection here is by
  content, so no blacklist maintenance),
- default shard counts 1024/128 (ref: :111-114).
"""

from __future__ import annotations

from pathlib import Path

from deepvision_tpu.data.builders.shard_writer import write_sharded
from deepvision_tpu.data.folder import load_synset_maps
from deepvision_tpu.data.image_io import ensure_rgb_jpeg


class ImageNetFeatures:
    """Per-image feature fn; a module-level class (not a closure) so
    ``multiprocessing.Pool`` can pickle it into worker processes."""

    def __init__(self, wnid_to_idx, human_map, bboxes):
        self.wnid_to_idx = wnid_to_idx
        self.human_map = human_map
        self.bboxes = bboxes

    def __call__(self, path: Path):
        wnid_to_idx, human_map, bboxes = (
            self.wnid_to_idx, self.human_map, self.bboxes
        )
        try:
            data, width, height = ensure_rgb_jpeg(path.read_bytes())
        except Exception:
            return None  # dirty-image skip
        synset = path.name.split("_")[0]
        label = wnid_to_idx[synset] + 1  # 1-based (ref: :216-231 schema)
        feats = {
            "image/encoded": [data],
            "image/height": [height],
            "image/width": [width],
            "image/colorspace": [b"RGB"],
            "image/channels": [3],
            "image/format": [b"JPEG"],
            "image/class/label": [label],
            "image/class/synset": [synset.encode()],
            "image/class/text": [human_map.get(synset, "").encode()],
            "image/filename": [path.name.encode()],
        }
        boxes = bboxes.get(path.name, [])
        if boxes:
            for i, key in enumerate(("xmin", "ymin", "xmax", "ymax")):
                feats[f"image/object/bbox/{key}"] = [
                    float(b[i]) for b in boxes
                ]
            feats["image/object/bbox/label"] = [label] * len(boxes)
        return feats


def load_bbox_csv(csv_path: str | Path) -> dict[str, list]:
    """CSV from the bbox XML converter: filename,xmin,ymin,xmax,ymax
    normalized to [0,1] (ref: process_bounding_boxes.py:16-60)."""
    out: dict[str, list] = {}
    p = Path(csv_path)
    if not p.exists():
        return out
    for line in p.read_text().splitlines():
        parts = line.strip().split(",")
        if len(parts) != 5:
            continue
        name, *coords = parts
        out.setdefault(name, []).append([float(c) for c in coords])
    return out


def build_imagenet_tfrecords(
    image_dir: str | Path,
    synsets_file: str | Path,
    output_dir: str | Path,
    split: str = "train",
    *,
    human_labels_file: str | Path | None = None,
    bbox_csv: str | Path | None = None,
    num_shards: int | None = None,
    num_workers: int = 16,
) -> int:
    wnid_to_idx, _ = load_synset_maps(synsets_file)
    human_map = {}
    if human_labels_file and Path(human_labels_file).exists():
        for line in Path(human_labels_file).read_text().splitlines():
            if "\t" in line:
                wnid, text = line.split("\t", 1)
                human_map[wnid] = text.strip()
    bboxes = load_bbox_csv(bbox_csv) if bbox_csv else {}
    if num_shards is None:
        num_shards = 1024 if split == "train" else 128  # ref: :111-114
    files = sorted(Path(image_dir).glob("*.JPEG"))
    return write_sharded(
        files,
        ImageNetFeatures(wnid_to_idx, human_map, bboxes),
        output_dir, split,
        num_shards=num_shards, num_workers=num_workers,
    )
