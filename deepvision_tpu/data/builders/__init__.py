"""Offline TFRecord builders for the reference's dataset zoo.

Replaces the reference's three generations of builder tooling — TF1
Session-based threading (ImageNet, ref:
Datasets/ILSVRC2012/build_imagenet_tfrecord.py), Ray remote shard writers
(VOC/COCO/MPII, ref: Datasets/VOC2007/tfrecords.py:98-121) — with one
``multiprocessing`` shard-writer over the dependency-free codec in
data/tfrecord.py.
"""

from deepvision_tpu.data.builders.shard_writer import write_sharded

__all__ = ["write_sharded"]
