"""MPII keypoint TFRecord builder (pose).

Rebuilds ref: Datasets/MPII/tfrecords_mpii.py:38-157 — per-person examples
with 16 keypoints (x, y normalized to image size, visibility), center/scale.

Reference defects fixed rather than tolerated (SURVEY §"known defects",
corrected by review against the actual code: the reference passes
``float_list=tf.train.Int64List(...)`` for parts/v, which CRASHES at
construction — it never produced quirky records): keypoint coordinates are
stored as proper floats, visibility as int64, and the negative-y fallback
(ref: :59 writes ``joint[0]`` when y<0) is replaced by an explicit
visibility=0 with coords zeroed.
"""

from __future__ import annotations

import json
from pathlib import Path

from deepvision_tpu.data.builders.shard_writer import write_sharded
from deepvision_tpu.data.image_io import ensure_rgb_jpeg

MPII_NUM_JOINTS = 16


def _pose_features(item: dict) -> dict | None:
    path = Path(item["image_path"])
    try:
        data, width, height = ensure_rgb_jpeg(path.read_bytes())
    except Exception:
        return None
    xs, ys, vs = [], [], []
    joints = {int(j["id"]): j for j in item["joints"]}
    for jid in range(MPII_NUM_JOINTS):
        j = joints.get(jid)
        if j is None or j["x"] < 0 or j["y"] < 0:
            xs.append(0.0)
            ys.append(0.0)
            vs.append(0)
        else:
            xs.append(float(j["x"]) / width)
            ys.append(float(j["y"]) / height)
            vs.append(int(j.get("visible", 1)))
    return {
        "image/encoded": [data],
        "image/height": [height],
        "image/width": [width],
        "image/filename": [path.name.encode()],
        "image/person/center/x": [float(item["center"][0]) / width],
        "image/person/center/y": [float(item["center"][1]) / height],
        "image/person/scale": [float(item["scale"])],
        "image/person/keypoints/x": xs,
        "image/person/keypoints/y": ys,
        "image/person/keypoints/v": vs,
    }


def build_mpii_tfrecords(
    images_dir: str | Path, annotations_json: str | Path,
    output_dir: str | Path, split: str = "train",
    *, num_shards: int = 64, num_workers: int = 8,
) -> int:
    """annotations_json: list of {image, joints:[{id,x,y,visible}],
    center:[x,y], scale} (the common MPII JSON export format)."""
    anns = json.loads(Path(annotations_json).read_text())
    items = [
        {**a, "image_path": str(Path(images_dir) / a["image"])}
        for a in anns
    ]
    return write_sharded(
        items, _pose_features, output_dir, split,
        num_shards=num_shards, num_workers=num_workers,
    )
