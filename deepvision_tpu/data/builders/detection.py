"""VOC / MSCOCO TFRecord builders (detection).

VOC: XML annotation parse + normalized-bbox asserts + split from ImageSets
(ref: Datasets/VOC2007/tfrecords.py:124-155, asserts :61-64; the 2012
variant differs only in shard counts/paths). COCO: instances JSON, images
re-encoded to RGB JPEG when non-conforming (ref: Datasets/MSCOCO/
tfrecords.py:42-47). Ray shard workers replaced by multiprocessing
(ref pattern: VOC tfrecords.py:98-121).

Schema (shared, ref: VOC tfrecords.py:70-95): image/encoded, height/width,
object lists xmin/ymin/xmax/ymax (normalized floats), class text + label id.
"""

from __future__ import annotations

import json
import xml.etree.ElementTree as ET
from pathlib import Path

from deepvision_tpu.data.builders.shard_writer import write_sharded
from deepvision_tpu.data.image_io import ensure_rgb_jpeg
from deepvision_tpu.data.tfrecord import BytesList, FloatList, Int64List

VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def parse_voc_xml(xml_path: Path) -> dict:
    """One annotation file -> {filename, width, height, objects:[...]}
    (ref: VOC2007/tfrecords.py:124-155)."""
    root = ET.parse(xml_path).getroot()
    size = root.find("size")
    width = int(size.find("width").text)
    height = int(size.find("height").text)
    objects = []
    for obj in root.findall("object"):
        box = obj.find("bndbox")
        name = obj.find("name").text
        xmin = float(box.find("xmin").text) / width
        ymin = float(box.find("ymin").text) / height
        xmax = float(box.find("xmax").text) / width
        ymax = float(box.find("ymax").text) / height
        # normalized-range asserts (ref: :61-64); clamp instead of crash
        xmin, ymin = max(0.0, xmin), max(0.0, ymin)
        xmax, ymax = min(1.0, xmax), min(1.0, ymax)
        objects.append({
            "name": name, "label": VOC_CLASSES.index(name) + 1,
            "xmin": xmin, "ymin": ymin, "xmax": xmax, "ymax": ymax,
        })
    return {
        "filename": root.find("filename").text,
        "width": width, "height": height, "objects": objects,
    }


def _detection_features(image_path: Path, ann: dict) -> dict | None:
    try:
        data, _, _ = ensure_rgb_jpeg(image_path.read_bytes())
    except Exception:
        return None
    objs = ann["objects"]
    return {
        "image/encoded": [data],
        "image/height": [ann["height"]],
        "image/width": [ann["width"]],
        "image/filename": [ann["filename"].encode()],
        # typed lists: images with no objects keep the FloatList/… wire type
        "image/object/bbox/xmin": FloatList(o["xmin"] for o in objs),
        "image/object/bbox/ymin": FloatList(o["ymin"] for o in objs),
        "image/object/bbox/xmax": FloatList(o["xmax"] for o in objs),
        "image/object/bbox/ymax": FloatList(o["ymax"] for o in objs),
        "image/object/class/text": BytesList(
            o["name"].encode() for o in objs
        ),
        "image/object/class/label": Int64List(o["label"] for o in objs),
        "image/object/count": [len(objs)],
    }


def _detection_item_features(item) -> dict | None:
    """Module-level (hence Pool-picklable) adapter over (path, ann) items."""
    return _detection_features(*item)


def build_voc_tfrecords(
    voc_root: str | Path, output_dir: str | Path, split: str = "train",
    *, num_shards: int = 16, num_workers: int = 8,
) -> int:
    """voc_root = .../VOCdevkit/VOC2007; splits from ImageSets/Main."""
    root = Path(voc_root)
    names = (root / "ImageSets" / "Main" / f"{split}.txt").read_text().split()
    items = []
    for name in names:
        ann = parse_voc_xml(root / "Annotations" / f"{name}.xml")
        items.append((root / "JPEGImages" / f"{name}.jpg", ann))
    return write_sharded(
        items, _detection_item_features, output_dir, split,
        num_shards=num_shards, num_workers=num_workers,
    )


def build_coco_tfrecords(
    images_dir: str | Path, instances_json: str | Path,
    output_dir: str | Path, split: str = "train",
    *, num_shards: int = 64, num_workers: int = 8,
) -> int:
    """COCO2017 instances -> detection records (ref: MSCOCO/tfrecords.py;
    64/8 shard defaults per the reference)."""
    meta = json.loads(Path(instances_json).read_text())
    cats = {c["id"]: c["name"] for c in meta["categories"]}
    # contiguous label ids 1..80 in category-id order
    cat_to_label = {cid: i + 1 for i, cid in enumerate(sorted(cats))}
    images = {im["id"]: im for im in meta["images"]}
    anns_by_img: dict[int, list] = {}
    for a in meta["annotations"]:
        if a.get("iscrowd"):
            continue
        anns_by_img.setdefault(a["image_id"], []).append(a)
    items = []
    for img_id, im in images.items():
        objs = []
        for a in anns_by_img.get(img_id, []):
            x, y, w, h = a["bbox"]
            objs.append({
                "name": cats[a["category_id"]],
                "label": cat_to_label[a["category_id"]],
                "xmin": max(0.0, x / im["width"]),
                "ymin": max(0.0, y / im["height"]),
                "xmax": min(1.0, (x + w) / im["width"]),
                "ymax": min(1.0, (y + h) / im["height"]),
            })
        ann = {"filename": im["file_name"], "width": im["width"],
               "height": im["height"], "objects": objs}
        items.append((Path(images_dir) / im["file_name"], ann))
    return write_sharded(
        items, _detection_item_features, output_dir, split,
        num_shards=num_shards, num_workers=num_workers,
    )
