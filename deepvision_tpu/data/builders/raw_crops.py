"""Pre-decoded raw-crop TFRecords: the input-pipeline fast path.

The JPEG pipeline is host-decode-bound (~a few hundred img/s per host
core — SURVEY §7 hard part #1; the reference never hit this because its
GPUs were slower than its CPUs, ref: ResNet/tensorflow/data_load.py:35-193
is the decode path being bypassed). This builder runs the decode +
aspect-preserving resize ONCE offline, storing fixed-size raw uint8
crops; the training-time reader is then a parse + reshape — no JPEG
work — so feeding scales with disk/memory bandwidth instead of CPU.

Records keep augmentation diversity: the stored crop is the ``stored``²
center region (default 256², the resize floor), and the reader still
applies the random ``size``² crop + flip per epoch.

Schema: ``image/raw`` (stored·stored·3 uint8 bytes),
``image/class/label`` (int, [1,1000] like the reference builder's),
``image/height``/``image/width`` (= stored, for validation).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    return tf


def jpeg_record_to_raw(serialized: bytes, stored: int) -> dict | None:
    """One reference-schema JPEG Example -> raw-crop feature dict."""
    tf = _tf()
    feats = tf.io.parse_single_example(
        serialized,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    image = tf.io.decode_jpeg(feats["image/encoded"], channels=3)
    shape = tf.shape(image)
    h, w = tf.cast(shape[0], tf.float32), tf.cast(shape[1], tf.float32)
    scale = stored / tf.minimum(h, w)
    new_h = tf.cast(tf.math.ceil(h * scale), tf.int32)
    new_w = tf.cast(tf.math.ceil(w * scale), tf.int32)
    image = tf.image.resize(tf.cast(image, tf.float32), [new_h, new_w])
    off_h = (new_h - stored) // 2
    off_w = (new_w - stored) // 2
    image = tf.slice(image, [off_h, off_w, 0], [stored, stored, 3])
    raw = tf.cast(tf.clip_by_value(tf.round(image), 0, 255), tf.uint8)
    return {
        "image/raw": [raw.numpy().tobytes()],
        "image/class/label": [int(feats["image/class/label"].numpy())],
        "image/height": [stored],
        "image/width": [stored],
    }


def build_raw_crops(
    jpeg_dir: str | Path,
    output_dir: str | Path,
    *,
    split: str = "train",
    stored: int = 256,
    num_shards: int = 64,
    num_workers: int = 8,
) -> int:
    """Reference-schema JPEG TFRecords (``<split>-*``) → raw-crop shards
    (``raw-<split>-*``). Returns the record count."""
    from functools import partial

    from deepvision_tpu.data.builders.shard_writer import write_sharded
    from deepvision_tpu.data.tfrecord import read_records

    files = sorted(Path(jpeg_dir).glob(f"{split}-*"))
    if not files:
        raise FileNotFoundError(f"no {split}-* records under {jpeg_dir}")
    items = [rec for f in files for rec in read_records(f)]
    write_sharded(
        items,
        partial(jpeg_record_to_raw, stored=stored),  # picklable for mp
        output_dir,
        f"raw-{split}",
        num_shards=num_shards,
        num_workers=num_workers,
    )
    # sidecar: readers gate the fast path on the stored crop size
    # (named with '.' so the 'raw-<split>-*' shard glob can't match it)
    import json

    (Path(output_dir) / f"raw-{split}.meta.json").write_text(
        json.dumps({"stored": stored, "count": len(items)})
    )
    return len(items)
