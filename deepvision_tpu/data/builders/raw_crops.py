"""Pre-decoded raw-frame TFRecords: the input-pipeline fast path.

The JPEG pipeline is host-decode-bound (~a few hundred img/s per host
core — SURVEY §7 hard part #1; the reference never hit this because its
GPUs were slower than its CPUs, ref: ResNet/tensorflow/data_load.py:35-193
is the decode path being bypassed). This builder runs the decode +
aspect-preserving resize ONCE offline, storing the FULL resized uint8
frame; the training-time reader is then a parse + reshape — no JPEG
work — so feeding scales with disk/memory bandwidth instead of CPU.

Augmentation coverage is exactly the JPEG path's: the stored frame is
the complete shorter-side-``stored`` resize (variable long side,
center-capped at 2:1 aspect — see ``jpeg_record_to_raw``), so the
reader's random ``size``² crop + flip sees the same support region
``random_crop`` reaches online (ref semantics:
ResNet/tensorflow/data_load.py:35-193). Earlier revisions stored only
the center ``stored``² square, which silently cut off-center content
for non-square images; tests/test_data_pipeline.py::
test_raw_frame_full_crop_support pins the full-support property on a
wide image now, and readers refuse to auto-enable on legacy sidecars
(no ``full_frame`` flag).

Schema: ``image/raw`` (height·width·3 uint8 bytes),
``image/class/label`` (int, [1,1000] like the reference builder's),
``image/height``/``image/width`` (actual stored dims; the reader
reshapes per-record, so legacy square records stay readable).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    return tf


def jpeg_record_to_raw(serialized: bytes, stored: int,
                       max_aspect: float = 2.0) -> dict | None:
    """One reference-schema JPEG Example -> raw-frame feature dict.

    Stores the full aspect-preserving resize (shorter side = ``stored``).
    ``max_aspect`` caps the long side at ``stored * max_aspect`` via a
    center crop — beyond 2:1 the extreme margins contribute little and
    the bytes grow linearly; the cap is recorded per-record in the
    height/width features so nothing is silent."""
    tf = _tf()
    feats = tf.io.parse_single_example(
        serialized,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    image = tf.io.decode_jpeg(feats["image/encoded"], channels=3)
    shape = tf.shape(image)
    h, w = tf.cast(shape[0], tf.float32), tf.cast(shape[1], tf.float32)
    scale = stored / tf.minimum(h, w)
    new_h = tf.cast(tf.math.ceil(h * scale), tf.int32)
    new_w = tf.cast(tf.math.ceil(w * scale), tf.int32)
    image = tf.image.resize(tf.cast(image, tf.float32), [new_h, new_w])
    cap = int(round(stored * max_aspect))
    keep_h = tf.minimum(new_h, cap)
    keep_w = tf.minimum(new_w, cap)
    off_h = (new_h - keep_h) // 2
    off_w = (new_w - keep_w) // 2
    image = tf.slice(image, [off_h, off_w, 0], [keep_h, keep_w, 3])
    raw = tf.cast(tf.clip_by_value(tf.round(image), 0, 255), tf.uint8)
    return {
        "image/raw": [raw.numpy().tobytes()],
        "image/class/label": [int(feats["image/class/label"].numpy())],
        "image/height": [int(keep_h.numpy())],
        "image/width": [int(keep_w.numpy())],
    }


def build_raw_crops(
    jpeg_dir: str | Path,
    output_dir: str | Path,
    *,
    split: str = "train",
    stored: int = 256,
    num_shards: int = 64,
    num_workers: int = 8,
) -> int:
    """Reference-schema JPEG TFRecords (``<split>-*``) → raw-crop shards
    (``raw-<split>-*``). Returns the record count."""
    from functools import partial

    from deepvision_tpu.data.builders.shard_writer import write_sharded
    from deepvision_tpu.data.tfrecord import read_records

    files = sorted(Path(jpeg_dir).glob(f"{split}-*"))
    if not files:
        raise FileNotFoundError(f"no {split}-* records under {jpeg_dir}")
    items = [rec for f in files for rec in read_records(f)]
    write_sharded(
        items,
        partial(jpeg_record_to_raw, stored=stored),  # picklable for mp
        output_dir,
        f"raw-{split}",
        num_shards=num_shards,
        num_workers=num_workers,
    )
    # sidecar: readers gate the fast path on the stored crop size
    # (named with '.' so the 'raw-<split>-*' shard glob can't match it)
    import json

    (Path(output_dir) / f"raw-{split}.meta.json").write_text(
        json.dumps({"stored": stored, "count": len(items),
                    "full_frame": True})
    )
    return len(items)
