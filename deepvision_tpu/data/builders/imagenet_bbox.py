"""ImageNet bounding-box annotations: XML → normalized CSV → bbox map.

Capability parity with ref: Datasets/ILSVRC2012/process_bounding_boxes.py
(VERDICT §2 item 37): walk ``<dir>/nXXXXXXXX/nXXXXXXXX_YYYY.xml``
annotator files, convert each object's integer box to floats relative to
the annotator-displayed width/height, clamp to [0, 1], optionally filter
to a synset list, and emit ``filename.JPEG,xmin,ymin,xmax,ymax`` CSV rows
— the format ``load_bbox_csv`` (builders/imagenet.py) feeds into the
TFRecord builder's bbox fields.

Divergence: degenerate boxes (min ≥ max after clamping — the annotations
the reference only warns about) are dropped rather than written.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from pathlib import Path


def parse_annotation_xml(path: str | Path) -> list[tuple[str, list[float]]]:
    """One annotation file -> [(filename.JPEG, [xmin,ymin,xmax,ymax]), ...]
    with coordinates normalized by the annotator's displayed size and
    clamped to [0, 1]."""
    try:
        root = ET.parse(path).getroot()
        filename = root.findtext("filename", Path(path).stem)
        if not filename.endswith(".JPEG"):
            filename += ".JPEG"
        width = float(root.findtext("size/width") or 0)
        height = float(root.findtext("size/height") or 0)
        if width <= 0 or height <= 0:
            return []  # malformed annotator size — tolerate, like the ref
    except (ET.ParseError, TypeError, ValueError):
        return []
    out = []
    for obj in root.iter("object"):
        box = obj.find("bndbox")
        if box is None:
            continue
        try:
            xmin = min(max(float(box.findtext("xmin")) / width, 0.0), 1.0)
            ymin = min(max(float(box.findtext("ymin")) / height, 0.0), 1.0)
            xmax = min(max(float(box.findtext("xmax")) / width, 0.0), 1.0)
            ymax = min(max(float(box.findtext("ymax")) / height, 0.0), 1.0)
        except (TypeError, ValueError):
            continue
        if xmin >= xmax or ymin >= ymax:
            continue  # degenerate after clamping
        out.append((filename, [xmin, ymin, xmax, ymax]))
    return out


def process_bounding_boxes(
    annotations_dir: str | Path,
    output_csv: str | Path,
    *,
    synsets: set[str] | None = None,
) -> int:
    """Walk the synset-per-directory XML tree and write the CSV; returns
    the number of boxes written. ``synsets`` filters to the challenge
    subset (the reference's optional synsets-file)."""
    annotations_dir = Path(annotations_dir)
    n = 0
    with open(output_csv, "w") as fh:
        for syn_dir in sorted(annotations_dir.iterdir()):
            if not syn_dir.is_dir():
                continue
            if synsets is not None and syn_dir.name not in synsets:
                continue
            for xml_path in sorted(syn_dir.glob("*.xml")):
                for filename, box in parse_annotation_xml(xml_path):
                    fh.write(
                        f"{filename},{box[0]:.4f},{box[1]:.4f},"
                        f"{box[2]:.4f},{box[3]:.4f}\n"
                    )
                    n += 1
    return n
