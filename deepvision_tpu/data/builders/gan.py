"""CycleGAN unpaired-image records + CelebA attribute splitter.

- builder: trainA/trainB/testA/testB image-only records
  (ref: CycleGAN/tensorflow/tfrecords.py:9-73),
- splitter: img_align_celeba -> trainA/trainB by a named attribute column
  (gender in the reference — ref: CycleGAN/tensorflow/celeba.py:1-24),
  generalized to any attribute in the standard list_attr_celeba.txt.
"""

from __future__ import annotations

import shutil
from pathlib import Path

from deepvision_tpu.data.builders.shard_writer import write_sharded
from deepvision_tpu.data.image_io import ensure_rgb_jpeg


def _image_features(path: Path) -> dict | None:
    try:
        data, width, height = ensure_rgb_jpeg(path.read_bytes())
    except Exception:
        return None
    return {
        "image/encoded": [data],
        "image/height": [height],
        "image/width": [width],
        "image/filename": [path.name.encode()],
    }


def build_cyclegan_tfrecords(
    data_root: str | Path, output_dir: str | Path,
    *, num_shards: int = 4, num_workers: int = 4,
) -> dict[str, int]:
    """data_root contains trainA/trainB/testA/testB image dirs."""
    counts = {}
    for split in ("trainA", "trainB", "testA", "testB"):
        d = Path(data_root) / split
        if not d.is_dir():
            continue
        files = sorted(p for p in d.iterdir()
                       if p.suffix.lower() in (".jpg", ".jpeg", ".png"))
        counts[split] = write_sharded(
            files, _image_features, output_dir, split,
            num_shards=num_shards, num_workers=num_workers,
        )
    return counts


def split_celeba_by_attribute(
    celeba_dir: str | Path, attr_file: str | Path, output_root: str | Path,
    *, attribute: str = "Male", limit_per_side: int | None = None,
) -> tuple[int, int]:
    """img_align_celeba + list_attr_celeba.txt -> trainA (attr=-1) /
    trainB (attr=+1) file trees (ref: celeba.py:1-24)."""
    lines = Path(attr_file).read_text().splitlines()
    header = lines[1].split()
    col = header.index(attribute)
    out_a = Path(output_root) / "trainA"
    out_b = Path(output_root) / "trainB"
    out_a.mkdir(parents=True, exist_ok=True)
    out_b.mkdir(parents=True, exist_ok=True)
    n_a = n_b = 0
    for line in lines[2:]:
        parts = line.split()
        if not parts:
            continue
        name, value = parts[0], int(parts[1 + col])
        src = Path(celeba_dir) / name
        if not src.exists():
            continue
        if value < 0 and (limit_per_side is None or n_a < limit_per_side):
            shutil.copy(src, out_a / name)
            n_a += 1
        elif value > 0 and (limit_per_side is None or n_b < limit_per_side):
            shutil.copy(src, out_b / name)
            n_b += 1
    return n_a, n_b
