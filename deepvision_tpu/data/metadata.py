"""Dataset metadata: synset/class-name loaders (VERDICT §2 item 43).

The reference scatters these lookups across notebooks and builder scripts
(synsets + human maps in ``Datasets/ILSVRC2012/*.txt``, name lists in
``Datasets/{VOC2007,MSCOCO}/*names.txt``); here one module owns them.
The backing assets live in ``data/assets/`` (factual dataset constants —
see assets/README.md for provenance).
"""

from __future__ import annotations

from functools import lru_cache
from pathlib import Path

ASSETS = Path(__file__).parent / "assets"


@lru_cache(maxsize=None)
def imagenet_synsets() -> list[tuple[str, str]]:
    """1000 ``(wnid, human_name)`` pairs in label order (label i in
    [0, 999] ↔ entry i; TFRecord labels are 1-based)."""
    out = []
    for line in (ASSETS / "imagenet_synsets.txt").read_text().splitlines():
        wnid, _, name = line.partition(" ")
        out.append((wnid, name))
    return out


@lru_cache(maxsize=None)
def imagenet_wnid_to_index() -> dict[str, int]:
    """wnid → 0-based label index (the builders' label source)."""
    return {w: i for i, (w, _) in enumerate(imagenet_synsets())}


def imagenet_label_name(index: int) -> str:
    """0-based label → human-readable name."""
    return imagenet_synsets()[index][1]


@lru_cache(maxsize=None)
def imagenet_val_synsets() -> list[str]:
    """Ground-truth synset for each of the 50k validation images in
    sorted-filename order (for building validation TFRecords)."""
    return (ASSETS / "imagenet_val_labels.txt").read_text().split()


@lru_cache(maxsize=None)
def class_names(dataset: str) -> list[str]:
    """Detection class names: ``voc`` (20) or ``mscoco`` (80)."""
    path = ASSETS / f"{'voc' if dataset == 'voc' else 'mscoco'}_names.txt"
    return path.read_text().splitlines()
