"""ImageNet input pipeline: sharded TFRecords → device-ready NHWC batches.

Host side is ``tf.data`` (the only engine that can feed a TPU pod from
Python at line rate — SURVEY §7 hard part #1), with preprocessing parity to
the reference's "ResNet preprocessing"
(ref: ResNet/tensorflow/data_load.py:35-193):

  train: decode → aspect-preserving resize (shorter side 256) → random
         224 crop → random horizontal flip → channel-mean subtraction
         (123.68/116.78/103.94 — ref: data_load.py:35-38)
  eval:  decode → aspect-preserving resize → central crop → mean subtract

Record schema is the reference builder's
(ref: Datasets/ILSVRC2012/build_imagenet_tfrecord.py:216-231):
``image/encoded`` JPEG bytes, ``image/class/label`` in [1, 1000]
(shifted to [0, 999] here), plus filename/synset/bbox side fields.

The pipeline yields host numpy batches; core.shard_batch places them on the
mesh (per-host sharding for multi-host comes from ``shard_by_process``).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from deepvision_tpu.data.image_io import tf_wire_uint8
from deepvision_tpu.data.padding import pad_partial_batch
from deepvision_tpu.ops.normalize import (  # single source of truth
    IMAGENET_CHANNEL_MEANS as CHANNEL_MEANS,
    TORCH_CHANNEL_MEANS as TORCH_MEANS,
    TORCH_CHANNEL_STDS as TORCH_STDS,
)

RESIZE_MIN = 256
# PT-canonical augmentation strength (ref: ResNet/pytorch/train.py:319 —
# ColorJitter(brightness=0.2, contrast=0.2, saturation=0.2, hue=0))
PT_JITTER = 0.2


def resize_min_for(size: int) -> int:
    """Shorter-side resize target for a given crop: the reference's 256 for
    224 crops (ref: data_load.py), generalized by the standard 0.875
    crop-fraction rule so larger crops (Inception V3's 299 -> 342) work."""
    return max(RESIZE_MIN, round(size / 0.875))


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    return tf


def color_jitter(image, fb, fc, fs):
    """PIL-enhance-semantics jitter on a [0,255] f32 image with explicit
    factors (brightness, contrast, saturation) — the deterministic core of
    the PT reference's ColorJitter (ref: ResNet/pytorch/data_load.py:213-296),
    kept factor-for-factor identical to the numpy twin
    (data/transforms.ColorJitter) so the two pipelines are parity-testable.
    """
    tf = _tf()
    coeffs = tf.constant([0.299, 0.587, 0.114], tf.float32)
    img = image * fb
    gray = tf.tensordot(img, coeffs, 1)
    img = tf.reduce_mean(gray) * (1.0 - fc) + img * fc
    gray = tf.tensordot(img, coeffs, 1)[..., None]
    img = gray * (1.0 - fs) + img * fs
    return img


def _random_jitter(image, amount: float):
    """Sample PIL-enhance factors in [max(0, 1−a), 1+a] (transforms.py
    twin semantics) and apply; rounds through uint8 range like PIL does.

    Known divergence from torchvision.ColorJitter: the three factors are
    applied in FIXED brightness→contrast→saturation order, while
    torchvision shuffles the order per sample. The factor distributions
    are identical; only the composition order differs (the operators
    nearly commute — brightness is a pure scale)."""
    tf = _tf()
    lo = max(0.0, 1.0 - amount)
    fb, fc, fs = (
        tf.random.uniform([], lo, 1.0 + amount) for _ in range(3)
    )
    img = color_jitter(image, fb, fc, fs)
    return tf.clip_by_value(tf.round(img), 0.0, 255.0)


def parse_and_preprocess(serialized, size: int, is_training: bool,
                         as_uint8: bool = False, augment: str = "tf",
                         host_stage: str | None = None):
    """One Example -> (image [size,size,3], int32 label).

    Default emits f32 mean-subtracted images (full reference parity).
    ``as_uint8`` emits rounded uint8 crops WITHOUT normalization — 4×
    less host↔device wire traffic; the train step applies the matching
    ``ops.normalize`` kind on device (TPU-first: HBM bandwidth is cheaper
    than host link bandwidth).

    ``host_stage`` (training only; implies uint8 out) shrinks the host's
    job to the SPLIT pipeline's decode stage, with the remaining ops run
    on device inside the step (``data/device_aug.py``, keyed through the
    step's KeySeq — wire the matching ``DeviceAugment`` via
    ``train.py --device-aug``):

      - ``"crop"``: decode + resize + random ``size``² crop — flip /
        jitter / normalize move on-device. The spatial crop DRAW stays
        in tf.data (a uint8 slice costs the host nothing) so the wire
        ships exactly ``size``² 1-byte pixels: the full 4x byte win.
      - ``"canvas"``: decode + resize + center **canvas** crop
        (``resize_min_for(size)``², uint8) — the crop itself also moves
        on-device (``DeviceAugment(crop=size)``). Costs
        ~``(canvas/size)²`` more wire bytes than ``"crop"``; for hosts
        where the link is not the binding wall.

    ``augment`` selects the reference lineage:
      - ``"tf"``: crop/flip + channel-mean subtraction
        (ref: ResNet/tensorflow/data_load.py:35-193);
      - ``"pt"``: adds ColorJitter(0.2, 0.2, 0.2) in training and
        normalizes with the torchvision mean/std — the PT configs'
        accuracy-canonical recipe (ref: ResNet/pytorch/train.py:315-324).
    """
    if augment not in ("tf", "pt"):
        raise ValueError(f"unknown augment lineage {augment!r}")
    if host_stage not in (None, "crop", "canvas"):
        raise ValueError(f"unknown host_stage {host_stage!r}; "
                         "None, 'crop' or 'canvas'")
    tf = _tf()
    feats = tf.io.parse_single_example(
        serialized,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    image = tf.io.decode_jpeg(feats["image/encoded"], channels=3)
    image = tf.cast(image, tf.float32)
    # 1-indexed on disk (ref builder) -> 0-indexed; ONE definition so
    # the split-pipeline early return and the f32 tail can't skew
    label = tf.cast(feats["image/class/label"], tf.int32) - 1

    # aspect-preserving resize: shorter side -> resize_min_for(size)
    # (ref: data_load.py _aspect_preserving_resize)
    shape = tf.shape(image)
    h, w = tf.cast(shape[0], tf.float32), tf.cast(shape[1], tf.float32)
    scale = resize_min_for(size) / tf.minimum(h, w)
    new_h = tf.cast(tf.math.ceil(h * scale), tf.int32)
    new_w = tf.cast(tf.math.ceil(w * scale), tf.int32)
    image = tf.image.resize(image, [new_h, new_w])

    if is_training and host_stage is not None:
        # SPLIT-pipeline host stage: pure I/O — flip/jitter/normalize
        # (and for "canvas" the crop too) happen on device in the step
        if host_stage == "canvas":
            canvas = resize_min_for(size)
            off_h = (new_h - canvas) // 2
            off_w = (new_w - canvas) // 2
            image = tf.slice(image, [off_h, off_w, 0],
                             [canvas, canvas, 3])
        else:
            image = tf.image.random_crop(image, [size, size, 3])
        return tf_wire_uint8(tf, image), label
    if is_training:
        image = tf.image.random_crop(image, [size, size, 3])
        image = tf.image.random_flip_left_right(image)
        if augment == "pt":
            image = _random_jitter(image, PT_JITTER)
    else:
        # central crop (ref: data_load.py _central_crop)
        off_h = (new_h - size) // 2
        off_w = (new_w - size) // 2
        image = tf.slice(image, [off_h, off_w, 0], [size, size, 3])
    if as_uint8:
        image = tf_wire_uint8(tf, image)
    elif augment == "pt":
        image = (image / 255.0 - tf.constant(TORCH_MEANS, tf.float32)) \
            / tf.constant(TORCH_STDS, tf.float32)
    else:
        image = image - tf.constant(CHANNEL_MEANS, tf.float32)

    return image, label


def parse_raw_crop(serialized, size: int, is_training: bool,
                   augment: str = "tf", host_stage: str | None = None):
    """One pre-decoded raw-frame Example (data/builders/raw_crops.py) ->
    (uint8 image [size,size,3], int32 label). No JPEG decode: parse +
    reshape + random crop/flip only — the fast path when the host CPU,
    not the record format, bounds feeding. The frame is reshaped from
    the per-record height/width features (full shorter-side-``stored``
    resize, variable long side), so the random crop samples the same
    support region the JPEG path's ``random_crop`` does. ColorJitter
    (augment="pt") still applies; normalization always runs on device
    (uint8 wire).

    ``host_stage="crop"`` moves flip/jitter on-device too (split
    pipeline, as in :func:`parse_and_preprocess`); "canvas" is not
    available here — the stored frame's long side is variable, and a
    batch needs one static shape."""
    if augment not in ("tf", "pt"):
        raise ValueError(f"unknown augment lineage {augment!r}")
    if host_stage not in (None, "crop"):
        raise ValueError(
            f"raw-crop reader supports host_stage None or 'crop', got "
            f"{host_stage!r} (variable frame sizes cannot ship a fixed "
            "canvas)")
    tf = _tf()
    feats = tf.io.parse_single_example(
        serialized,
        {
            "image/raw": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
            "image/height": tf.io.FixedLenFeature([], tf.int64),
            "image/width": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    h = tf.cast(feats["image/height"], tf.int32)
    w = tf.cast(feats["image/width"], tf.int32)
    image = tf.reshape(
        tf.io.decode_raw(feats["image/raw"], tf.uint8), [h, w, 3]
    )
    if is_training:
        image = tf.image.random_crop(image, [size, size, 3])
        if host_stage is None:
            image = tf.image.random_flip_left_right(image)
            if augment == "pt":
                jittered = _random_jitter(tf.cast(image, tf.float32),
                                          PT_JITTER)
                image = tf.cast(jittered, tf.uint8)
    else:
        off_h = (h - size) // 2
        off_w = (w - size) // 2
        image = tf.slice(image, [off_h, off_w, 0], [size, size, 3])
    label = tf.cast(feats["image/class/label"], tf.int32) - 1
    return image, label


def _records_pipeline(
    file_pattern: str,
    batch_size: int,
    parse_fn,
    *,
    is_training: bool,
    shuffle_buffer: int,
    num_process: int,
    process_index: int,
    seed: int,
    private_threads: int | None = None,
):
    """Shared scaffolding for the JPEG and raw-crop readers: per-process
    file sharding (the ``experimental_distribute_dataset`` analog —
    ref: YOLO/tensorflow/train.py:291-294) and the epoch-seeded shuffle
    (resume at epoch N reproduces the order an uninterrupted run would
    have seen — SURVEY §5.3, the deterministic data-order restore the
    reference lacks).

    ``private_threads`` caps the pipeline to its own N-thread pool
    (tf.data threading option) instead of AUTOTUNE's shared pool —
    the knob that keeps K loader processes (``data/loader.py``) from
    oversubscribing the host at K x AUTOTUNE threads each, and that
    the bench uses to measure process fan-out at a controlled width."""
    tf = _tf()
    files = tf.data.Dataset.list_files(file_pattern, shuffle=is_training,
                                       seed=seed)
    if num_process > 1:
        files = files.shard(num_process, process_index)
    ds = tf.data.TFRecordDataset(files, num_parallel_reads=tf.data.AUTOTUNE)
    if is_training:
        ds = ds.shuffle(shuffle_buffer, seed=seed).repeat()
    ds = ds.map(parse_fn, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(batch_size, drop_remainder=is_training)
    ds = ds.prefetch(tf.data.AUTOTUNE)
    if private_threads is not None:
        opts = tf.data.Options()
        opts.threading.private_threadpool_size = private_threads
        ds = ds.with_options(opts)
    return ds


def make_raw_dataset(
    file_pattern: str,
    batch_size: int,
    size: int = 224,
    *,
    is_training: bool,
    stored: int = 256,
    shuffle_buffer: int = 10_000,
    num_process: int = 1,
    process_index: int = 0,
    augment: str = "tf",
    seed: int = 0,
    host_stage: str | None = None,
    private_threads: int | None = None,
):
    """tf.data pipeline over raw-crop shards (``raw-<split>-*``); same
    sharding/epoch-seeding contract as :func:`make_dataset`. ``size``
    must be < ``stored`` (the reader's only augmentation freedom is the
    random crop inside the stored region)."""
    if size >= stored:
        raise ValueError(
            f"raw-crop reader needs size < stored, got {size} >= {stored}"
        )
    return _records_pipeline(
        file_pattern, batch_size,
        lambda s: parse_raw_crop(s, size, is_training, augment,
                                 host_stage),
        is_training=is_training, shuffle_buffer=shuffle_buffer,
        num_process=num_process, process_index=process_index, seed=seed,
        private_threads=private_threads,
    )


def make_dataset(
    file_pattern: str,
    batch_size: int,
    size: int = 224,
    *,
    is_training: bool,
    shuffle_buffer: int = 10_000,
    num_process: int = 1,
    process_index: int = 0,
    as_uint8: bool = False,
    augment: str = "tf",
    seed: int = 0,
    host_stage: str | None = None,
    private_threads: int | None = None,
):
    """tf.data pipeline over sharded JPEG TFRecords (reference schema)."""
    return _records_pipeline(
        file_pattern, batch_size,
        lambda s: parse_and_preprocess(s, size, is_training, as_uint8,
                                       augment, host_stage),
        is_training=is_training, shuffle_buffer=shuffle_buffer,
        num_process=num_process, process_index=process_index, seed=seed,
        private_threads=private_threads,
    )


def _as_batches(ds, limit: int | None = None, pad_to: int | None = None):
    """``pad_to``: pad a final partial batch to that size with a 0/1 mask so
    every image is evaluated under ONE compiled batch shape (fixes the
    silent tail-drop the round-1 review flagged)."""
    for i, (img, lbl) in enumerate(ds.as_numpy_iterator()):
        if limit is not None and i >= limit:
            return
        batch = {"image": img, "label": lbl}
        if pad_to is not None:
            batch = pad_partial_batch(batch, pad_to)
        yield batch


class _TrainShardFactory:
    """Picklable per-worker dataset factory for the multi-process host
    loader (``data/loader.MultiProcessLoader``): worker ``w`` of ``n``
    reads the composed file shard ``base_index*n + w`` of
    ``base_shards*n`` — the same deterministic file-sharding contract
    multi-host training already uses, one level deeper. Carries only
    plain config (no tf/jax objects), so spawn can ship it; the child
    builds its own tf.data pipeline on a fresh interpreter."""

    def __init__(self, *, kind: str, pattern: str, batch_size: int,
                 size: int, augment: str, seed: int, base_shards: int,
                 base_index: int, host_stage: str | None,
                 as_uint8: bool, stored: int | None = None,
                 private_threads: int | None = None):
        self.kind = kind  # "jpeg" | "raw"
        self.pattern = pattern
        self.batch_size = batch_size
        self.size = size
        self.augment = augment
        self.seed = seed
        self.base_shards = base_shards
        self.base_index = base_index
        self.host_stage = host_stage
        self.as_uint8 = as_uint8
        self.stored = stored
        self.private_threads = private_threads

    def __call__(self, worker_id: int, num_workers: int):
        nproc = self.base_shards * num_workers
        pid = self.base_index * num_workers + worker_id
        if self.kind == "raw":
            ds = make_raw_dataset(
                self.pattern, self.batch_size, self.size,
                is_training=True, stored=self.stored,
                augment=self.augment, num_process=nproc,
                process_index=pid, seed=self.seed,
                host_stage=self.host_stage,
                private_threads=self.private_threads)
        else:
            ds = make_dataset(
                self.pattern, self.batch_size, self.size,
                is_training=True, as_uint8=self.as_uint8,
                augment=self.augment, num_process=nproc,
                process_index=pid, seed=self.seed,
                host_stage=self.host_stage,
                private_threads=self.private_threads)
        return _as_batches(ds)


def make_imagenet_data(
    data_dir: str, batch_size: int, size: int = 224,
    *, train_images: int = 1_281_167, val_images: int = 50_000,
    train_as_uint8: bool = True, augment: str = "tf",
    use_raw: bool | None = None, steps_per_epoch: int | None = None,
    device_aug: bool = False, loader_workers: int = 1,
    max_worker_restarts: int = 0, fault_injector=None,
):
    """-> (train_data(epoch)->iter, val_data()->iter, steps_per_epoch).

    Shard-name layout follows the reference builder: 1024 train / 128 val
    shards named ``train-*-of-*`` / ``validation-*-of-*``
    (ref: build_imagenet_tfrecord.py:111-114).

    Training batches default to uint8 wire transfer (mean subtraction on
    device — ops/normalize.py; <0.5-LSB rounding vs the reference's f32
    path); validation stays f32 for exact preprocessing parity.

    ``device_aug``: host emits decode-stage-only uint8 crops
    (``host_stage="crop"``) and the caller MUST run the matching device
    stage inside the step (``device_aug.augment_step`` — train.py
    ``--device-aug`` wires both ends); flip/jitter/normalize leave the
    host entirely. ``loader_workers`` > 1 spreads the host decode over
    N spawned processes (``data/loader.py``; deterministic round-robin
    merge over disjoint file shards — spawned fresh per epoch, seconds
    of startup amortized over the epoch).
    """
    import jax

    d = Path(data_dir)
    # batch_size is the GLOBAL batch. The repeated training stream has no
    # intrinsic epoch, so this limit IS the epoch length — overridable
    # for subset runs (the full-ImageNet default once trained a rehearsal
    # set of 16 images for 160k steps/epoch)
    steps = steps_per_epoch or train_images // batch_size
    nproc = jax.process_count()
    pid = jax.process_index()
    if batch_size % nproc:
        raise ValueError(
            f"global batch {batch_size} not divisible by "
            f"{nproc} processes"
        )
    local_bs = batch_size // nproc

    # fast path: pre-decoded raw-frame shards (builders/raw_crops.py)
    # bypass the JPEG decode bound — taken only when the requested crop
    # fits inside the stored region (sidecar written by the builder), so
    # 299²-input models fall back to the JPEG path instead of crashing.
    # use_raw: True forces it (error if absent), False disables, None
    # auto-enables with a printed notice (advisor r3: file presence alone
    # should never silently change the training distribution).
    raw_stored = None
    raw_full = False
    meta_path = d / "raw-train.meta.json"
    if use_raw is not False and meta_path.exists():
        import json

        meta = json.loads(meta_path.read_text())
        raw_stored = meta.get("stored")
        # legacy (pre-r4) shards stored only the center square — a
        # narrower crop support than the JPEG path; never auto-enable
        raw_full = bool(meta.get("full_frame"))
    have_raw = (raw_stored is not None and size < raw_stored
                and any(d.glob("raw-train-*")))
    if use_raw is True and not (have_raw and raw_full):
        raise FileNotFoundError(
            f"use_raw=True but no usable raw-train-* shards under {d} "
            f"(stored={raw_stored}, crop={size}, "
            f"full_frame={raw_full}; legacy center-square shards must be "
            f"rebuilt with data/builders/raw_crops.py)"
        )
    if have_raw and not raw_full:
        print(f"[data] raw-train-* shards under {d} are legacy "
              f"center-square records (no full_frame in {meta_path.name}) "
              f"— falling back to JPEG records; rebuild with "
              f"data/builders/raw_crops.py to re-enable the fast path")
        have_raw = False
    if have_raw and use_raw is None:
        print(f"[data] raw-frame fast path ENABLED (raw-train-* + "
              f"{meta_path.name}, stored={raw_stored}); pass "
              f"use_raw=False / --no-raw to read the JPEG records instead")

    host_stage = "crop" if device_aug else None

    def train_data(epoch: int):
        # Multi-host (train_dist.py): each process reads a DISJOINT file
        # shard and batches its local share; core.shard_batch assembles
        # the locals into the global array (local × nproc = global).
        if loader_workers > 1:
            from deepvision_tpu.data.loader import mp_batches

            factory = _TrainShardFactory(
                kind="raw" if have_raw else "jpeg",
                pattern=str(d / ("raw-train-*" if have_raw
                                 else "train-*")),
                batch_size=local_bs, size=size, augment=augment,
                seed=epoch, base_shards=nproc, base_index=pid,
                host_stage=host_stage, as_uint8=train_as_uint8,
                stored=raw_stored)
            # max_worker_restarts/fault_injector: bounded respawn of a
            # dead decode worker at its shard position + the
            # worker_kill chaos site (data/loader.py)
            return mp_batches(factory, loader_workers, steps,
                              max_restarts=max_worker_restarts,
                              fault_injector=fault_injector)
        if have_raw:
            ds = make_raw_dataset(str(d / "raw-train-*"), local_bs, size,
                                  is_training=True, stored=raw_stored,
                                  augment=augment,
                                  num_process=nproc, process_index=pid,
                                  seed=epoch, host_stage=host_stage)
        else:
            ds = make_dataset(str(d / "train-*"), local_bs, size,
                              is_training=True, as_uint8=train_as_uint8,
                              augment=augment,
                              num_process=nproc, process_index=pid,
                              seed=epoch, host_stage=host_stage)
        return _as_batches(ds, steps)

    def val_data():
        # Validation must NOT file-shard per process: uneven shard sizes
        # would give processes different batch counts and deadlock the
        # collective eval step. Every process streams the SAME full set
        # at the global batch size and slices its own row block — batch
        # counts always agree, coverage stays exact (final partial batch
        # padded + masked).
        ds = make_dataset(str(d / "validation-*"), batch_size, size,
                          is_training=False, augment=augment)
        for batch in _as_batches(ds, pad_to=batch_size):
            yield {
                k: v[pid * local_bs:(pid + 1) * local_bs]
                for k, v in batch.items()
            }

    return train_data, val_data, steps
