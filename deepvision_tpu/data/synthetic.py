"""Hermetic synthetic classification set shared by train.py and
evaluate.py.

One generator, used by BOTH CLIs, so the held-out split evaluate.py
scores is bit-identical to the one train.py held out — the same
contract the detection/pose/GAN gates already have through their
``synthetic_*`` builders. (Previously evaluate.py re-generated the
images WITHOUT the class signal and without the split, so the
classification family had no scoreable synthetic gate — VERDICT r4
missing #2.)

The class signal is a channel-0 brightness shift of ``0.3 * (label %
7)``: with ``num_classes <= 7`` every class is separable and a trained
model can reach top-1 ≈ 1.0; beyond 7 classes alias (use few classes
for gates, like the detection gates' ``--num-classes 5``).
"""

from __future__ import annotations

import numpy as np


def synthetic_classification(
    n: int, size: int, channels: int, num_classes: int, batch_size: int,
) -> tuple[np.ndarray, np.ndarray, int]:
    """-> (images, labels, split): ``images[:split]`` is the held-out
    validation slice, ``images[split:]`` the training set — exactly the
    slices train.py consumes."""
    r = np.random.default_rng(0)
    labels = r.integers(0, num_classes, n).astype(np.int32)
    imgs = r.normal(0, 1, (n, size, size, channels)).astype(np.float32)
    for i in range(n):  # make it learnable
        imgs[i, :, :, 0] += (labels[i] % 7) * 0.3
    split = max(batch_size, int(n * 0.1))
    return imgs, labels, split
