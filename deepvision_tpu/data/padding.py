"""Batch iteration + final-partial-batch padding (one shared impl).

Every pipeline (MNIST host arrays, ImageNet/detection tf.data, synthetic
sets) iterates epochs the same way: full batches for training, and for
eval the final partial batch padded to the full compiled batch shape with
a 0/1 ``mask`` row-validity vector — so exact full-set evaluation needs
only ONE compiled step shape (eval steps weight their per-sample sums by
the mask).
"""

from __future__ import annotations

import numpy as np


def pad_partial_batch(batch: dict, batch_size: int) -> dict:
    """Pad every array in ``batch`` along axis 0 to ``batch_size`` and
    attach ``mask`` ((batch_size,) float32, 1=real row, 0=padding).

    Arrays must share the same leading length ≤ ``batch_size``.
    """
    n = len(next(iter(batch.values())))
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds pad target {batch_size}")
    pad = batch_size - n
    out = {}
    for key, value in batch.items():
        value = np.asarray(value)
        if pad:
            value = np.pad(value, ((0, pad),) + ((0, 0),) * (value.ndim - 1))
        out[key] = value
    mask = np.ones(batch_size, np.float32)
    mask[n:] = 0.0
    out["mask"] = mask
    return out


def iter_array_batches(arrays: dict, batch_size: int, *, rng=None,
                       drop_remainder: bool = True):
    """Epoch iterator over a dict of equal-length host arrays.

    ``drop_remainder=False`` (the eval path) pads the final partial batch
    via :func:`pad_partial_batch` and attaches a mask to EVERY batch.
    """
    n = len(next(iter(arrays.values())))
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    end = n - n % batch_size if drop_remainder else n
    for s in range(0, end, batch_size):
        sel = idx[s : s + batch_size]
        batch = {k: v[sel] for k, v in arrays.items()}
        if not drop_remainder:
            batch = pad_partial_batch(batch, batch_size)
        yield batch


def iter_tf_batches(ds, keys, *, limit: int | None = None,
                    pad_to: int | None = None):
    """Epoch iterator over a ``tf.data`` dataset yielding tuples, as dicts
    keyed by ``keys``; ``pad_to`` pads+masks the final partial batch."""
    for i, values in enumerate(ds.as_numpy_iterator()):
        if limit is not None and i >= limit:
            return
        if not isinstance(values, tuple):
            values = (values,)
        batch = dict(zip(keys, values))
        if pad_to is not None:
            batch = pad_partial_batch(batch, pad_to)
        yield batch
