"""Final-partial-batch padding with an evaluation mask.

One shared implementation for every eval pipeline (MNIST host arrays,
ImageNet tf.data, detection/pose eval): the final partial batch is padded
to the full compiled batch shape and a 0/1 ``mask`` row-validity vector is
attached, so exact full-set evaluation needs only ONE compiled step shape
(eval steps weight their per-sample sums by the mask).
"""

from __future__ import annotations

import numpy as np


def pad_partial_batch(batch: dict, batch_size: int) -> dict:
    """Pad every array in ``batch`` along axis 0 to ``batch_size`` and
    attach ``mask`` ((batch_size,) float32, 1=real row, 0=padding).

    Arrays must share the same leading length ≤ ``batch_size``.
    """
    n = len(next(iter(batch.values())))
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds pad target {batch_size}")
    pad = batch_size - n
    out = {}
    for key, value in batch.items():
        value = np.asarray(value)
        if pad:
            value = np.pad(value, ((0, pad),) + ((0, 0),) * (value.ndim - 1))
        out[key] = value
    mask = np.ones(batch_size, np.float32)
    mask[n:] = 0.0
    out["mask"] = mask
    return out
