"""Host batch → global device array placement (single- and multi-host).

The reference's multi-device data path is
``strategy.experimental_distribute_dataset`` (per-replica dataset sharding —
ref: YOLO/tensorflow/train.py:291-294). TPU-native equivalent: each host's
``tf.data`` pipeline reads a disjoint file shard
(``data.imagenet.make_dataset(num_process=, process_index=)``) and the
process-local numpy batch becomes one **global** ``jax.Array`` spanning the
mesh via ``jax.make_array_from_process_local_data`` — batch-sharded over
the ``data`` axis, with XLA collectives riding ICI within a slice and DCN
across slices.

Single-process (one host, any number of local devices) degenerates to a
plain sharded ``device_put`` — same call, no branching in user code.
"""

from __future__ import annotations

import jax
import numpy as np

from deepvision_tpu.core.mesh import data_sharding


def shard_by_process(mesh, batch):
    """Per-process local batch pytree -> global batch-sharded jax.Arrays.

    Every participating process must call this with its own local shard of
    the global batch (local_batch = global_batch / process_count, the
    reference's ``global_batch = per_replica × replicas`` arithmetic —
    ref: YOLO/tensorflow/train.py:282).
    """

    def put(x):
        x = np.asarray(x)
        sharding = data_sharding(mesh, x.ndim)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(put, batch)


def global_batch_size(mesh, per_device_batch: int) -> int:
    """per-device batch × all mesh data-axis devices (the reference's
    global-batch arithmetic, ref: YOLO/tensorflow/train.py:282)."""
    return per_device_batch * mesh.shape["data"]
