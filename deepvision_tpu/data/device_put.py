"""Host batch → global device array placement (single- and multi-host).

The real implementation lives in :func:`deepvision_tpu.core.mesh.shard_batch`
(one call for both the single-process sharded ``device_put`` path and the
multi-host ``jax.make_array_from_process_local_data`` path); this module
re-exports it under the data-layer name the pipelines document, plus the
global-batch arithmetic helper.

Each participating process feeds its own disjoint file shard
(``data.imagenet.make_dataset(num_process=, process_index=)``) so that
local_batch × process_count = global batch — the reference's
``global_batch = per_replica × replicas`` arithmetic
(ref: YOLO/tensorflow/train.py:282).
"""

from __future__ import annotations

from deepvision_tpu.core.mesh import axis_size
from deepvision_tpu.core.mesh import shard_batch as shard_by_process

# Compat re-export: the synchronous in-loop generator this module used
# to define became the threaded async feed in data/prefetch.py (same
# contract — identical batches in identical order, ``depth`` transfers
# in flight — but sharding runs on a producer thread so H2D overlaps
# the step instead of serializing with it).
from deepvision_tpu.data.prefetch import device_prefetch

__all__ = ["shard_by_process", "global_batch_size", "device_prefetch"]


def global_batch_size(mesh, per_device_batch: int) -> int:
    """per-device batch × all mesh data-axis devices (the reference's
    global-batch arithmetic, ref: YOLO/tensorflow/train.py:282)."""
    return per_device_batch * axis_size(mesh)
