"""Host batch → global device array placement (single- and multi-host).

The real implementation lives in :func:`deepvision_tpu.core.mesh.shard_batch`
(one call for both the single-process sharded ``device_put`` path and the
multi-host ``jax.make_array_from_process_local_data`` path); this module
re-exports it under the data-layer name the pipelines document, plus the
global-batch arithmetic helper.

Each participating process feeds its own disjoint file shard
(``data.imagenet.make_dataset(num_process=, process_index=)``) so that
local_batch × process_count = global batch — the reference's
``global_batch = per_replica × replicas`` arithmetic
(ref: YOLO/tensorflow/train.py:282).
"""

from __future__ import annotations

from deepvision_tpu.core.mesh import shard_batch as shard_by_process

__all__ = ["shard_by_process", "global_batch_size"]


def global_batch_size(mesh, per_device_batch: int) -> int:
    """per-device batch × all mesh data-axis devices (the reference's
    global-batch arithmetic, ref: YOLO/tensorflow/train.py:282)."""
    return per_device_batch * mesh.shape["data"]


def device_prefetch(batches, mesh, *, depth: int = 2):
    """Double-buffered host→device transfer: keep ``depth`` batches'
    ``device_put`` dispatched ahead of the consumer so the wire transfer
    overlaps the running step (jax transfers are async — the classic TPU
    input double-buffering the reference's ``prefetch(1)`` does on the
    host side only, ref: ResNet/tensorflow/train.py:195-204).
    """
    import collections

    from deepvision_tpu.core.mesh import shard_batch

    queue = collections.deque()
    for batch in batches:
        queue.append(shard_batch(mesh, batch))
        if len(queue) > depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
