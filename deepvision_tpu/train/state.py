"""TrainState: the one pytree that flows through the compiled step.

Replaces the reference's checkpoint-dict-of-everything
(``{'epoch','model','optimizer','scheduler','loggers'}`` —
ref: ResNet/pytorch/train.py:417-428) with an immutable flax.struct dataclass
holding params + BN batch_stats + optax optimizer state + step counter. The
``loggers`` metric history stays host-side (train/loggers.py) and is saved
next to the state by the Orbax checkpointer, preserving the reference's
"curves live inside the checkpoint" workflow.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    # Static (non-pytree) fields:
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads, *, batch_stats=None) -> "TrainState":
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=self.batch_stats if batch_stats is None else batch_stats,
        )


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    sample_input,
    *,
    rng: jax.Array | int = 0,
    train_kwarg: bool = True,
) -> TrainState:
    """Initialize params/batch_stats from a sample batch and wrap with ``tx``.

    Initialization runs in TRAIN mode so lazily-created training-only
    submodules (Inception aux classifiers — ref:
    Inception/pytorch/models/inception_v1.py:92-113) get parameters.
    """
    if isinstance(rng, int):
        rng = jax.random.key(rng)
    p_rng, d_rng = jax.random.split(rng)
    kwargs = {"train": True} if train_kwarg else {}
    variables = model.init(
        {"params": p_rng, "dropout": d_rng}, sample_input, **kwargs
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )
