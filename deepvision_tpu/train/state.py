"""TrainState: the one pytree that flows through the compiled step.

Replaces the reference's checkpoint-dict-of-everything
(``{'epoch','model','optimizer','scheduler','loggers'}`` —
ref: ResNet/pytorch/train.py:417-428) with an immutable flax.struct dataclass
holding params + BN batch_stats + optax optimizer state + step counter. The
``loggers`` metric history stays host-side (train/loggers.py) and is saved
next to the state by the Orbax checkpointer, preserving the reference's
"curves live inside the checkpoint" workflow.

Mixed precision (core/precision.py): parameters here are the f32
MASTERS — layers cast them to the compute dtype at use. When the policy
enables dynamic loss scaling the :class:`DynamicLossScale` state rides
the ``loss_scale`` field (``None`` otherwise — an empty pytree, so
f32-era states flatten identically), and :meth:`TrainState.apply_gradients`
owns the unscale → finiteness check → skip-or-update select: a
non-finite-grad step backs the scale off and leaves master weights AND
optimizer state untouched.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax

from deepvision_tpu.core.precision import (
    MixedPolicy,
    all_finite,
    tree_select,
)


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    # Static (non-pytree) fields:
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    # DynamicLossScale when the precision policy scales the loss; None
    # (an EMPTY pytree — leaf list unchanged for every pre-policy
    # checkpoint and donation-alignment contract) otherwise.
    loss_scale: Any = None
    # core.sharding.Zero1Plan when the trainer turned on cross-replica
    # weight-update sharding (arXiv:2004.13336); None = replicated
    # update. Static: the plan is hashable (mesh + rule DSL string) and
    # part of the jit cache key, not a pytree leaf.
    zero1_plan: Any = flax.struct.field(pytree_node=False, default=None)

    def apply_gradients(self, grads, *, batch_stats=None) -> "TrainState":
        # ZeRO-1 reduce-scatter point: grads constrained to the
        # weight-update sharding BEFORE any use, so XLA reduces each
        # gradient straight into its local shard (the replicated
        # all-reduce never materializes). Elementwise unscale / zero /
        # finiteness below all preserve the sharding; opt_state enters
        # and leaves sharded via compile_train_step's state_spec.
        plan = self.zero1_plan
        if plan is not None:
            grads = plan.shard_update(grads)
        if self.loss_scale is None:
            updates, new_opt_state = self.tx.update(
                grads, self.opt_state, self.params
            )
            new_params = self._apply_updates(updates)
            return self.replace(
                step=self.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                batch_stats=self.batch_stats if batch_stats is None
                else batch_stats,
            )
        # dynamic loss scaling: grads arrive SCALED from the backward —
        # divide the scale back out (and cast up to the f32 masters),
        # then gate the whole update on grad finiteness: a non-finite
        # step is SKIPPED (masters, optimizer state and BN stats all
        # keep their pre-step values — under ZeRO-1 every opt_state
        # SHARD selects its own pre-step slice, so no shard moves)
        # while the scale backs off.
        ls = self.loss_scale
        grads = ls.unscale(grads)
        finite = all_finite(grads)
        new_ls = ls.adjust(finite)
        # the optimizer still runs unconditionally (one traced program,
        # no lax.cond over the whole update — XLA fuses the selects);
        # non-finite grads are zeroed first so the update math cannot
        # poison opt_state moments with inf*0 NaNs before the select.
        safe_grads = jax.tree_util.tree_map(
            lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads)
        updates, new_opt_state = self.tx.update(
            safe_grads, self.opt_state, self.params
        )
        new_params = self._apply_updates(updates)
        new_bs = self.batch_stats if batch_stats is None else batch_stats
        return self.replace(
            step=self.step + 1,
            params=tree_select(finite, new_params, self.params),
            opt_state=tree_select(finite, new_opt_state, self.opt_state),
            batch_stats=tree_select(finite, new_bs, self.batch_stats)
            if batch_stats is not None else self.batch_stats,
            loss_scale=new_ls,
        )

    def _apply_updates(self, updates):
        """``optax.apply_updates`` with the ZeRO-1 bracketing: updates
        pinned to the weight-update sharding (each replica adds only
        its own parameter slice), result all-gathered back to the
        replicated masters the next forward reads."""
        if self.zero1_plan is None:
            return optax.apply_updates(self.params, updates)
        updates = self.zero1_plan.shard_update(updates)
        new_params = optax.apply_updates(self.params, updates)
        return self.zero1_plan.replicate(new_params)

    def scale_loss(self, loss: jax.Array) -> jax.Array:
        """Loss scaled for the backward (identity without a scaler) —
        the one call sites multiply in before ``value_and_grad``."""
        if self.loss_scale is None:
            return loss
        return self.loss_scale.scale_loss(loss)


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    sample_input,
    *,
    rng: jax.Array | int = 0,
    train_kwarg: bool = True,
    policy: MixedPolicy | None = None,
) -> TrainState:
    """Initialize params/batch_stats from a sample batch and wrap with ``tx``.

    Initialization runs in TRAIN mode so lazily-created training-only
    submodules (Inception aux classifiers — ref:
    Inception/pytorch/models/inception_v1.py:92-113) get parameters.

    ``policy`` (core/precision.py): attaches the dynamic loss-scale
    state when the policy calls for it. The model's compute dtype is
    the module's own ``dtype`` attribute (set at construction from the
    same policy) — parameters are initialized in f32 masters either way.
    """
    if isinstance(rng, int):
        rng = jax.random.key(rng)
    p_rng, d_rng = jax.random.split(rng)
    kwargs = {"train": True} if train_kwarg else {}
    variables = model.init(
        {"params": p_rng, "dropout": d_rng}, sample_input, **kwargs
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
        loss_scale=policy.make_loss_scale() if policy is not None
        else None,
    )
