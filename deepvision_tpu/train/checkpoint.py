"""Checkpoint/resume via Orbax.

Replaces the reference's four ad-hoc schemes (SURVEY §5.4: torch
dict-of-everything / Keras HDF5 / TF2 save_weights-on-best /
tf.train.Checkpoint+Manager) with ONE: an Orbax CheckpointManager storing the
TrainState pytree, plus a JSON sidecar carrying epoch, the loggers metric
history (the reference keeps curves inside the checkpoint —
ref: ResNet/pytorch/train.py:417-428), and the plateau-controller state.

Also reproduces the reference's operational behaviors:
- save every epoch, keep last N (torch scheme);
- optional best-metric tracking (TF2 scheme, best-val save —
  ref: YOLO/tensorflow/train.py:243-257);
- resume-from-latest restores params/opt_state/step AND the host-side
  scheduler + metric history, which the reference could not fully do.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from deepvision_tpu.train.loggers import Loggers


class CheckpointManager:
    def __init__(self, directory: str | Path, *, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, epoch: int, state, *, loggers: Loggers | None = None,
             extra: dict[str, Any] | None = None, best_metric=None) -> None:
        meta = {
            "epoch": int(epoch),
            "loggers": loggers.to_json() if loggers else None,
            "extra": extra or {},
            "best_metric": best_metric,
        }
        payload = self._payload(state)
        self._mgr.save(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta),
            ),
        )
        self._mgr.wait_until_finished()

    @staticmethod
    def _payload(state) -> dict:
        """The checkpointed pytree. GAN states carry pools/etc. in an
        ``extra_vars`` field mirrored here (train/gan.py)."""
        payload = {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        }
        if getattr(state, "extra_vars", None) is not None:
            payload["extra_vars"] = state.extra_vars
        return payload

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state, epoch: int | None = None):
        """-> (state, meta dict with 'epoch', 'loggers', 'extra')."""
        if epoch is None:
            epoch = self._mgr.latest_step()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        template = self._payload(state)
        restored = self._mgr.restore(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                meta=ocp.args.JsonRestore(),
            ),
        )
        payload, meta = restored["state"], dict(restored["meta"])
        state = state.replace(**payload)
        if meta.get("loggers"):
            meta["loggers"] = Loggers.from_json(meta["loggers"])
        return state, meta

    def close(self):
        self._mgr.close()
