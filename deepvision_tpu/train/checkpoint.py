"""Checkpoint/resume via Orbax.

Replaces the reference's four ad-hoc schemes (SURVEY §5.4: torch
dict-of-everything / Keras HDF5 / TF2 save_weights-on-best /
tf.train.Checkpoint+Manager) with ONE: an Orbax CheckpointManager storing the
TrainState pytree, plus a JSON sidecar carrying epoch, the loggers metric
history (the reference keeps curves inside the checkpoint —
ref: ResNet/pytorch/train.py:417-428), and the plateau-controller state.

Also reproduces the reference's operational behaviors:
- save every epoch, keep last N (torch scheme);
- optional best-metric tracking (TF2 scheme, best-val save —
  ref: YOLO/tensorflow/train.py:243-257);
- resume-from-latest restores params/opt_state/step AND the host-side
  scheduler + metric history, which the reference could not fully do.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from deepvision_tpu.train.loggers import Loggers


class CheckpointManager:
    def __init__(self, directory: str | Path, *, max_to_keep: int = 3,
                 async_save: bool = False, keep_best_of: str | None = None):
        """``async_save``: saves overlap with training — ``save()`` returns
        after staging the device arrays to host; serialization runs on a
        background thread (SURVEY §5.3's periodic async checkpointing; the
        reference's saves are all synchronous/blocking).

        ``keep_best_of``: retention policy keyed on a metric name passed to
        :meth:`save` — the ``max_to_keep`` checkpoints with the HIGHEST
        value are kept instead of the most recent, the reference's
        save-on-new-best behavior with strictly better coverage
        (ref: YOLO/tensorflow/train.py:243-257 keeps best-val only).
        """
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        opts: dict[str, Any] = dict(
            max_to_keep=max_to_keep, create=True,
            enable_async_checkpointing=async_save,
        )
        if keep_best_of is not None:
            opts.update(
                best_fn=lambda metrics: float(metrics[keep_best_of]),
                best_mode="max",
                # un-metric'd saves (e.g. a manual final save) must not
                # evict the measured best
                keep_checkpoints_without_metrics=False,
            )
        self.keep_best_of = keep_best_of
        self._async = async_save
        self._mgr = ocp.CheckpointManager(
            self.directory, options=ocp.CheckpointManagerOptions(**opts)
        )

    def save(self, epoch: int, state, *, loggers: Loggers | None = None,
             extra: dict[str, Any] | None = None, best_metric=None,
             metrics: dict[str, float] | None = None) -> None:
        meta = {
            "epoch": int(epoch),
            "loggers": loggers.to_json() if loggers else None,
            "extra": extra or {},
            "best_metric": best_metric,
        }
        payload = self._payload(state)
        self._mgr.save(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta),
            ),
            metrics=metrics,
        )
        if not self._async:
            self._mgr.wait_until_finished()

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save commits (restore-latest and
        process exit must not race a pending write)."""
        self._mgr.wait_until_finished()

    @staticmethod
    def _payload(state) -> dict:
        """The checkpointed pytree. GAN states carry pools/etc. in an
        ``extra_vars`` field mirrored here (train/gan.py)."""
        payload = {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        }
        if getattr(state, "extra_vars", None) is not None:
            payload["extra_vars"] = state.extra_vars
        return payload

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def saved_epochs(self) -> list[int]:
        """Epochs currently on disk (after retention GC)."""
        self._mgr.wait_until_finished()
        return sorted(self._mgr.all_steps())

    def _resolve_epoch(self, epoch: int | None) -> int:
        # an in-flight async save must commit before it can be restored
        self._mgr.wait_until_finished()
        if epoch is None:
            epoch = self._mgr.latest_step()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return epoch

    @staticmethod
    def _decode_meta(meta) -> dict:
        meta = dict(meta)
        if meta.get("loggers"):
            meta["loggers"] = Loggers.from_json(meta["loggers"])
        return meta

    def restore_inference(self, state, epoch: int | None = None):
        """Params/batch_stats/step-only restore for inference.

        Skips ``opt_state`` (and GAN pools), so the template never has to
        reconstruct the exact optimizer the checkpoint was trained with —
        restoring a Trainer checkpoint into an inference-built state works
        regardless of schedule/plateau wrappers. -> (state, meta dict).
        """
        epoch = self._resolve_epoch(epoch)
        template = {"params": state.params, "step": state.step}
        if state.batch_stats:
            template["batch_stats"] = state.batch_stats
        # A fresh manager: on an instance that already save()d, the 'state'
        # item is registered with the Standard handler and PyTreeRestore
        # args would be rejected (orbax 0.11 registry semantics).
        mgr = ocp.CheckpointManager(self.directory)
        # partial_restore landed in orbax 0.11; on older builds the
        # documented sub-template idiom is transforms={} (keys absent
        # from the template are dropped instead of raising a Dict key
        # mismatch). Same semantics, version-gated.
        import inspect

        partial_kw = (
            {"partial_restore": True}
            if "partial_restore" in inspect.signature(
                ocp.args.PyTreeRestore.__init__).parameters
            else {"transforms": {}}
        )
        try:
            restored = mgr.restore(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.PyTreeRestore(
                        item=template,
                        # template shardings, NOT the on-disk sharding file:
                        # a chip/mesh-saved checkpoint must restore on a
                        # single-device inference host
                        restore_args=ocp.checkpoint_utils.construct_restore_args(
                            template
                        ),
                        **partial_kw,
                    ),
                    meta=ocp.args.JsonRestore(),
                ),
            )
        finally:
            mgr.close()
        state = state.replace(**restored["state"])
        return state, self._decode_meta(restored["meta"])

    def restore_meta(self, epoch: int | None = None) -> dict:
        """Restore only the JSON meta item (epoch/loggers/extra) through
        the manager API — no state template needed, no dependence on the
        Orbax on-disk layout."""
        epoch = self._resolve_epoch(epoch)
        restored = self._mgr.restore(
            epoch, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return self._decode_meta(restored["meta"])

    def restore(self, state, epoch: int | None = None):
        """-> (state, meta dict with 'epoch', 'loggers', 'extra')."""
        epoch = self._resolve_epoch(epoch)
        template = self._payload(state)
        restored = self._mgr.restore(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                meta=ocp.args.JsonRestore(),
            ),
        )
        state = state.replace(**restored["state"])
        return state, self._decode_meta(restored["meta"])

    def close(self):
        self._mgr.close()
