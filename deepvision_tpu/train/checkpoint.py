"""Checkpoint/resume via Orbax.

Replaces the reference's four ad-hoc schemes (SURVEY §5.4: torch
dict-of-everything / Keras HDF5 / TF2 save_weights-on-best /
tf.train.Checkpoint+Manager) with ONE: an Orbax CheckpointManager storing the
TrainState pytree, plus a JSON sidecar carrying epoch, the loggers metric
history (the reference keeps curves inside the checkpoint —
ref: ResNet/pytorch/train.py:417-428), and the plateau-controller state.

Also reproduces the reference's operational behaviors:
- save every epoch, keep last N (torch scheme);
- optional best-metric tracking (TF2 scheme, best-val save —
  ref: YOLO/tensorflow/train.py:243-257);
- resume-from-latest restores params/opt_state/step AND the host-side
  scheduler + metric history, which the reference could not fully do.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from deepvision_tpu.train import manifest as _manifest
from deepvision_tpu.train.loggers import Loggers

MANIFEST_VERSION = _manifest.MANIFEST_VERSION


def _primary_process() -> bool:
    """True on the process that owns shared-filesystem bookkeeping. In
    a ``jax.distributed`` run every host calls the collective
    save/restore, but the integrity manifest (and the chaos corrupt
    hook) must be written by exactly ONE of them — N hosts hashing and
    replacing the same sidecar is wasted work and the write race the
    manifest module only mitigates."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # jax absent/uninitialized: single-writer anyway
        return True


class CheckpointManager:
    def __init__(self, directory: str | Path, *, max_to_keep: int = 3,
                 async_save: bool = False, keep_best_of: str | None = None,
                 integrity: bool = True, fault_injector=None):
        """``async_save``: saves overlap with training — ``save()`` returns
        after staging the device arrays to host; serialization runs on a
        background thread (SURVEY §5.3's periodic async checkpointing; the
        reference's saves are all synchronous/blocking).

        ``keep_best_of``: retention policy keyed on a metric name passed to
        :meth:`save` — the ``max_to_keep`` checkpoints with the HIGHEST
        value are kept instead of the most recent, the reference's
        save-on-new-best behavior with strictly better coverage
        (ref: YOLO/tensorflow/train.py:243-257 keeps best-val only).

        ``integrity``: every committed save gets a JSON manifest beside
        the step directory (``manifest-<epoch>.json``: per-file size +
        SHA-256), written ATOMICALLY (tmp + ``os.replace``) so a SIGKILL
        mid-write can never leave a truncated sidecar that poisons
        resume. :meth:`restore_verified` recomputes the checksums,
        quarantines corrupt epochs into ``quarantine/``, and falls back
        to the newest verified older epoch instead of crashing — the
        recovery contract of ``resilience/``.

        ``fault_injector``: optional ``resilience.FaultInjector`` whose
        ``ckpt_corrupt`` site is consulted after each committed save
        (chaos tests corrupt a real on-disk file deterministically).
        """
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        opts: dict[str, Any] = dict(
            max_to_keep=max_to_keep, create=True,
            enable_async_checkpointing=async_save,
        )
        if keep_best_of is not None:
            opts.update(
                best_fn=lambda metrics: float(metrics[keep_best_of]),
                best_mode="max",
                # un-metric'd saves (e.g. a manual final save) must not
                # evict the measured best
                keep_checkpoints_without_metrics=False,
            )
        self.keep_best_of = keep_best_of
        self._async = async_save
        self._opts = opts
        self.integrity = integrity
        self._injector = fault_injector
        self._pending_manifests: list[int] = []
        # save-time state fingerprints awaiting their (possibly
        # deferred) manifest commit — resilience/sentinel.py's audited
        # checkpoints; computed at save() entry, so even an async save
        # records the state the caller actually handed over
        self._fingerprints: dict[int, dict] = {}
        self._mgr = ocp.CheckpointManager(
            self.directory, options=ocp.CheckpointManagerOptions(**opts)
        )

    def save(self, epoch: int, state, *, loggers: Loggers | None = None,
             extra: dict[str, Any] | None = None, best_metric=None,
             metrics: dict[str, float] | None = None,
             state_fingerprint: dict | None = None) -> None:
        if state_fingerprint is not None:
            self._fingerprints[int(epoch)] = dict(state_fingerprint)
        meta = {
            "epoch": int(epoch),
            "loggers": loggers.to_json() if loggers else None,
            "extra": extra or {},
            "best_metric": best_metric,
        }
        payload = self._payload(state)
        if self._async and self._pending_manifests:
            # the PRIOR epoch's async save: its manifest must hash
            # COMMITTED files, so it was deferred — flush it now (Orbax
            # admits one in-flight save at a time, so entering save(N+1)
            # means save(N) is durable). Deferring to end-of-run instead
            # would leave EVERY epoch manifest-less after a mid-run
            # kill, and verify_epoch passes manifest-less epochs
            # vacuously; this bounds the exposure to the newest epoch.
            self._mgr.wait_until_finished()
            self._flush_manifests()
        self._mgr.save(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta),
            ),
            metrics=metrics,
        )
        if self._async:
            self._pending_manifests.append(epoch)
        else:
            self._mgr.wait_until_finished()
            self._finalize_save(epoch)

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save commits (restore-latest and
        process exit must not race a pending write)."""
        self._mgr.wait_until_finished()
        self._flush_manifests()

    def _flush_manifests(self) -> None:
        while self._pending_manifests:
            self._finalize_save(self._pending_manifests.pop(0))

    # -- integrity (resilience/) ----------------------------------------
    def _step_dir(self, epoch: int) -> Path:
        return self.directory / str(epoch)

    def _manifest_path(self, epoch: int) -> Path:
        return self.directory / f"manifest-{epoch}.json"

    def _finalize_save(self, epoch: int) -> None:
        """Post-commit bookkeeping: write the integrity manifest for the
        epoch, GC manifests whose step dir the retention policy already
        deleted, and consult the fault injector (which corrupts AFTER
        the manifest is written — exactly the bit-rot/truncation window
        verification exists to catch). Primary-process-only in a
        multi-host run: the save itself is collective, the sidecar
        bookkeeping is single-writer."""
        if not _primary_process():
            return
        if self.integrity:
            self._write_manifest(epoch)
            live = {p.name for p in self.directory.iterdir()
                    if p.is_dir() and p.name.isdigit()}
            for mp in self.directory.glob("manifest-*.json"):
                if mp.stem.split("-", 1)[1] not in live:
                    mp.unlink(missing_ok=True)
        if self._injector is not None and self._step_dir(epoch).exists():
            self._injector.corrupt_checkpoint(self._step_dir(epoch))

    def _write_manifest(self, epoch: int) -> None:
        # atomic + multi-writer-safe (unique tmp name + os.replace):
        # see train/manifest.write_manifest; the save-time state
        # fingerprint (if the trainer supplied one) rides along
        fp = self._fingerprints.pop(int(epoch), None)
        _manifest.write_manifest(
            self.directory, epoch,
            extra={"state_fingerprint": fp} if fp else None)

    def verify_epoch(self, epoch: int) -> tuple[bool, str]:
        """-> (ok, reason). An epoch with NO manifest verifies vacuously
        (pre-integrity checkpoints stay restorable); an unreadable or
        mismatching manifest fails it."""
        return _manifest.verify_manifest(self.directory, epoch)

    def quarantine_epoch(self, epoch: int) -> Path:
        """Move a corrupt epoch (and its manifest) into ``quarantine/``
        for post-mortem instead of deleting evidence; reopens the
        underlying Orbax manager, whose step cache would otherwise go
        stale on the externally-moved directory."""
        qroot = self.directory / "quarantine"
        qroot.mkdir(exist_ok=True)
        target = qroot / str(epoch)
        n = 0
        while target.exists():  # re-corrupted re-saves of the same epoch
            n += 1
            target = qroot / f"{epoch}.{n}"
        shutil.move(str(self._step_dir(epoch)), str(target))
        mp = self._manifest_path(epoch)
        if mp.exists():
            shutil.move(str(mp), str(target) + ".manifest.json")
        self._reopen()
        return target

    def _reopen(self) -> None:
        """Recreate the Orbax manager: its in-memory step list does not
        track external directory moves (verified against orbax 0.7)."""
        self._mgr.close()
        self._mgr = ocp.CheckpointManager(
            self.directory, options=ocp.CheckpointManagerOptions(
                **self._opts)
        )

    def fs_epochs(self) -> list[int]:
        """Epoch dirs actually on disk — the quarantine scan must not
        trust the manager's (possibly stale) step cache."""
        return sorted(int(p.name) for p in self.directory.iterdir()
                      if p.is_dir() and p.name.isdigit())

    def restore_verified(self, state, *, counters=None, log=print,
                         fingerprint_fn=None):
        """Newest-first verified restore: checksum-verify each epoch,
        quarantine failures (counting ``ckpt_fallbacks``), and return
        the first epoch that both verifies and restores — the
        crash-free ``resume()`` the recovery layer promises. Raises
        ``FileNotFoundError`` only when no epoch survives.

        ``fingerprint_fn(state) -> {"digest": ...}`` (the sentinel
        monitor's state fingerprint) arms the AUDITED layer: when the
        manifest recorded a save-time ``state_fingerprint``, the
        restored state is re-fingerprinted and a digest mismatch
        quarantines the epoch exactly like a checksum failure — the
        case where the bytes round-tripped faithfully but were already
        corrupt before serialization (SDC between the last audit and
        the save)."""
        self.wait_until_finished()
        for epoch in reversed(self.fs_epochs()):
            ok, why = self.verify_epoch(epoch)
            if ok:
                try:
                    restored, meta = self.restore(state, epoch)
                    why = self._check_fingerprint(
                        epoch, restored, fingerprint_fn)
                    if why is None:
                        return restored, meta
                except Exception as e:
                    if self._manifest_path(epoch).exists():
                        # checksums PROVED the files intact, yet restore
                        # failed: that is a systematic error (template/
                        # optimizer mismatch, sharding change), not
                        # corruption — quarantining would repeat for
                        # every older epoch and silently discard the
                        # whole run's progress; surface it instead
                        raise
                    # manifest-less (pre-integrity) epoch: corruption is
                    # plausible and undetectable — quarantine + fall back
                    why = f"restore failed: {type(e).__name__}: {e}"
            log(f"[ckpt-integrity] epoch {epoch}: {why}; quarantining "
                "and falling back to an older epoch", flush=True)
            self.quarantine_epoch(epoch)
            if counters is not None:
                counters.inc("ckpt_fallbacks")
        raise FileNotFoundError(
            f"no verifiable checkpoints left in {self.directory} "
            "(corrupt epochs moved to quarantine/)")

    def _check_fingerprint(self, epoch: int, restored,
                           fingerprint_fn) -> str | None:
        """None when the audited-fingerprint layer passes (or does not
        apply); else the quarantine reason."""
        if fingerprint_fn is None:
            return None
        m = _manifest.read_manifest(self.directory, epoch)
        want = (m or {}).get("state_fingerprint")
        if not isinstance(want, dict) or "digest" not in want:
            return None  # pre-audit epoch: hash verification stands
        got = fingerprint_fn(restored)
        if got["digest"] == want["digest"]:
            return None
        return (f"state fingerprint mismatch (restored "
                f"{got['digest']} != saved {want['digest']}): the "
                "bytes round-tripped but the state was corrupt before "
                "serialization")

    @staticmethod
    def _payload(state) -> dict:
        """The checkpointed pytree. GAN states carry pools/etc. in an
        ``extra_vars`` field mirrored here (train/gan.py).

        Under ZeRO-1 (core/sharding.py) the ``opt_state`` leaves are
        data-axis-sharded jax.Arrays: Orbax serializes global arrays
        shard-wise, so each host persists only its LOCAL opt_state
        shards (no gather on the save path), and a restore template
        built from an already-sharded state restores straight into the
        shards. A template built from a FRESH (replicated) state — the
        resume path, possibly at a different host count — restores the
        full logical arrays instead; Trainer._reshard_state then
        re-shards them onto the new mesh, which is what makes elastic
        resume across host counts deterministic: same logical bytes,
        re-cut to whatever the mesh now prescribes. The PR 4 integrity
        manifests hash whatever files the save committed (shard files
        included); the PR 10 audited fingerprints stay
        params+batch_stats only (resilience/sentinel.py) — opt_state
        shards legitimately differ per host and must never trip a
        false SDC divergence."""
        payload = {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        }
        if getattr(state, "extra_vars", None) is not None:
            payload["extra_vars"] = state.extra_vars
        if getattr(state, "loss_scale", None) is not None:
            # mixed-precision scale state (core/precision.py): the
            # grow/backoff schedule must survive a resume — a reset
            # scale re-runs the whole warmup and can re-skip steps
            payload["loss_scale"] = state.loss_scale
        return payload

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def saved_epochs(self) -> list[int]:
        """Epochs currently on disk (after retention GC)."""
        self._mgr.wait_until_finished()
        return sorted(self._mgr.all_steps())

    def _resolve_epoch(self, epoch: int | None) -> int:
        # an in-flight async save must commit before it can be restored
        self._mgr.wait_until_finished()
        if epoch is None:
            epoch = self._mgr.latest_step()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return epoch

    @staticmethod
    def _decode_meta(meta) -> dict:
        meta = dict(meta)
        if meta.get("loggers"):
            meta["loggers"] = Loggers.from_json(meta["loggers"])
        return meta

    def restore_inference(self, state, epoch: int | None = None):
        """Params/batch_stats/step-only restore for inference.

        Skips ``opt_state`` (and GAN pools), so the template never has to
        reconstruct the exact optimizer the checkpoint was trained with —
        restoring a Trainer checkpoint into an inference-built state works
        regardless of schedule/plateau wrappers. -> (state, meta dict).
        """
        epoch = self._resolve_epoch(epoch)
        template = {"params": state.params, "step": state.step}
        if state.batch_stats:
            template["batch_stats"] = state.batch_stats
        # A fresh manager: on an instance that already save()d, the 'state'
        # item is registered with the Standard handler and PyTreeRestore
        # args would be rejected (orbax 0.11 registry semantics).
        mgr = ocp.CheckpointManager(self.directory)
        # partial_restore landed in orbax 0.11; on older builds the
        # documented sub-template idiom is transforms={} (keys absent
        # from the template are dropped instead of raising a Dict key
        # mismatch). Same semantics, version-gated.
        import inspect

        partial_kw = (
            {"partial_restore": True}
            if "partial_restore" in inspect.signature(
                ocp.args.PyTreeRestore.__init__).parameters
            else {"transforms": {}}
        )
        try:
            restored = mgr.restore(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.PyTreeRestore(
                        item=template,
                        # template shardings, NOT the on-disk sharding file:
                        # a chip/mesh-saved checkpoint must restore on a
                        # single-device inference host
                        restore_args=ocp.checkpoint_utils.construct_restore_args(
                            template
                        ),
                        **partial_kw,
                    ),
                    meta=ocp.args.JsonRestore(),
                ),
            )
        finally:
            mgr.close()
        state = state.replace(**restored["state"])
        return state, self._decode_meta(restored["meta"])

    def restore_meta(self, epoch: int | None = None) -> dict:
        """Restore only the JSON meta item (epoch/loggers/extra) through
        the manager API — no state template needed, no dependence on the
        Orbax on-disk layout."""
        epoch = self._resolve_epoch(epoch)
        restored = self._mgr.restore(
            epoch, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return self._decode_meta(restored["meta"])

    def restore(self, state, epoch: int | None = None):
        """-> (state, meta dict with 'epoch', 'loggers', 'extra')."""
        epoch = self._resolve_epoch(epoch)
        template = self._payload(state)
        try:
            restored = self._mgr.restore(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(template),
                    meta=ocp.args.JsonRestore(),
                ),
            )
        except Exception:
            if "loss_scale" not in template:
                raise
            # migration: a pre-mixed-precision checkpoint (saved before
            # the config declared a scaling policy) has no loss_scale
            # item — restore everything else and keep the FRESH scale
            # state (it re-warms from init_scale; the alternative is a
            # hard crash until the operator guesses --precision f32)
            template = {k: v for k, v in template.items()
                        if k != "loss_scale"}
            restored = self._mgr.restore(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(template),
                    meta=ocp.args.JsonRestore(),
                ),
            )
            print("[ckpt] pre-mixed-precision checkpoint (no saved "
                  "loss_scale): restored state, keeping a fresh "
                  "loss-scale state", flush=True)
        state = state.replace(**restored["state"])
        return state, self._decode_meta(restored["meta"])

    def close(self):
        self.wait_until_finished()  # flush pending integrity manifests
        self._mgr.close()
