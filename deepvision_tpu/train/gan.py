"""GAN training: two-optimizer states, DCGAN/CycleGAN steps, ImagePool.

Re-expresses the reference's GAN trainers as pure compiled step functions:

- DCGAN alternating G/D Adam updates computed from the SAME forward pass
  (both losses share one fake batch and one discriminator dropout mask,
  exactly the reference's two-tape step — ref: DCGAN/tensorflow/main.py:57-76).
- CycleGAN two-phase step: generator phase (LSGAN + cycle + identity
  losses over both generators, ref: CycleGAN/tensorflow/train.py:150-205)
  then discriminator phase on POOLED fakes (ref: :207-255, :249-255).
- ``ImagePool`` redesigned as an on-device functional ring buffer: the
  reference's version mutates Python state and is documented eager-only
  (ref: CycleGAN/tensorflow/utils.py:31-61); here the pool is part of the
  train-state pytree and the query is a ``lax.scan``, so the whole step
  (G update → pool query → D update) compiles into ONE XLA program.

States mirror TrainState's field names (params/batch_stats/opt_state/step
plus ``extra_vars`` for the pools) so the Orbax CheckpointManager handles
them unchanged — the reference's `tf.train.Checkpoint` of both optimizers
and nets (ref: DCGAN/tensorflow/main.py:34-40, CycleGAN/train.py:133-148).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

LAMBDA_CYCLE = 10.0  # ref: CycleGAN/tensorflow/train.py:16
LAMBDA_ID = 5.0  # ref: train.py:17
POOL_SIZE = 50  # ref: train.py:18


@flax.struct.dataclass
class GANState:
    """Two-network train state. ``params``/``batch_stats`` are dicts keyed
    by network role; ``opt_state`` holds one optax state per optimizer
    ('generator' spans all generator nets, 'discriminator' all critics —
    the reference's optimizer pairing, ref: CycleGAN/train.py:126-127).

    ``loss_scale`` (core/precision.py): ONE shared DynamicLossScale
    over both phases when the precision policy scales — a non-finite
    grad in EITHER tape skips both updates for the step and backs the
    scale off (the two-network coupling means half an update is worse
    than none). None = empty pytree, f32-era states flatten identically.
    """

    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    extra_vars: Any
    g_apply: Callable = flax.struct.field(pytree_node=False)
    d_apply: Callable = flax.struct.field(pytree_node=False)
    g_tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    d_tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    noise_dim: int = flax.struct.field(pytree_node=False, default=100)
    loss_scale: Any = None
    # core.sharding.Zero1Plan when fit_gan turned on weight-update
    # sharding; static (hashable) — same contract as TrainState's.
    zero1_plan: Any = flax.struct.field(pytree_node=False, default=None)

    def scale_loss(self, loss):
        """Loss scaled for a backward (identity without a scaler)."""
        if self.loss_scale is None:
            return loss
        return self.loss_scale.scale_loss(loss)


def _bce(logits, is_real: bool, smooth: float = 0.0):
    """``smooth`` > 0 applies one-sided label smoothing (real targets
    become 1-smooth; Salimans et al. 2016) — the standard fix when the
    discriminator saturates and starves the generator of gradient."""
    target = (jnp.full_like(logits, 1.0 - smooth) if is_real
              else jnp.zeros_like(logits))
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, target))


def _lsgan(pred, is_real: bool):
    target = jnp.ones_like(pred) if is_real else jnp.zeros_like(pred)
    return jnp.mean((pred - target) ** 2)


def _l1(a, b):
    return jnp.mean(jnp.abs(a - b))


def _gan_apply_gradients(state: "GANState", g_grads, d_grads, *,
                         g_params, d_params, batch_stats, assemble,
                         extra_vars=None):
    """Shared two-optimizer update for both GAN steps: with a
    DynamicLossScale on the state, unscale both tapes' grads, gate the
    WHOLE step (params, opt states, BN stats, pools) on their joint
    finiteness, and grow/backoff the scale; plain updates otherwise.
    ``assemble(new_gp, new_dp)`` rebuilds the full params dict from the
    updated subsets. Returns ``(new_state, mp_metrics)``."""
    from deepvision_tpu.core.precision import (
        all_finite,
        precision_metrics,
        tree_select,
    )

    # ZeRO-1 reduce-scatter point (core.sharding.Zero1Plan, same
    # bracketing as TrainState.apply_gradients): both tapes' grads and
    # updates pinned to the weight-update sharding, updated params
    # all-gathered back to replicated. The plan is shape-driven, so one
    # plan serves both subtrees.
    plan = state.zero1_plan
    if plan is not None:
        g_grads, d_grads = plan.shard_update(g_grads), \
            plan.shard_update(d_grads)
    ls = state.loss_scale
    new_ls, finite = None, None
    if ls is not None:
        g_grads, d_grads = ls.unscale(g_grads), ls.unscale(d_grads)
        finite = all_finite({"g": g_grads, "d": d_grads})
        new_ls = ls.adjust(finite)
        # zero non-finite grads BEFORE the optimizer so inf*0 NaNs
        # cannot poison the moment estimates ahead of the select
        zero = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda g: jnp.where(finite, g, jnp.zeros_like(g)), t)
        g_grads, d_grads = zero(g_grads), zero(d_grads)
    g_up, g_opt = state.g_tx.update(
        g_grads, state.opt_state["generator"], g_params)
    d_up, d_opt = state.d_tx.update(
        d_grads, state.opt_state["discriminator"], d_params)
    if plan is not None:
        g_up, d_up = plan.shard_update(g_up), plan.shard_update(d_up)
    new_gp = optax.apply_updates(g_params, g_up)
    new_dp = optax.apply_updates(d_params, d_up)
    if plan is not None:
        new_gp, new_dp = plan.replicate(new_gp), plan.replicate(new_dp)
    new_params = assemble(new_gp, new_dp)
    new_opt = {"generator": g_opt, "discriminator": d_opt}
    new_ev = state.extra_vars if extra_vars is None else extra_vars
    if ls is not None:
        new_params = tree_select(finite, new_params, state.params)
        new_opt = tree_select(finite, new_opt, state.opt_state)
        batch_stats = tree_select(finite, batch_stats, state.batch_stats)
        if extra_vars is not None:
            new_ev = tree_select(finite, new_ev, state.extra_vars)
    new_state = state.replace(
        step=state.step + 1,
        params=new_params,
        batch_stats=batch_stats,
        opt_state=new_opt,
        extra_vars=new_ev,
        loss_scale=new_ls if ls is not None else None,
    )
    return new_state, precision_metrics(new_state)


# --------------------------------------------------------------- DCGAN


def create_dcgan_state(
    generator, discriminator, *, noise_dim: int = 100,
    lr: float = 1e-4, rng: int | jax.Array = 0,
    sample_image_shape=(28, 28, 1),
    policy=None,
) -> GANState:
    """Both Adams at 1e-4 (ref: DCGAN/tensorflow/main.py:31-32).
    ``policy`` (core/precision.MixedPolicy) attaches the shared
    DynamicLossScale when the precision policy scales the loss."""
    if isinstance(rng, int):
        rng = jax.random.key(rng)
    kg, kd = jax.random.split(rng)
    z = jnp.zeros((1, noise_dim), jnp.float32)
    gv = generator.init({"params": kg}, z, train=True)
    x = jnp.zeros((1, *sample_image_shape), jnp.float32)
    dv = discriminator.init({"params": kd, "dropout": kd}, x, train=True)
    params = {"generator": gv["params"], "discriminator": dv["params"]}
    stats = {
        "generator": gv.get("batch_stats", {}),
        "discriminator": dv.get("batch_stats", {}),
    }
    g_tx, d_tx = optax.adam(lr), optax.adam(lr)
    return GANState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=stats,
        opt_state={"generator": g_tx.init(params["generator"]),
                   "discriminator": d_tx.init(params["discriminator"])},
        extra_vars={},
        g_apply=generator.apply,
        d_apply=discriminator.apply,
        g_tx=g_tx,
        d_tx=d_tx,
        noise_dim=noise_dim,
        loss_scale=(policy.make_loss_scale() if policy is not None
                    else None),
    )


def dcgan_train_step(state: GANState, batch: dict, key: jax.Array,
                     label_smooth: float = 0.0):
    """One simultaneous G+D update on {'image'} — both gradients are taken
    at the PRE-update parameters from one shared forward, like the
    reference's two tapes over a single noise batch (ref: main.py:57-76).

    ``label_smooth``: one-sided label smoothing on the discriminator's
    REAL targets only (generator loss untouched). Off by default —
    reference parity; the synthetic gate enables it because the
    deterministic blob set lets D saturate (measured d_loss 0.04 /
    g_loss 4.2 collapse without it).
    """
    real = batch["image"]
    kz, kdrop_fake, kdrop_real = jax.random.split(key, 3)
    z = jax.random.normal(kz, (real.shape[0], state.noise_dim))

    def d_forward(d_params, images, drop_key, stats):
        out, mut = state.d_apply(
            {"params": d_params, "batch_stats": stats},
            images, train=True, mutable=["batch_stats"],
            rngs={"dropout": drop_key},
        )
        return out, mut.get("batch_stats", stats)

    def g_loss_fn(g_params):
        fake, g_mut = state.g_apply(
            {"params": g_params, "batch_stats": state.batch_stats["generator"]},
            z, train=True, mutable=["batch_stats"],
        )
        fake_logits, _ = d_forward(
            state.params["discriminator"], fake, kdrop_fake,
            state.batch_stats["discriminator"],
        )
        loss = _bce(fake_logits, True)
        return state.scale_loss(loss), (
            loss,
            g_mut.get("batch_stats", state.batch_stats["generator"]), fake
        )

    (_, (g_loss, g_stats, fake)), g_grads = jax.value_and_grad(
        g_loss_fn, has_aux=True
    )(state.params["generator"])

    def d_loss_fn(d_params):
        real_logits, d_stats = d_forward(
            d_params, real, kdrop_real, state.batch_stats["discriminator"]
        )
        fake_logits, d_stats = d_forward(
            d_params, jax.lax.stop_gradient(fake), kdrop_fake, d_stats
        )
        loss = (_bce(real_logits, True, smooth=label_smooth)
                + _bce(fake_logits, False))
        return state.scale_loss(loss), (loss, d_stats)

    (_, (d_loss, d_stats)), d_grads = jax.value_and_grad(
        d_loss_fn, has_aux=True
    )(state.params["discriminator"])

    new_state, mp = _gan_apply_gradients(
        state, g_grads, d_grads,
        g_params=state.params["generator"],
        d_params=state.params["discriminator"],
        batch_stats={"generator": g_stats, "discriminator": d_stats},
        assemble=lambda new_gp, new_dp: {"generator": new_gp,
                                         "discriminator": new_dp},
    )
    return new_state, {"g_loss": g_loss, "d_loss": d_loss, **mp}


def dcgan_sample(state: GANState, key: jax.Array, n: int = 16):
    """Sample n images in eval mode (ref: DCGAN/tensorflow/inference.py:26-29)."""
    z = jax.random.normal(key, (n, state.noise_dim))
    return state.g_apply(
        {"params": state.params["generator"],
         "batch_stats": state.batch_stats["generator"]},
        z, train=False,
    )


# ----------------------------------------------------------- ImagePool


def create_pool(size: int, image_shape, dtype=jnp.float32) -> dict:
    return {
        "images": jnp.zeros((size, *image_shape), dtype),
        "count": jnp.zeros((), jnp.int32),
    }


def pool_query(pool: dict, images: jnp.ndarray, key: jax.Array):
    """Historical-fake buffer query (ref semantics, utils.py:38-61):
    per image — fill the buffer while not full (return the image);
    afterwards 50%: swap with a random stored image and return the old
    one, else return the image. Pure: returns (out_images, new_pool)."""
    size = pool["images"].shape[0]
    keys = jax.random.split(key, images.shape[0])

    def body(carry, x):
        buf, count = carry
        img, k = x
        kp, ki = jax.random.split(k)
        p = jax.random.uniform(kp)
        rid = jax.random.randint(ki, (), 0, size)

        def insert(_):
            return (
                jax.lax.dynamic_update_index_in_dim(buf, img, count, 0),
                count + 1,
                img,
            )

        def mature(_):
            stored = buf[rid]
            take = p > 0.5
            new_buf = jnp.where(take, buf.at[rid].set(img), buf)
            out = jnp.where(take, stored, img)
            return new_buf, count, out

        buf2, count2, out = jax.lax.cond(count < size, insert, mature, None)
        return (buf2, count2), out

    (buf, count), outs = jax.lax.scan(
        body, (pool["images"], pool["count"]), (images, keys)
    )
    return outs, {"images": buf, "count": count}


# ------------------------------------------------------------ CycleGAN


def create_cyclegan_state(
    generator, discriminator, *, image_size: int = 256,
    lr_schedule=2e-4, beta1: float = 0.5, pool_size: int = POOL_SIZE,
    rng: int | jax.Array = 0, policy=None,
) -> GANState:
    """Two Adams (β1=0.5) over {G_a2b+G_b2a} and {D_a+D_b}
    (ref: CycleGAN/tensorflow/train.py:122-127); ``lr_schedule`` may be a
    float or an optax schedule (schedules.linear_decay for ref parity)."""
    if isinstance(rng, int):
        rng = jax.random.key(rng)
    ks = jax.random.split(rng, 4)
    x = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    nets = {}
    for name, net, k in (
        ("gen_a2b", generator, ks[0]), ("gen_b2a", generator, ks[1]),
        ("dis_a", discriminator, ks[2]), ("dis_b", discriminator, ks[3]),
    ):
        nets[name] = net.init({"params": k}, x, train=True)
    params = {n: v["params"] for n, v in nets.items()}
    stats = {n: v.get("batch_stats", {}) for n, v in nets.items()}
    gp = {k: params[k] for k in ("gen_a2b", "gen_b2a")}
    dp = {k: params[k] for k in ("dis_a", "dis_b")}
    g_tx = optax.adam(lr_schedule, b1=beta1)
    d_tx = optax.adam(lr_schedule, b1=beta1)
    shape = (image_size, image_size, 3)
    return GANState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=stats,
        opt_state={"generator": g_tx.init(gp),
                   "discriminator": d_tx.init(dp)},
        extra_vars={"pool_a2b": create_pool(pool_size, shape),
                    "pool_b2a": create_pool(pool_size, shape)},
        g_apply=generator.apply,
        d_apply=discriminator.apply,
        g_tx=g_tx,
        d_tx=d_tx,
        loss_scale=(policy.make_loss_scale() if policy is not None
                    else None),
    )


def cyclegan_train_step(state: GANState, batch: dict, key: jax.Array):
    """One two-phase step on {'a','b'} image batches (ref: train.py:249-255).

    Phase 1 updates both generators (LSGAN + λ·cycle + λ_id·identity);
    phase 2 updates both discriminators on real vs POOLED fakes ×0.5.
    Discriminator BN statistics also update during phase 1, mirroring the
    reference's ``training=True`` critic calls inside the generator tape
    (ref: train.py:170-175).
    """
    real_a, real_b = batch["a"], batch["b"]
    k_pool_a2b, k_pool_b2a = jax.random.split(key)

    def gen_apply(params, stats, x):
        out, mut = state.g_apply(
            {"params": params, "batch_stats": stats},
            x, train=True, mutable=["batch_stats"],
        )
        return out, mut.get("batch_stats", stats)

    def dis_apply(params, stats, x):
        out, mut = state.d_apply(
            {"params": params, "batch_stats": stats},
            x, train=True, mutable=["batch_stats"],
        )
        return out, mut.get("batch_stats", stats)

    # ---- Phase 1: generators (ref: train.py:150-205)
    def g_loss_fn(gp):
        s = dict(state.batch_stats)
        fake_a2b, s["gen_a2b"] = gen_apply(
            gp["gen_a2b"], s["gen_a2b"], real_a
        )
        recon_b2a, s["gen_b2a"] = gen_apply(
            gp["gen_b2a"], s["gen_b2a"], fake_a2b
        )
        fake_b2a, s["gen_b2a"] = gen_apply(
            gp["gen_b2a"], s["gen_b2a"], real_b
        )
        recon_a2b, s["gen_a2b"] = gen_apply(
            gp["gen_a2b"], s["gen_a2b"], fake_b2a
        )
        identity_a2b, s["gen_a2b"] = gen_apply(
            gp["gen_a2b"], s["gen_a2b"], real_b
        )
        identity_b2a, s["gen_b2a"] = gen_apply(
            gp["gen_b2a"], s["gen_b2a"], real_a
        )
        logits_b, s["dis_b"] = dis_apply(
            state.params["dis_b"], s["dis_b"], fake_a2b
        )
        logits_a, s["dis_a"] = dis_apply(
            state.params["dis_a"], s["dis_a"], fake_b2a
        )
        loss_gan_a2b = _lsgan(logits_b, True)
        loss_gan_b2a = _lsgan(logits_a, True)
        loss_cycle_a = _l1(recon_b2a, real_a)
        loss_cycle_b = _l1(recon_a2b, real_b)
        loss_id_a2b = _l1(identity_a2b, real_b)
        loss_id_b2a = _l1(identity_b2a, real_a)
        total = (
            loss_gan_a2b + loss_gan_b2a
            + (loss_cycle_a + loss_cycle_b) * LAMBDA_CYCLE
            + (loss_id_a2b + loss_id_b2a) * LAMBDA_ID
        )
        metrics = {
            "loss_gen_a2b": loss_gan_a2b, "loss_gen_b2a": loss_gan_b2a,
            "loss_cycle_a2b2a": loss_cycle_a, "loss_cycle_b2a2b": loss_cycle_b,
            "loss_id_a2b": loss_id_a2b, "loss_id_b2a": loss_id_b2a,
            "loss_gen_total": total,
        }
        return state.scale_loss(total), (s, fake_a2b, fake_b2a, metrics)

    gp = {k: state.params[k] for k in ("gen_a2b", "gen_b2a")}
    (_, (stats1, fake_a2b, fake_b2a, g_metrics)), g_grads = (
        jax.value_and_grad(g_loss_fn, has_aux=True)(gp)
    )

    # ---- Pool query on the fresh fakes (ref: train.py:251-252)
    pooled_a2b, pool_a2b = pool_query(
        state.extra_vars["pool_a2b"], jax.lax.stop_gradient(fake_a2b),
        k_pool_a2b,
    )
    pooled_b2a, pool_b2a = pool_query(
        state.extra_vars["pool_b2a"], jax.lax.stop_gradient(fake_b2a),
        k_pool_b2a,
    )

    # ---- Phase 2: discriminators (ref: train.py:207-245)
    def d_loss_fn(dp):
        s = dict(stats1)
        ra, s["dis_a"] = dis_apply(dp["dis_a"], s["dis_a"], real_a)
        fa, s["dis_a"] = dis_apply(dp["dis_a"], s["dis_a"], pooled_b2a)
        rb, s["dis_b"] = dis_apply(dp["dis_b"], s["dis_b"], real_b)
        fb, s["dis_b"] = dis_apply(dp["dis_b"], s["dis_b"], pooled_a2b)
        loss_a = (_lsgan(ra, True) + _lsgan(fa, False)) * 0.5
        loss_b = (_lsgan(rb, True) + _lsgan(fb, False)) * 0.5
        total = loss_a + loss_b
        return state.scale_loss(total), (
            s, {"loss_dis_a": loss_a, "loss_dis_b": loss_b,
                "loss_dis_total": total})

    dp = {k: state.params[k] for k in ("dis_a", "dis_b")}
    (_, (stats2, d_metrics)), d_grads = jax.value_and_grad(
        d_loss_fn, has_aux=True
    )(dp)
    new_state, mp = _gan_apply_gradients(
        state, g_grads, d_grads, g_params=gp, d_params=dp,
        batch_stats=stats2,
        assemble=lambda new_gp, new_dp: {**new_gp, **new_dp},
        extra_vars={"pool_a2b": pool_a2b, "pool_b2a": pool_b2a},
    )
    return new_state, {**g_metrics, **d_metrics, **mp}


def cyclegan_translate(state: GANState, images, direction: str = "a2b"):
    """Eval-mode translation (ref: CycleGAN/tensorflow/inference.py:34-68)."""
    name = f"gen_{direction}"
    return state.g_apply(
        {"params": state.params[name],
         "batch_stats": state.batch_stats[name]},
        images, train=False,
    )


def fit_gan(
    state: GANState,
    train_step,
    train_data,
    mesh,
    *,
    epochs: int,
    workdir: str = "runs/gan",
    save_every: int = 2,
    log_every: int = 50,
    resume: bool = False,
    resume_epoch: int | None = None,
    check_numerics: bool = False,
    shard_weight_update: bool = False,
    async_checkpoint: bool = False,
    preempt=None,
    watchdog=None,
    prefetch_depth: int = 2,
):
    """Minimal GAN epoch loop: compiled step + loggers + TB + Orbax saves
    every ``save_every`` epochs keeping 3 (ref: DCGAN/tensorflow/main.py:39,
    80-83; CycleGAN saves every epoch with the epoch tracked in the
    checkpoint, ref: train.py:329-333 — pass save_every=1).

    ``preempt``: optional zero-arg callable polled at every epoch
    boundary; when truthy the loop saves off-cadence and stops (the GAN
    analog of Trainer's SIGTERM handling — epoch-granular because GAN
    epochs on the reference workloads are short; resume restarts at the
    next epoch).

    ``watchdog``: optional Trainer.StallWatchdog — started here, beaten
    per step/drain, stopped on exit (same hang-detection contract as
    Trainer.fit).

    ``prefetch_depth``: device batches kept in flight ahead of the step
    by the async feed (data/prefetch.py); 1 = classic double
    buffering."""
    from deepvision_tpu.core.step import (
        compile_checked_train_step,
        compile_train_step,
    )
    from deepvision_tpu.train.checkpoint import CheckpointManager
    from deepvision_tpu.train.loggers import Loggers, TensorBoardWriter

    mgr = CheckpointManager(f"{workdir}/ckpt", async_save=async_checkpoint)
    loggers = Loggers()
    tb = TensorBoardWriter(f"{workdir}/tb")
    start_epoch = 0
    if resume and mgr.latest_epoch() is not None:
        state, meta = mgr.restore(state, resume_epoch)
        start_epoch = meta["epoch"] + 1
        if meta.get("loggers"):
            loggers = meta["loggers"]
    state_spec = None
    if shard_weight_update:
        from deepvision_tpu.core.sharding import zero1_plan
        from deepvision_tpu.core.step import weight_update_sharding

        plan = zero1_plan(mesh)
        if plan is None:
            raise ValueError(
                "--zero1 asked for weight-update sharding but the "
                "[[shardcheck.rule]] opt_state row does not prescribe a "
                "largest(...) spec — declare it in the table first")
        state = state.replace(zero1_plan=plan)
        state_spec = weight_update_sharding(state, mesh)
    compiler = (
        compile_checked_train_step if check_numerics else compile_train_step
    )
    step = compiler(train_step, mesh, state_spec=state_spec)
    base_key = jax.random.key(np.uint32(1234))
    if watchdog is not None:
        watchdog.start()
    try:
        state, loggers = _gan_epoch_loop(
            state, step, train_data, mesh, start_epoch, epochs,
            base_key, mgr, loggers, tb, save_every, log_every,
            preempt, watchdog, prefetch_depth,
        )
    finally:
        # an exception mid-epoch must still stop the daemon watchdog
        # (abort=True could otherwise os._exit(75) during unrelated
        # exception handling, masking the real traceback) and close the
        # manager so staged async saves commit or are cleanly dropped
        tb.flush()
        mgr.close()
        if watchdog is not None:
            watchdog.stop()
    return state, loggers


def _gan_epoch_loop(state, step, train_data, mesh, start_epoch, epochs,
                    base_key, mgr, loggers, tb, save_every, log_every,
                    preempt, watchdog, prefetch_depth=2):
    from deepvision_tpu.core.prng import KeySeq
    from deepvision_tpu.data.prefetch import DevicePrefetcher, FeedTelemetry
    from deepvision_tpu.obs.trace import span
    from deepvision_tpu.train.loggers import input_wait_metrics

    for epoch in range(start_epoch, epochs):
        # epoch-derived noise stream (core.prng.KeySeq, the blessed
        # threading idiom — jaxlint JX103): resume reproduces the
        # uninterrupted run's z draws / pool coin flips (same rationale
        # as Trainer)
        keys = KeySeq(jax.random.fold_in(base_key, epoch))
        t0 = time.time()
        # pending/drain split (same as Trainer.train_epoch): metrics stay
        # device-side until a drain, so the dispatch queue keeps running —
        # per-batch float() here serialized a D2H round trip per metric
        # per batch and stalled the device between steps.
        pending: list[dict] = []  # device scalars not yet fetched
        fetched: list[dict] = []  # host floats; each metric fetched ONCE

        def drain():
            # completed-step heartbeats, same rationale as Trainer
            if not pending:
                return
            with span("drain", cat="train"):
                for m in pending:
                    fetched.append({k: float(v) for k, v in m.items()})
                    if watchdog is not None:
                        watchdog.beat()
                pending.clear()

        # async H2D feed (data/prefetch.py, same as Trainer.train_epoch):
        # producer-thread sharding keeps `prefetch_depth` transfers in
        # flight; close() in the finally stops the thread on every exit.
        # Spans (obs/trace.py) mirror the Trainer's epoch/step/drain
        # attribution; no-ops unless the tracer is enabled (--trace).
        tel = FeedTelemetry()
        with span("epoch", cat="train", args={"epoch": int(epoch)}):
            feed = DevicePrefetcher(train_data(epoch), mesh,
                                    depth=prefetch_depth, telemetry=tel)
            try:
                for i, device_batch in enumerate(feed):
                    with span("step", cat="train"):
                        state, metrics = step(state, device_batch,
                                              next(keys))
                        pending.append(metrics)
                    # beats land only in drain() (per COMPLETED step) — a
                    # dispatch-side beat would mask a wedged device until
                    # the dispatch queue itself blocked; cadence bounded
                    # at 32 batches regardless of log_every (same fix as
                    # Trainer)
                    if watchdog is not None \
                            and i % min(32, log_every or 32) == 0:
                        drain()
                    if log_every and i % log_every == 0:
                        drain()  # syncs mostly-finished work; O(n) total
                        print(f"[epoch {epoch} batch {i}] " + " ".join(
                            f"{k}={v:.4f}"
                            for k, v in sorted(fetched[-1].items())
                        ), flush=True)
            finally:
                feed.close()
            drain()  # drains the dispatch queue — precedes the timing read
        epoch_metrics = {
            k: float(np.mean([m[k] for m in fetched]))
            for k in (fetched[0] if fetched else {})
        }
        # per-stage feed telemetry, same metric names as the Trainer
        epoch_metrics.update(input_wait_metrics(tel.summary()))
        loggers.log_metrics(epoch, epoch_metrics)
        for k, v in epoch_metrics.items():
            tb.scalar(k, v, epoch)
        # wall-clock per epoch, the reference's only perf signal
        # (ref: DCGAN/tensorflow/main.py:85, CycleGAN/train.py:335-336)
        print(f"[epoch {epoch}] " + " ".join(
            f"{k}={v:.4f}" for k, v in sorted(epoch_metrics.items())
        ) + f" time={time.time() - t0:.1f}s", flush=True)
        stop = preempt is not None and preempt()
        if (epoch + 1) % save_every == 0 or epoch == epochs - 1 or stop:
            with span("checkpoint", cat="train"):
                mgr.save(epoch, state, loggers=loggers)
        if stop:
            print(f"[preempted] after completed epoch {epoch}", flush=True)
            break
    return state, loggers
