"""The Trainer: one epoch-loop harness for the whole zoo.

Re-expresses the reference's copy-pasted per-model ``run_epochs`` /
``train`` / ``validate`` (ref: ResNet/pytorch/train.py:392-520) as one
class over the compiled step functions:

- pre-train validation at epoch 0 (ref: train.py:390),
- per-N-batch running-loss prints (ref: train.py:472-483),
- top-1/top-5 validation with exact epoch aggregation (ref: :488-520),
- plateau/step LR scheduling (ref: :412-415),
- checkpoint every epoch with loggers history inside (ref: :417-428),
- examples/sec and images/sec/chip (the reference's only throughput
  metric, ref: YOLO/tensorflow/train.py:212-239, promoted here to a
  first-class logged metric),
- TensorBoard split writers.
"""

from __future__ import annotations

import fcntl
import os
import shutil
import signal
import threading
import time
from pathlib import Path
from typing import Callable, Iterable

import jax
import numpy as np

from deepvision_tpu.core import shard_batch
from deepvision_tpu.core.prng import KeySeq
from deepvision_tpu.core.step import (
    checkify_error_cls as _checkify_error,
    compile_eval_step,
    compile_train_step,
)
from deepvision_tpu.data.prefetch import DevicePrefetcher, FeedTelemetry
from deepvision_tpu.obs.profiler import ProfileWindow, sample_memory_gauges
from deepvision_tpu.obs.trace import span
from deepvision_tpu.resilience.recovery import (
    NumericDivergence,
    RecoveryCounters,
    RecoveryError,
)
from deepvision_tpu.train.checkpoint import CheckpointManager
from deepvision_tpu.train.loggers import (
    Loggers,
    TensorBoardWriter,
    input_wait_metrics,
    recovery_metrics,
)
from deepvision_tpu.train.optimizers import make_optimizer, set_lr_scale
from deepvision_tpu.train.state import create_train_state
from deepvision_tpu.train.steps import (
    aggregate_eval_parts,
    classification_eval_step,
    classification_train_step,
)


class PreemptLock:
    """Advisory cross-process mutex (``fcntl.flock``) serializing the
    preemption-checkpoint protocol.

    Root cause of the r4 field crash (logs/gate_yolo_r4c.log:866-910):
    a relaunched ``--resume`` process's stale-cleanup ``rmtree`` of
    ``ckpt_preempt/`` ran while the dying process was still inside
    Orbax finalize, deleting the ``*.orbax-checkpoint-tmp`` staging dir
    out from under the atomic rename (``FileNotFoundError: ...
    meta.orbax-checkpoint-tmp -> meta``). Nothing serialized the three
    parties that touch the directory: the dying writer
    (``_save_preempt``), a concurrent resumer (``resume``'s inspect /
    restore / stale-clear), and the epoch-supersede clear in ``fit``.

    All three now run under this lock. ``flock`` conflicts between
    separate open file descriptions, so it excludes both other
    processes and other Trainer instances in-process (threads).
    Acquisition is bounded: a waiter that times out proceeds WITHOUT
    touching the preemption directory (a wedged lock holder must not
    block recovery forever; skipping the clear is always safe because
    resume ignores preemption saves older than the latest epoch
    checkpoint).
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._fd: int | None = None

    def acquire(self, timeout: float | None = None) -> bool:
        """True once the exclusive lock is held; False on timeout, or
        immediately on a filesystem that cannot flock at all
        (ENOTSUP/ENOLCK — gcsfuse, NFS without lockd): fail fast into
        the callers' degraded paths instead of spinning the full
        timeout on every acquisition."""
        import errno

        contention = {errno.EWOULDBLOCK, errno.EAGAIN, errno.EACCES,
                      errno.EINTR}
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._fd = fd
                return True
            except OSError as e:
                if e.errno not in contention:
                    os.close(fd)
                    print(f"[preempt-lock] {self.path}: flock unsupported "
                          f"({e}); proceeding without cross-process "
                          "locking", flush=True)
                    return False
                if deadline is not None and time.monotonic() >= deadline:
                    os.close(fd)
                    return False
                time.sleep(0.05)

    def release(self) -> None:
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None


class Trainer:
    def __init__(
        self,
        model,
        config: dict,
        mesh,
        train_data: Callable[[int], Iterable[dict]],
        val_data: Callable[[], Iterable[dict]],
        *,
        workdir: str | Path = "runs",
        steps_per_epoch: int | None = None,
        train_step=classification_train_step,
        eval_step=classification_eval_step,
        log_every: int = 10,
        seed: int = 0,
        check_numerics: bool = False,
        shard_weight_update: bool = False,
        async_checkpoint: bool = False,
        keep_best: bool = False,
        data_echo: int = 1,
        prefetch_depth: int = 2,
        stall_timeout: float | None = None,
        stall_abort: bool = False,
        rss_limit_gb: float | None = None,
        recovery=None,
        fault_injector=None,
        sentinel=None,
        ckpt_integrity: bool = True,
        profile_steps: str | None = None,
        profile_dir: str | Path | None = None,
    ):
        self.model = model
        self.config = config
        self.mesh = mesh
        self.train_data = train_data
        self.val_data = val_data
        self.workdir = Path(workdir) / config.get("name", "run")
        self.log_every = log_every
        # data echoing (Choi et al. 2019): run `data_echo` optimizer
        # steps per transferred batch (fresh dropout/augment PRNG each),
        # multiplying effective step throughput when the host pipeline or
        # H2D link — not the chip — is the bottleneck
        self.data_echo = max(1, int(data_echo))
        # async feed (data/prefetch.py): device batches kept in flight
        # ahead of the step; 1 = classic double buffering
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}")
        self.prefetch_depth = int(prefetch_depth)

        # step-count schedules see OPTIMIZER steps: with echoing each
        # data epoch advances the counter data_echo * steps_per_epoch
        self.tx, self.plateau = make_optimizer(
            config, (steps_per_epoch or 1000) * self.data_echo
        )
        size = config.get("input_size", 224)
        sample = np.zeros(
            (1, size, size, config.get("channels", 3)), np.float32
        )
        # numerics policy (core/precision.py): the config's explicit
        # "precision" declaration (train.py resolves CLI > config);
        # a scaling policy attaches the DynamicLossScale to the state
        from deepvision_tpu.core.precision import get_policy

        self.policy = get_policy(config.get("precision", "bf16"))
        self.state = create_train_state(model, self.tx, sample, rng=seed,
                                        policy=self.policy)
        state_spec = None
        if shard_weight_update:
            # ZeRO-1 (arXiv:2004.13336): optimizer state + the weight
            # update sharded over the data axis. Plan and state specs
            # both come from the [[shardcheck.rule]] table via the
            # partition-rule engine (core/sharding.py); the plan rides
            # the state as a STATIC field so apply_gradients places the
            # reduce-scatter/all-gather. Attached before any host copy
            # of the state (recovery's _init_state) so every rollback /
            # restore template carries the same static plan — a
            # plan-less state would silently retrace a replicated
            # update program.
            from deepvision_tpu.core.sharding import zero1_plan
            from deepvision_tpu.core.step import weight_update_sharding

            plan = zero1_plan(mesh)
            if plan is None:
                raise ValueError(
                    "--zero1 asked for weight-update sharding but the "
                    "[[shardcheck.rule]] opt_state row does not "
                    "prescribe a largest(...) spec — declare it in the "
                    "table first")
            self.state = self.state.replace(zero1_plan=plan)
            state_spec = weight_update_sharding(self.state, mesh)
        self._state_spec = state_spec
        # self-healing (resilience/): with a RecoveryPolicy the checkify
        # NaN/Inf tripwire becomes rollback-and-skip instead of a crash,
        # transient data reads retry with backoff, and resume verifies
        # checkpoint integrity with quarantine + fallback. The injector
        # is the deterministic chaos harness those paths are tested with.
        self.recovery = recovery
        self.injector = fault_injector
        self.rec_counters = RecoveryCounters()
        self._consecutive_rollbacks = 0
        # silent-failure defense (resilience/sentinel.py): in-graph
        # sentinel scalars fused into the compiled step, z-scored on
        # the existing drain cadence; cross-host state audits every
        # monitor.audit_every RUN steps (epoch * steps_per_epoch +
        # step — the epoch-anchored counter that makes resumes and
        # supervisor replays audit/inject at identical points)
        self.sentinel = sentinel
        self.steps_per_epoch = steps_per_epoch
        if sentinel is not None:
            from deepvision_tpu.resilience.sentinel import sentinel_step

            train_step = sentinel_step(train_step)
        if recovery is not None:
            if not check_numerics:
                # rollback needs the tripwire: without checkify the NaN
                # silently corrupts the weights and nothing ever raises
                print("[recovery] enabling --check-numerics (the NaN/Inf "
                      "tripwire recovery rolls back from)", flush=True)
                check_numerics = True
            # rollback target of last resort (no checkpoint saved yet):
            # a host-side copy of the pristine initial state. Costs one
            # state-sized host buffer — the price of epoch-0 recovery.
            self._init_state = jax.tree.map(np.asarray, self.state)
        if check_numerics:  # NaN/Inf tripwire (SURVEY §5.2)
            from deepvision_tpu.core.step import compile_checked_train_step

            self._train_step = compile_checked_train_step(
                train_step, mesh, state_spec=state_spec
            )
        else:
            self._train_step = compile_train_step(
                train_step, mesh, state_spec=state_spec
            )
        # eval must see the SAME state sharding: pinning a sharded
        # opt_state to replicated would all-gather it every val batch
        self._eval_step = compile_eval_step(
            eval_step, mesh, state_spec=state_spec
        )
        self.loggers = Loggers()
        self.tb = TensorBoardWriter(self.workdir / "tb")
        # async: per-epoch saves overlap the next epoch's compute;
        # keep_best: retention keyed on the plateau metric instead of
        # recency (ref: YOLO/tensorflow/train.py:243-257 best-val save)
        # ckpt_integrity=False skips the per-save manifest hashing (one
        # SHA-256 pass over the committed files) — the opt-out for
        # multi-GB states where seconds per epoch matter more than a
        # verified --recover resume later
        self.ckpt = CheckpointManager(
            self.workdir / "ckpt",
            async_save=async_checkpoint,
            keep_best_of="plateau_metric" if keep_best else None,
            fault_injector=fault_injector,
            integrity=ckpt_integrity,
        )
        self.start_epoch = 0
        self.start_step = 0  # mid-epoch resume point (preemption)
        self.best_metric = -float("inf")
        # preemption (SURVEY §5.3 — the reference has no preemption
        # handling at all): a signal flips _preempt; the step loop saves
        # a synchronous mid-epoch checkpoint into ckpt_preempt/ and fit()
        # returns with .preempted set so the launcher can exit 143.
        self._preempt = False
        self.preempted = False
        # serializes save / resume-inspect / stale-clear of ckpt_preempt/
        # across processes (see PreemptLock). The lock file lives BESIDE
        # the directory so clearing the directory can't delete the lock.
        self._plock = PreemptLock(self.workdir / "ckpt_preempt.lock")
        self.preempt_lock_timeout = 300.0  # bounded wait; see PreemptLock
        # hang detection (SURVEY §5.3): heartbeat per step/val batch
        self._watchdog = (
            StallWatchdog(stall_timeout, abort=stall_abort)
            if stall_timeout else None
        )
        # host-RSS self-preemption: the axon relay TPU client leaks
        # ~one staged input batch of host memory per device_put (the
        # framework's own loop is leak-free — tools RSS check on CPU
        # holds flat over hundreds of steps), so multi-hour runs grow
        # without bound and an eventual OOM kill (SIGKILL, no save)
        # loses the epoch. Crossing the limit triggers the EXISTING
        # preemption path instead: sync mid-epoch checkpoint, exit 143,
        # supervised relaunch into bit-exact --resume with a fresh
        # process (and a fresh, small RSS). Checked at step granularity
        # (cheap: one /proc read per log_every batches).
        self.rss_limit_bytes = (
            int(rss_limit_gb * 1e9) if rss_limit_gb else None
        )
        if self.rss_limit_bytes is not None:
            _check_rss_limit_sane(self.rss_limit_bytes)
        self._rss_preempted = False
        # observability (obs/): an opt-in jax.profiler window over
        # global steps A..B (--profile-steps), and a monotonic
        # transferred-batch counter feeding it. Span tracing needs no
        # state here — the loops emit through the process tracer, which
        # the CLI enables/exports (--trace).
        self._profiler = (
            ProfileWindow(profile_steps,
                          Path(profile_dir) if profile_dir
                          else self.workdir / "profile")
            if profile_steps else None
        )
        self._global_step = 0
        # multi-host cluster coordination (resilience/cluster.py):
        # attach_cluster() sets the member; None = single-host behavior
        # exactly as before
        self.cluster = None
        self._cluster_stop: int | None = None
        # silent-failure exit surface: replay_done set when a
        # supervisor replay window completes; sdc_detected when a
        # cross-host audit diverged (train.py exits 76 on it)
        self.replay_done = False
        self.sdc_detected = False
        # per-epoch KeySeq derived in train_epoch from this root key
        self._base_key = jax.random.key(seed + 1)

    # -- multi-host cluster (resilience/cluster.py) ----------------------
    def attach_cluster(self, member) -> None:
        """Join a cluster coordination directory: per-batch heartbeats,
        the coordinated checkpoint-on-preempt barrier, and the degraded
        exit rules. Call before :meth:`resume`/:meth:`fit`. In cluster
        mode the PreemptLock is bypassed — the supervisor serializes
        generations, and a shared flock would deadlock the COLLECTIVE
        preemption save (every host must be inside save() at once)."""
        self.cluster = member

    def _cluster_poll(self, epoch: int, dispatched: int) -> bool:
        """Pre-dispatch poll (once per batch): heartbeat + barrier
        marker. Returns True when the epoch must be ABANDONED now —
        a stale marker from an earlier epoch means peers already
        exited, and any further fetch could wedge on a collective
        nobody will ever complete (so the caller returns WITHOUT the
        final drain)."""
        m = self.cluster
        m.beat(self._global_step, epoch)
        if self._preempt and m.read_barrier() is None:
            # this host holds the preemption notice: publish the
            # cluster-wide stop point far enough ahead (barrier_lead >
            # 2x the forced fetch cadence below) that every peer sees
            # the marker strictly before passing it
            mk = m.write_barrier(epoch, dispatched + m.barrier_lead)
            print(f"[cluster] host {m.host}: preemption notice — save "
                  f"barrier requested at epoch {mk.get('epoch', epoch)} "
                  f"step {mk.get('stop_step')}", flush=True)
        mark = m.read_barrier()
        if mark is None:
            return False
        self._preempt = True  # the notice is cluster-wide from here on
        if mark.get("after_epoch") is not None:
            if mark["after_epoch"] < epoch:
                return self._cluster_degrade(
                    f"stale after-epoch marker ({mark['after_epoch']} < "
                    f"epoch {epoch}): peers exited at the boundary")
            return False  # exit after this epoch's save (boundary check)
        if mark["epoch"] < epoch:
            return self._cluster_degrade(
                f"stale save barrier for epoch {mark['epoch']} "
                f"(now in epoch {epoch})")
        if mark["epoch"] == epoch and self._cluster_stop is None:
            if dispatched >= mark["stop_step"]:
                # at-or-past the stop on FIRST sight (>=: this poll runs
                # pre-dispatch, so even equality means batch `stop`
                # would dispatch next and wedge every peer's drain at an
                # unmatched collective) — the skew invariant was
                # violated; degrade instead of hanging
                return self._cluster_degrade(
                    f"save barrier step {mark['stop_step']} already "
                    f"reached (dispatched {dispatched})")
            self._cluster_stop = int(mark["stop_step"])
        return False

    def _run_step(self, epoch: int, step_in_epoch: int) -> int:
        """The epoch-anchored run-step counter (epoch *
        steps_per_epoch + step): identical for the uninterrupted run,
        a mid-epoch resume, and a supervisor replay — the determinism
        the sdc sites and the audit cadence key on. Falls back to the
        process-local transferred-batch counter when the epoch length
        is unknown (no drills run that way)."""
        if self.steps_per_epoch:
            return epoch * self.steps_per_epoch + step_in_epoch
        return self._global_step

    def _cluster_audit(self, epoch: int, run_step: int) -> None:
        """Fingerprint the replicated state and run the lag-tolerant
        cross-host comparison; a divergence is an SDC somewhere in the
        fleet — publish the marker and abandon the generation (exit
        76) so the supervisor can attribute by replay bisection."""
        fp = self.sentinel.fingerprint_state(self.state)
        self.sentinel.audits.inc()
        div = self.cluster.record_audit(run_step, fp)
        if div is not None:
            self._raise_divergence(div)

    def _raise_divergence(self, div: dict):
        from deepvision_tpu.resilience.sentinel import AuditDivergence

        err = AuditDivergence(div["step"], div["fps"])
        print(f"[sentinel] {err} — abandoning the generation for "
              "supervisor attribution (replay bisection)", flush=True)
        self.cluster.write_divergence(div)
        self.sdc_detected = True
        raise err

    def _cluster_degrade(self, why: str) -> bool:
        print(f"[cluster] host {self.cluster.host}: {why}; exiting "
              "WITHOUT a coordinated save — resume falls back to the "
              "newest commonly-verified epoch", flush=True)
        self.preempted = True
        return True

    def _cluster_maybe_save(self, epoch: int, dispatched: int,
                            drain) -> bool:
        """Post-dispatch barrier stop: every host halts at the SAME
        dispatched-step count, rendezvouses on arrive markers (file
        polls only — a waiting host never fetches, so it cannot wedge
        a peer), then commits ONE collective mid-epoch checkpoint. A
        rendezvous timeout (peer lost after the notice) degrades to
        no-save. True = epoch over, preempted."""
        if self._cluster_stop is None or dispatched < self._cluster_stop:
            return False
        m = self.cluster
        stop = self._cluster_stop
        m.arrive(stop)
        if not m.await_all_arrived(timeout_s=m.barrier_timeout_s):
            return self._cluster_degrade(
                f"save barrier at step {stop} timed out after "
                f"{m.barrier_timeout_s:.0f}s (peer lost?)")
        # all hosts dispatched exactly `stop` steps: every collective
        # is matched, so this drain cannot wedge and the save commits
        # one common step on every host
        drain()
        self._save_preempt(epoch, stop)
        m.mark_committed(epoch, stop)
        print(f"[cluster] host {m.host}: coordinated save committed at "
              f"epoch {epoch} step {stop}", flush=True)
        self.preempted = True
        return True

    # -- preemption ------------------------------------------------------
    @property
    def _preempt_dir(self) -> Path:
        return self.workdir / "ckpt_preempt"

    @property
    def _preempt_unlocked_dir(self) -> Path:
        # escape-hatch target for a save whose PreemptLock acquisition
        # timed out: writing (and pre-clearing) a SEPARATE directory
        # means the unlocked path can never rmtree data the wedged lock
        # holder is still reading/writing in ckpt_preempt/ — the exact
        # class of race the lock exists to prevent. Only timed-out
        # writers ever write here; resume() scans both directories.
        return self.workdir / "ckpt_preempt_unlocked"

    def request_preempt(self, signum=None, frame=None) -> None:
        """Async-signal-safe: only flips a flag; the step loop performs
        the synchronous save at the next step boundary."""
        self._preempt = True

    def install_preemption_handler(self, signals=(signal.SIGTERM,)) -> None:
        """Route SIGTERM (the TPU-VM/k8s preemption grace signal) into
        :meth:`request_preempt`. Called by the CLI, not the ctor — a
        library must not install process-wide handlers implicitly."""
        for s in signals:
            signal.signal(s, self.request_preempt)

    def _save_preempt(self, epoch: int, step_in_epoch: int) -> None:
        # separate sync manager + directory: a mid-epoch save must never
        # enter the main manager's retention (keep_best would rank it by
        # a metric it doesn't have) and must be committed before exit.
        # Always start fresh: a second preemption of the SAME epoch
        # (resume -> preempted again) would otherwise hit Orbax's
        # step-already-exists error.
        # The whole clear+save runs under the cross-process PreemptLock:
        # a concurrently relaunched --resume process must not rmtree the
        # in-flight Orbax staging dir mid-finalize (the r4 field crash).
        # On lock timeout save anyway — a best-effort save under a
        # wedged lock holder beats losing the mid-epoch state — but into
        # the SEPARATE ckpt_preempt_unlocked/ directory, so the unlocked
        # path never deletes data the wedged holder may be touching.
        got = False
        target = self._preempt_dir
        if self.cluster is not None:
            # cluster mode: no flock — the supervisor serializes
            # generations (no concurrent resumer exists) and the save
            # below is COLLECTIVE, so hosts serializing on a lock would
            # deadlock it. Host 0 clears; peers rendezvous on the
            # marker so nobody opens a manager inside a directory
            # mid-rmtree.
            if not self.cluster.coordinate_clear(
                    f"{epoch}-{step_in_epoch}",
                    self._clear_preempt_ckpt):
                print("[cluster] preempt-dir clear rendezvous timed "
                      "out; saving anyway", flush=True)
        else:
            got = self._plock.acquire(timeout=self.preempt_lock_timeout)
            if not got:
                target = self._preempt_unlocked_dir
                print("[preempted] WARNING: preemption lock not "
                      f"acquired in {self.preempt_lock_timeout:.0f}s; "
                      f"saving unlocked to {target}", flush=True)
        try:
            delay = float(os.environ.get("DVTPU_PREEMPT_SAVE_DELAY", "0"))
            if delay:  # test hook: widen the locked critical section
                time.sleep(delay)
            if self.cluster is None:
                shutil.rmtree(target, ignore_errors=True)
            # no integrity manifest here: the SIGTERM grace window is
            # budgeted in seconds, and preemption saves are restored
            # unverified (superseded at the next epoch save anyway)
            mgr = CheckpointManager(target, max_to_keep=1,
                                    integrity=False)
            try:
                mgr.save(
                    epoch, self.state, loggers=self.loggers,
                    extra={
                        "step_in_epoch": int(step_in_epoch),
                        "data_echo": self.data_echo,
                        **({"plateau": self.plateau.state_dict()}
                           if self.plateau else {}),
                    },
                    best_metric=self.best_metric,
                )
            finally:
                mgr.close()
        finally:
            if got:
                self._plock.release()
        self.ckpt.wait_until_finished()  # commit in-flight async saves too
        print(f"[preempted] saved epoch {epoch} step {step_in_epoch} "
              f"to {target}", flush=True)

    def _clear_preempt_ckpt(self) -> None:
        if self._preempt_dir.exists():
            shutil.rmtree(self._preempt_dir, ignore_errors=True)

    # -- resume ----------------------------------------------------------
    def resume(self, epoch: int | None = None) -> None:
        """Restore latest (or given) checkpoint incl. host-side scheduler +
        metric history — the reference restores model/opt/scheduler/loggers
        the same way (ref: ResNet/pytorch/train.py:293-307).

        A preemption checkpoint (``ckpt_preempt/``, written by the SIGTERM
        path) newer than the latest epoch checkpoint takes precedence and
        resumes MID-epoch at its recorded step, bit-identical to the
        uninterrupted run (epoch-seeded data order + replayed PRNG chain).

        The whole inspect / restore / stale-clear runs under the
        cross-process PreemptLock: it both WAITS for a dying process's
        in-flight preemption save (then resumes from it, instead of
        missing the newest state) and guarantees the stale-clear rmtree
        can never delete that save's Orbax staging dir mid-finalize
        (the r4 field crash). If the lock cannot be acquired in
        ``preempt_lock_timeout`` the resume degrades to READ-ONLY: it
        restores the newest finalized preemption save if one exists
        (without clearing anything — never deleting data a wedged
        holder may be touching), else falls back to the latest epoch
        checkpoint, else raises with an actionable message so a
        supervisor's relaunch loop effectively polls the lock.
        """
        if epoch is None and self.cluster is not None:
            # cluster mode: N hosts resume CONCURRENTLY (the restore is
            # collective) — no flock, read-only scan; host 0 owns any
            # clearing, at the next epoch save
            if self._resume_from_preempt(allow_clear=False):
                return
        elif epoch is None:
            got = self._plock.acquire(timeout=self.preempt_lock_timeout)
            if got:
                try:
                    if self._resume_from_preempt():
                        return
                finally:
                    self._plock.release()
            else:
                print("[resume] WARNING: preemption lock not acquired in "
                      f"{self.preempt_lock_timeout:.0f}s; read-only "
                      "preemption scan, nothing will be cleared",
                      flush=True)
                if self._resume_from_preempt(allow_clear=False):
                    return
                if self.ckpt.latest_epoch() is None:
                    raise RuntimeError(
                        "resume blocked: the preemption lock "
                        f"{self._plock.path} is held (a dying process "
                        "may still be saving), no finalized preemption "
                        "checkpoint is visible yet, and no epoch "
                        "checkpoint exists to fall back to — retry "
                        "once the in-flight save lands")
        if self.recovery is not None and epoch is None:
            # integrity-checked restore: a corrupt/truncated latest epoch
            # is quarantined and the newest verified older epoch wins,
            # instead of an Orbax decode crash killing the relaunch
            self.state, meta = self.ckpt.restore_verified(
                self.state, counters=self.rec_counters,
                fingerprint_fn=self._fingerprint_fn())
        else:
            if self.recovery is not None:
                # operator-pinned epoch: verify it too, but NEVER
                # silently substitute another epoch for an explicit pin
                # — fail with the reason instead
                ok, why = self.ckpt.verify_epoch(epoch)
                if not ok:
                    raise RuntimeError(
                        f"--recover resume: pinned epoch {epoch} failed "
                        f"integrity verification ({why}); pick another "
                        "epoch, or drop the pin to fall back to the "
                        "newest verified epoch automatically")
            self.state, meta = self.ckpt.restore(self.state, epoch)
        self._reshard_state()
        self._apply_meta(meta)
        self.start_epoch = meta["epoch"] + 1
        self.start_step = 0

    def _fingerprint_fn(self):
        """State-fingerprint recompute hook for the verified restore
        (audited checkpoints): with sentinels on, a restore whose
        recomputed fingerprint mismatches the manifest's save-time one
        is corruption that predates serialization and quarantines like
        any checksum failure."""
        if self.sentinel is None:
            return None
        return self.sentinel.fingerprint_state

    def _reshard_state(self) -> None:
        """Re-establish the compiled step's state shardings after a
        checkpoint restore. Orbax restores host-side arrays committed to
        a single device; the donated jit refuses committed args whose
        sharding mismatches its in_shardings, so a ZeRO-1
        (--shard-weight-update) run could train but never RESUME until
        this device_put (found by the composed-resilience test,
        VERDICT r4 weak #6). No-op for replicated (default) runs."""
        if self._state_spec is None:
            return
        from deepvision_tpu.core.sharding import make_shard_and_gather_fns

        shard_fn, _ = make_shard_and_gather_fns(self._state_spec, self.mesh)
        self.state = shard_fn(self.state)

    def _resume_from_preempt(self, allow_clear: bool = True) -> bool:
        """Restore the newest mid-epoch preemption checkpoint (from
        ``ckpt_preempt/`` or the unlocked escape-hatch directory) if it
        is newer than the latest epoch checkpoint (True), else report
        False. With ``allow_clear`` (held PreemptLock) stale
        directories are garbage-collected; read-only callers (lock
        timeout) never delete anything."""
        latest = self.ckpt.latest_epoch()
        best = None  # (epoch, step_in_epoch, dir)
        for d in (self._preempt_dir, self._preempt_unlocked_dir):
            if not d.exists():
                continue
            pmgr = CheckpointManager(d, max_to_keep=1)
            try:
                p_epoch = pmgr.latest_epoch()
                if p_epoch is None or (latest is not None
                                       and p_epoch <= latest):
                    # stale (superseded by an epoch save) or no
                    # finalized step (crashed/in-flight save leftovers)
                    if allow_clear and not (
                        d == self._preempt_unlocked_dir
                        and p_epoch is None
                    ):
                        # never clear a step-less unlocked dir even
                        # under the lock: its writer is by definition
                        # NOT a lock holder, so an in-flight unlocked
                        # save is indistinguishable from garbage
                        shutil.rmtree(d, ignore_errors=True)
                    continue
                # rank candidates by (epoch, step_in_epoch): with both
                # a locked and an unlocked save present, the furthest
                # training point wins
                meta = pmgr.restore_meta(p_epoch)
                cand = (p_epoch, int(meta["extra"].get("step_in_epoch",
                                                       0)), d)
                if best is None or cand[:2] > best[:2]:
                    best = cand
            finally:
                pmgr.close()
        if best is None:
            return False
        p_epoch, _, d = best
        pmgr = CheckpointManager(d, max_to_keep=1)
        try:
            self.state, meta = pmgr.restore(self.state, p_epoch)
        finally:
            pmgr.close()
        self._reshard_state()
        saved_echo = meta["extra"].get("data_echo", 1)
        if saved_echo != self.data_echo:
            # the step index and PRNG replay are in units of
            # the saved echo factor — resuming under another
            # silently diverges from the uninterrupted run
            raise ValueError(
                f"preemption checkpoint was written with "
                f"--data-echo {saved_echo}; resume with the "
                f"same value (got {self.data_echo})")
        self._apply_meta(meta)
        self.start_epoch = meta["epoch"]  # redo this epoch...
        self.start_step = meta["extra"]["step_in_epoch"]  # here
        return True

    def _apply_meta(self, meta: dict) -> None:
        if meta.get("loggers"):
            self.loggers = meta["loggers"]
        extra = meta.get("extra", {})
        if self.plateau is not None and "plateau" in extra:
            self.plateau.load_state_dict(extra["plateau"])
            self.state = self.state.replace(
                opt_state=set_lr_scale(self.state.opt_state,
                                       self.plateau.scale)
            )
        if meta.get("best_metric") is not None:
            self.best_metric = meta["best_metric"]

    # -- loops -----------------------------------------------------------
    def train_epoch(self, epoch: int, start_step: int = 0) -> dict | None:
        """One epoch; ``start_step`` > 0 resumes mid-epoch after a
        preemption (skips the first batches of the epoch-seeded stream and
        replays the PRNG split chain, so the remaining steps are
        bit-identical to the uninterrupted run). Returns None when
        preempted mid-epoch (partial aggregates would be misleading)."""
        # epoch-derived PRNG stream (core.prng.KeySeq — the one blessed
        # threading idiom, jaxlint JX103): together with the epoch-seeded
        # data order this makes resume-at-epoch-N bit-identical to an
        # uninterrupted run reaching epoch N (dropout masks, GAN noise).
        # skip() replays the consumed chain positions (echo steps
        # consume data_echo draws per batch).
        keys = KeySeq(jax.random.fold_in(self._base_key, epoch))
        keys.skip(start_step * self.data_echo)
        t0 = time.perf_counter()
        counts: list[int] = []
        # device scalars not yet fetched, as (step_in_epoch, metrics):
        # the step index is what a sentinel trip hands the rollback
        pending: list[tuple[int, dict]] = []
        fetched: list[dict] = []  # host floats; each metric fetched ONCE

        def drain():
            # each float() below is a COMPLETED device step — beat per
            # fetch so a long epoch-end drain of the dispatch queue (or
            # a blocking save) cannot trip the watchdog, and a wedged
            # device is detected even while dispatches still enqueue
            if not pending:
                return
            with span("drain", cat="train"):
                for step_idx, m in pending:
                    host = {k: float(v) for k, v in m.items()}
                    fetched.append(host)
                    if self._watchdog:
                        self._watchdog.beat()
                    if self.sentinel is not None:
                        # EWMA z-score over loss + the in-graph sent_*
                        # scalars; raises SentinelTrip (a
                        # NumericDivergence) into the rollback loop
                        self.sentinel.observe(epoch, step_idx, host)
                pending.clear()

        def counted():
            for j, batch in enumerate(self.train_data(epoch)):
                if j < start_step:  # host-side skip keeps the data order
                    continue
                if self.injector is not None:
                    # chaos hooks (resilience/faults.py): consults land
                    # AFTER the resume skip, so a rollback never replays
                    # a consumed fault occurrence
                    batch, fired = self.injector.poison_nan(batch)
                    if fired:
                        print(f"[fault] NaN-poisoned epoch {epoch} "
                              f"batch {j}", flush=True)
                    self.injector.maybe_stall()
                counts.append(len(batch["image"]))
                yield batch

        # async H2D feed (data/prefetch.py): a producer thread shards +
        # device_puts `prefetch_depth` batches ahead so the wire
        # transfer overlaps the running step; the telemetry splits the
        # epoch wall time into host-wait / H2D-wait / step-compute.
        # close() in the finally stops the producer thread on EVERY exit
        # (preemption return, upstream exception), not just exhaustion.
        # span attribution (obs/trace.py): "epoch" is the wall-clock
        # window tools/trace_summary.py attributes; "step"/"fetch"/
        # "drain" (+ the producer thread's host_next/shard) are the
        # leaves inside it. All no-ops unless the tracer is enabled
        # (train.py --trace). NOTE on async backends (TPU): the "step"
        # span deliberately does NOT device_sync — a per-step block
        # would serialize the overlapped feed this loop exists for —
        # so it measures dispatch + queue backpressure (converging to
        # true step time once the dispatch queue fills), and the
        # residual compute drains into the "drain" spans; exact
        # per-step device time is --profile-steps' job.
        tel = FeedTelemetry()
        with span("epoch", cat="train", args={"epoch": int(epoch)}):
            feed = DevicePrefetcher(counted(), self.mesh,
                                    depth=self.prefetch_depth,
                                    telemetry=tel,
                                    fault_injector=self.injector,
                                    retry_policy=self.recovery,
                                    retry_counters=self.rec_counters)
            try:
                for i, device_batch in enumerate(feed):
                    if self.cluster is not None and self._cluster_poll(
                            epoch, start_step + i):
                        # degraded abandon: NO final drain — peers are
                        # gone and the pending collectives will never
                        # complete; the process exits 143 and the
                        # supervisor relaunches from the newest
                        # commonly-verified epoch
                        return None
                    if self._profiler:  # --profile-steps window (obs/);
                        # its own span: the start/stop XPlane dump costs
                        # seconds and must attribute as profiler time,
                        # not vanish from the epoch's span coverage
                        with span("profiler", cat="train"):
                            self._profiler.on_step(self._global_step)
                    self._global_step += 1
                    with span("step", cat="train"):
                        for _ in range(self.data_echo):  # batch reuse
                            try:
                                self.state, metrics = self._train_step(
                                    self.state, device_batch, next(keys)
                                )
                            except _checkify_error() as e:
                                if self.recovery is None:
                                    raise  # fail fast, exactly as before
                                # the tripwire fired: hand the position
                                # to the rollback loop in _fit (restore
                                # last-good checkpoint, skip past this
                                # batch window)
                                raise NumericDivergence(
                                    epoch, start_step + i, e) from e
                            pending.append((start_step + i, metrics))
                    run_step = self._run_step(epoch, start_step + i + 1)
                    if self.injector is not None:
                        # deterministic SDC drill sites (faults.py
                        # sdc_grad/sdc_param): keyed by RUN step, so a
                        # resumed or replayed window re-fires (or, in a
                        # quiesced replay, re-omits) identically
                        sdc = self.injector.check_sdc(run_step)
                        if sdc is not None:
                            from deepvision_tpu.resilience.sentinel import (
                                apply_sdc,
                            )

                            # deliberate one-shot host sync: chaos
                            # injection fires a bounded handful of
                            # times per drill, never steady-state
                            self.state = apply_sdc(  # jaxlint: disable=JX109
                                self.state, sdc)
                            print(f"[fault] {sdc.kind} corrupted local "
                                  f"state at run step {run_step}",
                                  flush=True)
                    # heartbeats land only in drain() (per COMPLETED
                    # step): a dispatch-side beat marks an ENQUEUED step,
                    # so a wedged device would keep "beating" until the
                    # dispatch queue blocked, stretching detection
                    # latency past the timeout. The watchdog forces its
                    # own drain cadence, bounded at 32 batches regardless
                    # of log_every (log_every=500 would otherwise starve
                    # beats and false-trip healthy runs). Cluster mode
                    # shifts every drain off i=0 and forces a fetch
                    # cadence of barrier_lead//2 (capped at 32): a
                    # host's own fetches block on every peer's
                    # dispatched collectives, so the cadence bounds
                    # cross-host dispatch skew strictly UNDER the
                    # barrier lead — the invariant that guarantees
                    # every host sees the stop marker before reaching
                    # it, for ANY lead >= 2.
                    cad = min(32, self.log_every or 32)
                    if self.cluster is not None:
                        ccad = max(1, min(
                            32, self.cluster.barrier_lead // 2))
                        if i % ccad == ccad - 1:
                            drain()
                    elif self._watchdog and i % cad == 0:
                        drain()
                    if self.sentinel is not None \
                            and self.cluster is not None \
                            and self.sentinel.audit_due(run_step):
                        # cross-host agreement audit: ONE bounded host
                        # sync every audit_every steps, on the drain
                        # cadence (a per-step fingerprint is exactly
                        # the JX109/JX116 stall class)
                        drain()
                        self._cluster_audit(epoch, run_step)
                    if self.sentinel is not None \
                            and self.sentinel.replay_until is not None \
                            and run_step >= self.sentinel.replay_until:
                        # replay-bisection mode: the window is re-run
                        # and audited; stop WITHOUT saving — the audit
                        # files are the verdict the supervisor reads
                        drain()
                        print(f"[sentinel] replay window complete at "
                              f"run step {run_step}", flush=True)
                        self.replay_done = True
                        return None
                    if (self.rss_limit_bytes
                            and i % (self.log_every or 32) == 0):
                        rss = _process_rss()
                        if rss > self.rss_limit_bytes:
                            print(
                                f"[rss-limit] host RSS {rss/1e9:.2f}GB > "
                                f"{self.rss_limit_bytes/1e9:.2f}GB — "
                                "self-preempting (mid-epoch save; "
                                "relaunch with --resume to continue in "
                                "a fresh process)",
                                flush=True,
                            )
                            self._rss_preempted = True
                            self.request_preempt()
                    if self.cluster is not None:
                        # coordinated stop: all hosts halt at the SAME
                        # dispatched count (the barrier marker), not at
                        # whatever batch the signal happened to land on
                        if self._cluster_maybe_save(
                                epoch, start_step + i + 1, drain):
                            return None
                    elif self._preempt:
                        # batch-granular: the resume point is a
                        # transferred-batch index, so a preemption
                        # mid-echo-group replays the group
                        drain()  # park the dispatch queue before saving
                        self._save_preempt(epoch, start_step + i + 1)
                        self.preempted = True
                        return None
                    if self.log_every and (
                            i % self.log_every == 0
                            if self.cluster is None
                            else (i + 1) % self.log_every == 0):
                        drain()  # syncs mostly-finished work; O(n) total
                        # true running mean over EVERY batch so far,
                        # matching the reference
                        # (ref: ResNet/pytorch/train.py:472-483)
                        running = np.mean([m["loss"] for m in fetched])
                        print(
                            f"[epoch {epoch} batch {i}] "
                            f"loss={fetched[-1]['loss']:.4f} "
                            f"running={running:.4f}",
                            flush=True,
                        )
            finally:
                feed.close()
            drain()  # drains the dispatch queue — MUST precede the
            # timing read
        dt = time.perf_counter() - t0
        # throughput counts optimizer-processed samples; with echoing
        # each transferred image is processed data_echo times
        n_images = sum(counts) * self.data_echo
        w = np.repeat(np.asarray(counts, np.float64), self.data_echo)
        # exact batch-size-weighted epoch aggregates
        agg = {
            k: float(np.average([m[k] for m in fetched], weights=w))
            for k in (fetched[0] if fetched else {})
        }
        n_chips = self.mesh.devices.size
        out = {
            f"train_{k}": v for k, v in agg.items()
        }  # loss + whatever the step emits (top1/top5, YOLO loss parts…)
        if self.data_echo > 1:  # make echoed throughput attributable
            out["data_echo"] = float(self.data_echo)
        # per-stage feed telemetry (input_host_wait_ms / input_h2d_wait_ms
        # / input_step_ms / input_wait_frac): attributes a throughput gap
        # to the host pipeline, the wire, or the step
        out.update(input_wait_metrics(tel.summary()))
        out.update(
            examples_per_sec=n_images / dt,
            images_per_sec_per_chip=n_images / dt / n_chips,
            lr_scale=self.plateau.scale if self.plateau else 1.0,
        )
        return out

    def validate(self) -> dict:
        def parts():
            for batch in self.val_data():
                out = self._eval_step(self.state,
                                      shard_batch(self.mesh, batch))
                if self._watchdog:
                    self._watchdog.beat()
                if self.cluster is not None:
                    self.cluster.beat(self._global_step, status="eval")
                yield out

        metrics, _ = aggregate_eval_parts(parts())
        return metrics

    def fit(self, epochs: int | None = None) -> Loggers:
        if self._watchdog:
            self._watchdog.start()
        try:
            return self._fit(epochs)
        finally:
            if self._watchdog:
                self._watchdog.stop()
            if self._profiler:  # close a still-open --profile-steps
                self._profiler.close()  # window (run ended inside A:B)
            # grep-stable summaries on EVERY exit path (the chaos gate
            # asserts on these lines; operators read them post-mortem)
            if self.injector is not None:
                print(f"[faults] fired: {self.injector.summary()}",
                      flush=True)
            if self.recovery is not None:
                print(f"[recovery] {self.rec_counters.format()}",
                      flush=True)

    def _rollback(self, nd: NumericDivergence) -> int:
        """Recover from a tripped NaN/Inf check: restore the newest
        VERIFIED checkpoint (quarantining corrupt ones — counted as
        ``ckpt_fallbacks``), fall back to the pristine initial state if
        none survives, optionally re-warm the LR, and return the step to
        resume the epoch from (skipping the offending batch window; the
        epoch-seeded data order + ``KeySeq.skip`` replay make the retry
        deterministic). Aborts with :class:`RecoveryError` after
        ``max_rollbacks`` consecutive rollbacks."""
        pol = self.recovery
        if self._consecutive_rollbacks >= pol.max_rollbacks:
            # budget check BEFORE incrementing: the abort message and
            # the [recovery] counter line must agree on how many
            # rollbacks actually executed
            raise RecoveryError(
                f"aborting after {self._consecutive_rollbacks} "
                f"consecutive rollbacks (max_rollbacks="
                f"{pol.max_rollbacks}): the divergence is persistent, "
                "not transient — inspect the data/LR before retrying"
            ) from nd
        self._consecutive_rollbacks += 1
        self.rec_counters.inc("rollbacks")
        if self.sentinel is not None:
            # the restored state jumps every watched series back;
            # re-warm the detector instead of re-tripping on the jump
            self.sentinel.reset()
        try:
            self.state, meta = self.ckpt.restore_verified(
                self.state, counters=self.rec_counters,
                fingerprint_fn=self._fingerprint_fn())
            source = f"epoch-{meta['epoch']} checkpoint"
        except FileNotFoundError:
            # commit the reset to the MESH (replicated), not the default
            # device: a bare device_put parks the whole state on device
            # 0, which the donated jit then rejects or silently reshards
            # every step on a multi-device mesh (JX125)
            from deepvision_tpu.core.mesh import replicated_sharding

            self.state = jax.device_put(
                self._init_state, replicated_sharding(self.mesh))
            source = "initial state (no verifiable checkpoint yet)"
        self._reshard_state()
        if pol.lr_rewarm is not None and hasattr(
                self.state.opt_state, "hyperparams"):
            scale = float(
                self.state.opt_state.hyperparams["lr_scale"]
            ) * pol.lr_rewarm
            self.state = self.state.replace(
                opt_state=set_lr_scale(self.state.opt_state, scale))
            if self.plateau is not None:
                self.plateau.scale = scale  # keep controller consistent
            self.rec_counters.inc("lr_rewarms")
        resume_step = nd.step_in_epoch + pol.skip_batches
        print(f"[rollback] {nd}: restored {source}; resuming epoch "
              f"{nd.epoch} at step {resume_step} "
              f"({self._consecutive_rollbacks}/{pol.max_rollbacks} "
              "consecutive)", flush=True)
        time.sleep(pol.backoff(self._consecutive_rollbacks - 1))
        return resume_step

    def _fit(self, epochs: int | None = None) -> Loggers:
        total = epochs or self.config.get("total_epochs", 1)
        if self.start_epoch == 0 and self.start_step == 0:
            with span("eval", cat="train"):
                # pre-train validation (ref: train.py:390)
                val = self.validate()
            if val:
                self.loggers.log_metrics(-1, val)
                print(f"[pre-train] {_fmt(val)}", flush=True)
        for epoch in range(self.start_epoch, total):
            start_step = (self.start_step
                          if epoch == self.start_epoch else 0)
            while True:
                try:
                    tr = self.train_epoch(epoch, start_step=start_step)
                except NumericDivergence as nd:
                    from deepvision_tpu.resilience.sentinel import (
                        SentinelTrip,
                    )

                    if self.cluster is not None \
                            and isinstance(nd, SentinelTrip):
                        # a sentinel trip is HOST-LOCAL (only the
                        # corrupted replica's metrics moved): a local
                        # rollback would desync this host's
                        # collectives from its peers. Publish the
                        # self-identified trip (attribution needs no
                        # bisection — the host caught its own state)
                        # and hand the generation to the supervisor.
                        # A checkify NaN is NOT diverted: it derives
                        # from the psum-shared gradients, so every
                        # host raises at the same step and the PR 4
                        # rollback below stays collective-consistent.
                        self.sdc_detected = True
                        self.cluster.write_trip(
                            nd.step_in_epoch, nd.key, nd.value, nd.z)
                        raise
                    if self.recovery is None:
                        raise  # sentinel trip without --recover:
                        # loud fail-fast, exactly the checkify contract
                    # tripwire -> rollback (resilience/): restore the
                    # last-good state and retry the epoch past the
                    # offending batch window; bounded by max_rollbacks
                    start_step = self._rollback(nd)
                    continue
                break
            self._consecutive_rollbacks = 0  # a completed epoch resets
            if tr is None:  # preempted mid-epoch; checkpoint already saved
                return self.loggers
            if self.recovery is not None:
                # cumulative self-healing counters ride the metric
                # history (and TB): the run must SAY what it survived
                tr.update(recovery_metrics(self.rec_counters))
            # per-epoch HBM accounting (obs/profiler.py): mem_* gauges
            # + logged metrics from device memory_stats(); {} on CPU
            # backends, so CPU runs log exactly what they always did
            mem = sample_memory_gauges()
            if mem:
                tr.update(mem)
            if start_step:
                # honest history: this epoch's train aggregates cover only
                # the post-resume tail of the epoch
                tr["train_from_step"] = float(start_step)
            with span("eval", cat="train"):
                val = self.validate()
            epoch_metrics = {**tr, **val}
            self.loggers.log_metrics(epoch, epoch_metrics)
            for k, v in tr.items():
                self.tb.scalar(k, v, epoch, "train")
            for k, v in val.items():
                self.tb.scalar(k, v, epoch, "val")
            self.tb.flush()
            print(f"[epoch {epoch}] {_fmt(epoch_metrics)}", flush=True)

            # plateau metric: accuracy when available, else negated loss
            # (the reference's detection trainers plateau on val loss,
            # ref: YOLO/tensorflow/train.py:56-68). On a mid-epoch-resumed
            # epoch WITHOUT validation the train loss covers only the
            # epoch tail — feeding it to the scheduler would diverge from
            # the uninterrupted run, so that epoch is skipped for
            # plateau/best tracking (val-based metrics are unaffected:
            # validation always runs on the full set).
            metric = val.get(
                "val_top1",
                -val["val_loss"] if "val_loss" in val
                else (-tr["train_loss"] if not start_step else None),
            )
            if metric is not None:
                if self.plateau is not None:
                    scale = self.plateau.update(metric)
                    if scale != float(
                        self.state.opt_state.hyperparams["lr_scale"]
                    ):
                        self.state = self.state.replace(
                            opt_state=set_lr_scale(self.state.opt_state,
                                                   scale)
                        )
                self.best_metric = max(self.best_metric, metric)
            with span("checkpoint", cat="train"):
                self.ckpt.save(
                    epoch,
                    self.state,
                    loggers=self.loggers,
                    extra={"plateau": self.plateau.state_dict()}
                    if self.plateau else {},
                    best_metric=self.best_metric,
                    # metric-less partial epoch: rank at the current best
                    # so keep_best retention neither drops nor promotes it
                    metrics={"plateau_metric": float(
                        metric if metric is not None
                        else self.best_metric)},
                    # audited checkpoint (resilience/sentinel.py): the
                    # save-time state fingerprint rides the integrity
                    # manifest, so a verified restore can catch
                    # corruption that PREDATES serialization
                    state_fingerprint=(
                        self.sentinel.fingerprint_state(self.state)
                        if self.sentinel is not None else None),
                )
            # the epoch checkpoint supersedes any earlier preemption save —
            # but only once it is DURABLE: an async save has merely been
            # staged when save() returns, and deleting the preemption
            # checkpoint before the commit would leave a kill window with
            # no recent checkpoint at all. (The wait only triggers on the
            # first epoch after a preemption resume.) The clear runs under
            # the PreemptLock so it can never rmtree another process's
            # in-flight save; on timeout the stale dir is simply left
            # (resume ignores preemption saves older than an epoch save).
            if self._preempt_dir.exists():
                self.ckpt.wait_until_finished()
                if self.cluster is not None:
                    # single-writer clear, no lock: every host is past
                    # the collective epoch save, so nobody reads the
                    # preemption directory anymore
                    if self.cluster.host == 0:
                        self._clear_preempt_ckpt()
                elif self._plock.acquire(timeout=60.0):
                    try:
                        self._clear_preempt_ckpt()
                    finally:
                        self._plock.release()
            if self.cluster is not None:
                self.cluster.beat(self._global_step, epoch,
                                  status="boundary", force=True)
                mark = self.cluster.read_barrier()
                if self._preempt and mark is None:
                    # the notice landed outside the step loop
                    # (validate/save): publish an exit-after-epoch
                    # marker so peers stop at THIS boundary too
                    mark = self.cluster.write_after_epoch(epoch)
                if mark is not None \
                        and mark.get("after_epoch") == epoch:
                    self._preempt = True
            if self._preempt:  # signal arrived during validate/save: the
                self.preempted = True  # epoch is fully committed — stop
                self.ckpt.wait_until_finished()
                print(f"[preempted] after completed epoch {epoch}",
                      flush=True)
                return self.loggers
        if self.sentinel is not None and self.cluster is not None:
            # bounded end-of-run audit sweep: a divergence published at
            # the final audit step must not slip out with exit 0
            div = self.cluster.final_audit_check(
                timeout_s=self.cluster.barrier_timeout_s)
            if div is not None:
                self._raise_divergence(div)
        self.ckpt.wait_until_finished()  # commit any in-flight async save
        return self.loggers


class StallWatchdog:
    """Failure DETECTION for silent device hangs (SURVEY §5.3 — the
    reference has none; its failure story is reading nohup logs).

    A wedged runtime RPC blocks the step loop in a C call: no exception,
    no log line, signal handlers can't run — the observed failure mode
    on the relay-attached chip (EVIDENCE.md r4 YOLO gate). A daemon
    thread watches a heartbeat the step loop touches after every step;
    if none lands within ``timeout_s`` it prints a loud diagnosis, and
    with ``abort=True`` exits the process with code 75 (EX_TEMPFAIL) so
    a supervisor can restart into the bit-exact ``--resume`` path —
    detection + recovery instead of a hang nobody notices.
    """

    def __init__(self, timeout_s: float, *, abort: bool = False,
                 _exit=os._exit):
        if timeout_s <= 0:
            raise ValueError(f"stall timeout must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.abort = abort
        self._exit = _exit  # injectable for tests
        # ARMED ONLY AFTER THE FIRST BEAT: the first step call blocks on
        # XLA compilation for minutes legitimately; a pre-armed watchdog
        # would abort healthy cold starts into a supervisor restart loop.
        # (Tradeoff: a wedge before any step ever completes goes
        # undetected — acceptable, the operator sees a run that never
        # logged a batch.)
        self._last: float | None = None
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        """Idempotent while running; re-entrant after stop() — fit() may
        be called repeatedly on one Trainer."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._last = None
        self._stop = threading.Event()
        # fresh fired-state per run: a stale fired=True from a previous
        # non-abort stall would mislabel every later healthy fit()
        self._fired = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        self._last = time.monotonic()

    def stop(self):
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def _run(self):
        poll = min(self.timeout_s / 4.0, 5.0)
        while not self._stop.wait(poll):
            if self._last is None:
                continue  # not armed until the first step lands
            stalled = time.monotonic() - self._last
            if stalled > self.timeout_s:
                self._fired.set()
                print(
                    f"[stall] no heartbeat in {stalled:.0f}s "
                    f"(timeout {self.timeout_s:.0f}s) — likely a wedged "
                    "device/runtime RPC; the process "
                    + ("will exit 75 for a supervised restart + --resume"
                       if self.abort else
                       "is left running (use --stall-abort to exit 75)"),
                    flush=True,
                )
                if self.abort:
                    self._exit(75)
                self._last = time.monotonic()  # warn again, don't spam


def _process_rss(*, honor_fake: bool = True) -> int:
    """Current process resident set size in bytes — one ``/proc`` read,
    no third-party dependency (psutil is not in requirements.txt).
    Returns 0 where /proc is unavailable (the limit check then never
    fires, which degrades to "no RSS watchdog" rather than a crash).

    ``DVTPU_FAKE_RSS`` (bytes) is a test hook for the in-loop check —
    the ctor-time sanity guard ignores it (``honor_fake=False``) so a
    faked huge RSS cannot make construction itself fail."""
    fake = os.environ.get("DVTPU_FAKE_RSS")
    if honor_fake and fake:
        try:
            return int(fake)
        except ValueError:
            pass  # malformed hook value: fall through to the real RSS
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def _check_rss_limit_sane(limit_bytes: int) -> None:
    """A limit at/below the process's CURRENT RSS would fire on batch 0
    of every relaunch: each restart pays full XLA recompilation to
    advance one batch — the run looks alive but effectively stalls.
    Fail at construction instead, with the number the operator needs."""
    now = _process_rss(honor_fake=False)
    if now and limit_bytes <= now:
        raise ValueError(
            f"rss limit {limit_bytes/1e9:.2f}GB is at/below the current "
            f"process RSS {now/1e9:.2f}GB — every relaunch would "
            "immediately re-preempt after one batch; raise the limit "
            "above the steady-state baseline")


def make_rss_limit_flag(limit_gb: float) -> Callable[[], bool]:
    """Zero-arg RSS-limit poll for loops that take a ``preempt``
    callable instead of a Trainer (``fit_gan``): returns True — and
    stays True — once host RSS crosses ``limit_gb``. LATCHED like
    make_preempt_flag, and for the same reason: the caller re-polls
    after the loop to decide the exit-143 path, and RSS may have
    dropped back under the limit by then (epoch buffers freed) — an
    unlatched flag would let a preempted run masquerade as complete.
    Same relaunch-storm guard at creation as the Trainer ctor."""
    limit = int(limit_gb * 1e9)
    _check_rss_limit_sane(limit)
    fired = {"rss": False}

    def exceeded() -> bool:
        if fired["rss"]:
            return True
        rss = _process_rss()
        if rss > limit:
            fired["rss"] = True
            print(
                f"[rss-limit] host RSS {rss/1e9:.2f}GB > "
                f"{limit/1e9:.2f}GB — stopping for a supervised "
                "relaunch (--resume)",
                flush=True,
            )
            return True
        return False

    return exceeded


def make_preempt_flag(signals=(signal.SIGTERM,)) -> Callable[[], bool]:
    """Install handlers for ``signals`` and return a zero-arg callable
    reporting whether one arrived — the preemption hook for loops that
    are functions rather than Trainer instances (``fit_gan``)."""
    fired = {"stop": False}

    def handler(signum=None, frame=None):
        fired["stop"] = True

    for s in signals:
        signal.signal(s, handler)
    return lambda: fired["stop"]


def _fmt(d: dict) -> str:
    return " ".join(f"{k}={v:.4g}" for k, v in d.items())
