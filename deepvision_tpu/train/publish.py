"""Best-model publication to GCS (ref: Hourglass/tensorflow/main.py:50-65).

After training, uploads the best checkpoint archive to a bucket and writes
the ``gs://`` URI to ``/tmp/output.txt`` — the reference's pipeline
handoff contract. Gated on google-cloud-storage being importable (it is
not in the baked image; the Dockerfile installs it for cloud runs).
"""

from __future__ import annotations

import os
import tarfile
import tempfile
from pathlib import Path


def publish_to_gcs(
    model_path: str | Path,
    bucket_name: str,
    output_dir: str,
    *,
    handoff_file: str = "/tmp/output.txt",
) -> str | None:
    """Upload ``model_path`` (file OR checkpoint directory, tarred) to
    ``gs://bucket/output_dir/``; returns the gs:// URI (None if the GCS
    client library is unavailable)."""
    try:
        from google.cloud import storage  # optional dependency
    except ImportError:
        print("google-cloud-storage not installed; skipping upload")
        return None

    model_path = Path(model_path)
    tmpdir = None
    upload_path = model_path
    if model_path.is_dir():  # Orbax checkpoints are directories
        tmpdir = tempfile.TemporaryDirectory()
        upload_path = Path(tmpdir.name) / f"{model_path.name}.tar.gz"
        with tarfile.open(upload_path, "w:gz") as tar:
            tar.add(model_path, arcname=model_path.name)

    client = storage.Client()
    bucket = client.bucket(bucket_name)
    blob_name = os.path.join(output_dir, upload_path.name)
    bucket.blob(blob_name).upload_from_filename(str(upload_path))
    uri = f"gs://{bucket_name}/{blob_name}"
    if tmpdir is not None:
        tmpdir.cleanup()
    print(f"Uploaded model to {uri}")
    # pipeline handoff (ref: main.py:63-65)
    Path(handoff_file).write_text(uri + "\n")
    return uri
