from deepvision_tpu.train.state import TrainState, create_train_state

__all__ = ["TrainState", "create_train_state"]
