"""Per-model training configs mirroring the reference's ``training_config``
(ref: ResNet/pytorch/train.py:26-215; LeNet/pytorch/train.py). The PyTorch
configs are the accuracy-bearing ones (SURVEY §7 "hard parts" #7) and are
treated as canonical; paper-quote comments preserved in spirit via the ref
citations above each entry.

``input_size`` is the train-time crop; ``image_key`` datasets are wired by
the CLI (train.py at the repo root).
"""

from __future__ import annotations

TRAINING_CONFIG: dict[str, dict] = {
    # ref: LeNet/pytorch/train.py:18-30 — batch 64, Adam 1e-3, plateau, 50ep
    "lenet5": {
        "precision": "f32",
        "batch_size": 64,
        "input_size": 32,
        "channels": 1,
        "num_classes": 10,
        "dataset": "mnist",
        "optimizer": "adam",
        "optimizer_params": {"lr": 1e-3},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 50,
    },
    # ref: ResNet/pytorch/train.py:27-51 (SGD 0.01/0.9/5e-4, plateau max)
    "alexnet1": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 128,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.01, "momentum": 0.9,
                             "weight_decay": 5e-4},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 200,
    },
    # ref: train.py:52-73
    "alexnet2": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 128,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.01, "momentum": 0.9,
                             "weight_decay": 5e-4},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 200,
    },
    # ref: train.py:74-100 (StepLR 10/0.5)
    "vgg16": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 128,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.01, "momentum": 0.9,
                             "weight_decay": 5e-4},
        "scheduler": "step",
        "scheduler_params": {"step_size": 10, "gamma": 0.5},
        "total_epochs": 200,
    },
    # ref: train.py:101-117
    "vgg19": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 64,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.01, "momentum": 0.9,
                             "weight_decay": 5e-4},
        "scheduler": "step",
        "scheduler_params": {"step_size": 10, "gamma": 0.5},
        "total_epochs": 200,
    },
    # ref: train.py:118-136 (poly decay lambda)
    "inception1": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 128,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.01, "momentum": 0.9,
                             "weight_decay": 2e-4},
        "scheduler": "inception_poly",
        "total_epochs": 200,
    },
    # ref: train.py:137-163 (SGD 0.1/0.9/1e-4, plateau max, batch 256)
    "resnet34": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 256,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.1, "momentum": 0.9,
                             "weight_decay": 1e-4},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 200,
        # MXU-friendly space-to-depth 7x7/2 stem: identical parameter
        # pytree + numerics (models/resnet._Conv7S2D), +2.6% measured
        # img/s on v5e; needs even H/W (all ResNet inputs are 224)
        "model_kwargs": {"s2d_stem": True},
    },
    # ref: train.py:164-180 — the north-star accuracy config (73.93% top-1)
    "resnet50": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 256,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.1, "momentum": 0.9,
                             "weight_decay": 1e-4},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 200,
        # MXU-friendly space-to-depth 7x7/2 stem: identical parameter
        # pytree + numerics (models/resnet._Conv7S2D), +2.6% measured
        # img/s on v5e; needs even H/W (all ResNet inputs are 224)
        "model_kwargs": {"s2d_stem": True},
    },
    "resnet152": {
        "precision": "bf16",
        # block-boundary remat (models/resnet.ResNet.remat, registry
        # default): trade recompute for the 36-deep stage-3 activation
        # surface — the ISSUE 15 HBM diet for the deepest classifier
        "remat": "block",
        "augment": "pt",
        "batch_size": 256,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.1, "momentum": 0.9,
                             "weight_decay": 1e-4},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 200,
        # MXU-friendly space-to-depth 7x7/2 stem: identical parameter
        # pytree + numerics (models/resnet._Conv7S2D), +2.6% measured
        # img/s on v5e; needs even H/W (all ResNet inputs are 224)
        "model_kwargs": {"s2d_stem": True},
    },
    "resnet50v2": {
        "precision": "bf16",
        "batch_size": 256,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.1, "momentum": 0.9,
                             "weight_decay": 1e-4},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max"},
        "total_epochs": 200,
    },
    # ref: train.py:181-214 (RMSprop 0.045/alpha .9/eps 1.0, StepLR 2/0.94)
    "mobilenet1": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 128,
        "input_size": 224,
        "optimizer": "rmsprop",
        "optimizer_params": {"lr": 0.045, "alpha": 0.9, "eps": 1.0},
        "scheduler": "step",
        "scheduler_params": {"step_size": 2, "gamma": 0.94},
        "total_epochs": 200,
    },
    # reference WIP — config completed per the ShuffleNet paper (linear decay)
    "shufflenet1": {
        "precision": "bf16",
        "augment": "pt",
        "batch_size": 256,
        "input_size": 224,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.1, "momentum": 0.9,
                             "weight_decay": 4e-5},
        "scheduler": "step",
        "scheduler_params": {"step_size": 30, "gamma": 0.1},
        "total_epochs": 120,
    },
    # reference stub — config per Inception V3 paper
    "inception3": {
        "precision": "bf16",
        "batch_size": 128,
        "input_size": 299,
        "optimizer": "rmsprop",
        "optimizer_params": {"lr": 0.045, "alpha": 0.9, "eps": 1.0},
        "scheduler": "step",
        "scheduler_params": {"step_size": 2, "gamma": 0.94},
        "total_epochs": 200,
    },
    # Darknet-53 ImageNet pretraining for the YOLO backbone (paper config;
    # the reference trains detection from scratch and has no pretrain path)
    "darknet53": {
        "precision": "bf16",
        "batch_size": 128,
        "input_size": 256,
        "optimizer": "sgd",
        "optimizer_params": {"lr": 0.1, "momentum": 0.9,
                             "weight_decay": 5e-4},
        "scheduler": "step",
        "scheduler_params": {"step_size": 30, "gamma": 0.1},
        "total_epochs": 120,
    },
    # ref: YOLO/tensorflow/train.py:13-29 — per-replica batch 16, Adam 0.01,
    # /10 plateau on val loss (simulated ReduceLROnPlateau :56-68), 300 ep
    "yolov3": {
        "precision": "bf16",
        "batch_size": 16,
        "input_size": 416,
        "num_classes": 20,  # VOC; 80 for COCO (ref: train.py:14)
        "dataset": "detection",
        "optimizer": "adam",
        "optimizer_params": {"lr": 0.01},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max", "patience": 10},
        "total_epochs": 300,
    },
    # ref: DCGAN/tensorflow/main.py:13-17,31-32 — batch 256, two Adams
    # 1e-4, 50 epochs, noise dim 100, checkpoint every 2 epochs keep 3
    "dcgan": {
        "precision": "bf16",
        "batch_size": 256,
        "input_size": 28,
        "channels": 1,
        "dataset": "gan_mnist",
        "noise_dim": 100,
        "optimizer": "adam",
        "optimizer_params": {"lr": 1e-4},
        "save_every": 2,
        "total_epochs": 50,
    },
    # ref: CycleGAN/tensorflow/train.py:14-21,122-127 — batch 4 (CLI
    # default), two Adams 2e-4 β1 0.5, LinearDecay to 0 over epochs
    # 100..200, pool 50, λ_cycle 10, λ_id 5
    "cyclegan": {
        "precision": "bf16",
        "batch_size": 4,
        "input_size": 256,
        "dataset": "gan_unpaired",
        "optimizer": "adam",
        "optimizer_params": {"lr": 2e-4, "beta1": 0.5},
        "decay_epochs": 100,
        "save_every": 1,
        "total_epochs": 200,
    },
    # ref: ObjectsAsPoints/tensorflow/train.py:24-57,205-216 — Adam,
    # per-replica batch 16, /10 plateau after 10 stale epochs. The ref's
    # 0.01 default was never trained (loss list empty, run commented out);
    # we deliberately use 1e-3: 0.01 destabilizes penalty-reduced focal
    # loss (the paper itself trains hourglass CenterNet at 2.5e-4).
    "centernet": {
        "precision": "bf16",
        "batch_size": 16,
        "input_size": 256,
        "num_classes": 80,  # MSCOCO (ref model.py:131)
        "dataset": "detection",
        "steps": "centernet",
        "optimizer": "adam",
        "optimizer_params": {"lr": 1e-3},
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max", "patience": 10},
        "total_epochs": 100,
    },
    # ref: Hourglass/tensorflow/train.py:30-44,229-240 — Adam 1e-4 (the
    # paper quote says "rmsprop 2.5e-4" but the code uses Adam), batch 16,
    # /10 plateau on val loss after max_patience=10 stale epochs (:46-58)
    "hourglass104": {
        "batch_size": 16,
        "input_size": 256,
        "num_heatmaps": 16,
        "dataset": "pose",
        "optimizer": "adam",
        "optimizer_params": {"lr": 1e-4},
        # r4 measured plain bf16 crippling this net (synthetic gate
        # loss 74 vs 5.1 at 30 epochs — bf16 rounding compounding
        # through the recursion). ISSUE 15 addressed the mechanism
        # structurally: the residual/cross-stack carrier now accumulates
        # in f32 (models/hourglass.py) with only block internals in
        # bf16, plus dynamic loss scaling as the wide-range heatmap
        # regression's guard — the bf16-vs-f32 twin gate
        # (tests/test_precision.py) pins the trajectory agreement.
        "precision": "bf16_scaled",
        # per-stack remat (models/hourglass.StackedHourglass.remat,
        # registry default): the order-4 recursion x 4 stacks is the
        # deepest activation surface in the zoo
        "remat": "stack",
        # mode "max" on the Trainer's negated val loss (the yolov3
        # convention): lower loss -> higher metric -> improvement
        "scheduler": "plateau",
        "scheduler_params": {"factor": 0.1, "mode": "max", "patience": 10},
        "total_epochs": 100,
    },
}


def get_config(name: str) -> dict:
    # "<model>_ref" = reference-exact architecture variant (converter
    # parity, e.g. inception1_ref = BN-free BasicConv blocks); trains and
    # evaluates with the base model's config
    base = name
    if name.endswith("_ref") and name[:-4] in TRAINING_CONFIG:
        base = name[:-4]
    # deep copy: callers override nested entries (train.py writes
    # optimizer_params["lr"] from --lr), and a shallow dict() would let
    # those writes contaminate the global table across in-process runs
    import copy

    cfg = copy.deepcopy(TRAINING_CONFIG[base])
    cfg.setdefault("input_size", 224)
    cfg.setdefault("channels", 3)
    cfg.setdefault("num_classes", 1000)
    cfg.setdefault("dataset", "imagenet")
    # numerics policy (ISSUE 15): every shipped entry declares
    # "precision" explicitly (the table is the single source of truth —
    # CLI --precision overrides, nothing else does); the setdefault
    # only covers ad-hoc test configs built outside the table
    cfg.setdefault("precision", "bf16")
    # remat: config declaration wins; else the registry-declared
    # per-model policy (models/registry.model_remat). Folded into
    # model_kwargs so every builder that constructs the model from this
    # config (train.py, evalcheck, ircheck, bench) compiles the policy.
    if "remat" not in cfg:
        # the package import (not bare registry) guarantees the
        # registration side effects ran before the lookup
        import deepvision_tpu.models  # noqa: F401
        from deepvision_tpu.models.registry import model_remat

        cfg["remat"] = model_remat(base)
    if cfg["remat"] is not None:
        mk = cfg.setdefault("model_kwargs", {})
        mk.setdefault("remat", cfg["remat"])
    cfg["name"] = name
    return cfg
