"""LR schedules matching the reference's three mechanisms.

- StepLR (VGG step_size=10 gamma=0.5; MobileNet 2/0.94 —
  ref: ResNet/pytorch/train.py:95-99,205-209)
- LambdaLR polynomial-then-floor for Inception (ref: train.py:128-135)
- ReduceLROnPlateau on val top-1 (AlexNet/ResNet — ref: train.py:45-49,
  applied at train.py:412-415): inherently host-side control flow, so it is
  a host ``PlateauController`` driving an ``optax.inject_hyperparams`` LR —
  the jitted step never sees Python control flow.
- LinearDecay for CycleGAN (constant, then linear to 0 —
  ref: CycleGAN/tensorflow/utils.py:5-28).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import optax


def step_decay(base_lr: float, steps_per_epoch: int, step_size_epochs: int,
               gamma: float) -> optax.Schedule:
    def schedule(count):
        epoch = count // steps_per_epoch
        return base_lr * gamma ** (epoch // step_size_epochs)
    return schedule


def inception_poly(base_lr: float, steps_per_epoch: int) -> optax.Schedule:
    """(1 - e/60)^0.5 for e<60, then 1e-2, then 1e-3 of base —
    ref: ResNet/pytorch/train.py:132-134."""
    def schedule(count):
        epoch = count // steps_per_epoch
        frac = jnp.sqrt(jnp.maximum(1.0 - epoch / 60.0, 0.0))
        scale = jnp.where(epoch < 60, frac, jnp.where(epoch < 75, 0.01, 0.001))
        return base_lr * scale
    return schedule


def linear_decay(base_lr: float, total_steps: int, decay_start: int) -> optax.Schedule:
    """Constant until ``decay_start``, then linear to 0 at ``total_steps``."""
    def schedule(count):
        frac = jnp.clip(
            (count - decay_start) / jnp.maximum(total_steps - decay_start, 1),
            0.0, 1.0,
        )
        return base_lr * (1.0 - frac)
    return schedule


@dataclasses.dataclass
class PlateauController:
    """torch ReduceLROnPlateau semantics (mode/factor/patience/threshold).

    ``update(metric)`` returns the new LR scale in (0, 1]; the Trainer writes
    it into the optimizer's injected hyperparams.
    """

    mode: str = "max"
    factor: float = 0.1
    patience: int = 10
    threshold: float = 1e-4
    min_scale: float = 1e-8

    scale: float = 1.0
    best: float | None = None
    bad_epochs: int = 0

    def update(self, metric: float) -> float:
        if self.best is None:
            self.best = metric
            return self.scale
        if self.mode == "max":
            improved = metric > self.best * (1 + self.threshold)
        else:
            improved = metric < self.best * (1 - self.threshold)
        if improved:
            self.best = metric
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
            if self.bad_epochs > self.patience:
                self.scale = max(self.scale * self.factor, self.min_scale)
                self.bad_epochs = 0
        return self.scale

    def state_dict(self) -> dict:
        return {"scale": self.scale, "best": self.best,
                "bad_epochs": self.bad_epochs}

    def load_state_dict(self, d: dict) -> None:
        self.scale = d["scale"]
        self.best = d["best"]
        self.bad_epochs = d["bad_epochs"]
