"""Metric loggers: the reference's ``loggers`` dict pattern + TensorBoard.

``{metric: {"epochs": [...], "value": [...]}}`` — built at
ref: ResNet/pytorch/train.py:260-279, appended via ``log_metrics`` (:282-286),
persisted inside the checkpoint (:427) and re-plotted by notebooks. Kept
JSON-serializable here so it rides along with the Orbax checkpoint and the
notebook-replacement plotting scripts can read it directly.

TensorBoard: split train/val writers with per-epoch scalars, matching the
TF2 reference (ref: YOLO/tensorflow/train.py:196-199,224-241), via
``tf.summary`` when TensorFlow is importable; silently disabled otherwise.
"""

from __future__ import annotations

import json
from pathlib import Path

# telemetry summary keys (data/prefetch.FeedTelemetry.summary) promoted
# to logged per-epoch metrics; host_wait is producer-side (upstream
# iterator), shard is the producer's host-staging + device_put dispatch
# (the wire-facing stage), h2d_wait is consumer-side (blocked on a
# ready device batch), step is the consumer's between-batch time, and
# the frac is wait/(wait+step) — >0.5 means the run is input-bound,
# not chip-bound.
_INPUT_WAIT_KEYS = ("host_wait_ms", "shard_ms", "h2d_wait_ms",
                    "step_ms", "input_wait_frac",
                    "h2d_bytes_per_image")


def input_wait_metrics(summary: dict, prefix: str = "input_") -> dict:
    """Flatten a ``FeedTelemetry.summary()`` into loggable scalar
    metrics (``input_host_wait_ms`` …) for ``Loggers``/TensorBoard —
    the one place the per-stage feed telemetry gets its metric names,
    shared by the Trainer epoch loop, the GAN loop, and ``bench.py``."""
    return {
        # "input_wait_frac" already carries the prefix in its name
        (k if k.startswith(prefix) else prefix + k): float(summary[k])
        for k in _INPUT_WAIT_KEYS if k in summary
    }


def recovery_metrics(counters, prefix: str = "recovery_") -> dict:
    """Flatten a ``resilience.RecoveryCounters`` (or a plain snapshot
    dict) into loggable scalar metrics (``recovery_rollbacks`` …) —
    the recovery analog of :func:`input_wait_metrics`: cumulative
    counts logged per epoch, so the metric history says WHEN a run
    rolled back / fell back / retried, not just that it did."""
    snap = counters.snapshot() if hasattr(counters, "snapshot") \
        else dict(counters)
    return {prefix + k: float(v) for k, v in snap.items()}


class Loggers:
    def __init__(self, metrics: list[str] | None = None):
        self.data: dict[str, dict[str, list]] = {}
        for m in metrics or []:
            self._ensure(m)

    def _ensure(self, name: str):
        self.data.setdefault(name, {"epochs": [], "value": []})

    def log_metrics(self, epoch: int, metrics: dict[str, float]) -> None:
        for name, value in metrics.items():
            self._ensure(name)
            self.data[name]["epochs"].append(int(epoch))
            self.data[name]["value"].append(float(value))

    def latest(self, name: str):
        vals = self.data.get(name, {}).get("value", [])
        return vals[-1] if vals else None

    def to_json(self) -> str:
        return json.dumps(self.data)

    @classmethod
    def from_json(cls, s: str) -> "Loggers":
        out = cls()
        out.data = json.loads(s)
        return out


class TensorBoardWriter:
    """Thin tf.summary wrapper; no-op if TF is unavailable."""

    def __init__(self, logdir: str | Path, enabled: bool = True):
        self._writers = {}
        self._logdir = Path(logdir)
        self._tf = None
        if enabled:
            try:
                import tensorflow as tf

                self._tf = tf
            except ImportError:
                pass

    def scalar(self, tag: str, value: float, step: int, split: str = "train"):
        if self._tf is None:
            return
        if split not in self._writers:
            self._writers[split] = self._tf.summary.create_file_writer(
                str(self._logdir / split)
            )
        with self._writers[split].as_default():
            self._tf.summary.scalar(tag, value, step=step)

    def flush(self):
        for w in self._writers.values():
            w.flush()
