"""Metric loggers: the reference's ``loggers`` dict pattern + TensorBoard.

``{metric: {"epochs": [...], "value": [...]}}`` — built at
ref: ResNet/pytorch/train.py:260-279, appended via ``log_metrics`` (:282-286),
persisted inside the checkpoint (:427) and re-plotted by notebooks. Kept
JSON-serializable here so it rides along with the Orbax checkpoint and the
notebook-replacement plotting scripts can read it directly.

TensorBoard: split train/val writers with per-epoch scalars, matching the
TF2 reference (ref: YOLO/tensorflow/train.py:196-199,224-241), via
``tf.summary`` when TensorFlow is importable; silently disabled otherwise.
"""

from __future__ import annotations

import json
from pathlib import Path


class Loggers:
    def __init__(self, metrics: list[str] | None = None):
        self.data: dict[str, dict[str, list]] = {}
        for m in metrics or []:
            self._ensure(m)

    def _ensure(self, name: str):
        self.data.setdefault(name, {"epochs": [], "value": []})

    def log_metrics(self, epoch: int, metrics: dict[str, float]) -> None:
        for name, value in metrics.items():
            self._ensure(name)
            self.data[name]["epochs"].append(int(epoch))
            self.data[name]["value"].append(float(value))

    def latest(self, name: str):
        vals = self.data.get(name, {}).get("value", [])
        return vals[-1] if vals else None

    def to_json(self) -> str:
        return json.dumps(self.data)

    @classmethod
    def from_json(cls, s: str) -> "Loggers":
        out = cls()
        out.data = json.loads(s)
        return out


class TensorBoardWriter:
    """Thin tf.summary wrapper; no-op if TF is unavailable."""

    def __init__(self, logdir: str | Path, enabled: bool = True):
        self._writers = {}
        self._logdir = Path(logdir)
        self._tf = None
        if enabled:
            try:
                import tensorflow as tf

                self._tf = tf
            except ImportError:
                pass

    def scalar(self, tag: str, value: float, step: int, split: str = "train"):
        if self._tf is None:
            return
        if split not in self._writers:
            self._writers[split] = self._tf.summary.create_file_writer(
                str(self._logdir / split)
            )
        with self._writers[split].as_default():
            self._tf.summary.scalar(tag, value, step=step)

    def flush(self):
        for w in self._writers.values():
            w.flush()
