"""Canonical pure step functions for classification models.

The reference repeats this logic in every train.py (forward → CE → backward →
step → metrics; ref: ResNet/pytorch/train.py:438-485 and validate :488-520).
Here it is written once, as pure functions suitable for
``core.step.compile_train_step``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from deepvision_tpu.losses.classification import (
    softmax_cross_entropy,
    topk_accuracy,
)
from deepvision_tpu.train.state import TrainState


def classification_train_step(
    state: TrainState, batch: dict, key: jax.Array
) -> tuple[TrainState, dict]:
    """One SGD step on {'image','label'}; returns (new_state, metrics)."""
    images, labels = batch["image"], batch["label"]

    def loss_fn(params):
        out, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": key},
        )
        # Inception-style aux heads return (main, aux...) tuples; weight the
        # aux losses 0.3 as the paper/reference do
        # (ref: Inception/pytorch/train.py aux handling, models/inception_v1.py:92-113).
        if isinstance(out, (tuple, list)):
            main, *aux = out
            loss = softmax_cross_entropy(main, labels)
            for a in aux:
                loss = loss + 0.3 * softmax_cross_entropy(a, labels)
            logits = main
        else:
            logits = out
            loss = softmax_cross_entropy(logits, labels)
        return loss, (logits, mutated.get("batch_stats", state.batch_stats))

    (loss, (logits, new_bs)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    new_state = state.apply_gradients(grads, batch_stats=new_bs)
    metrics = {"loss": loss, **topk_accuracy(logits, labels)}
    return new_state, metrics


def classification_eval_step(state: TrainState, batch: dict) -> dict:
    images, labels = batch["image"], batch["label"]
    variables: dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    logits = state.apply_fn(variables, images, train=False)
    if isinstance(logits, (tuple, list)):
        logits = logits[0]
    loss = softmax_cross_entropy(logits, labels)
    n = jnp.asarray(labels.shape[0], jnp.float32)
    acc = topk_accuracy(logits, labels)
    # Return sums so the host can aggregate exactly over a full epoch
    # (the reference accumulates counts the same way,
    # ref: ResNet/pytorch/train.py:488-520).
    return {
        "loss_sum": loss * n,
        "count": n,
        **{k: v * n for k, v in acc.items()},
    }
