"""Canonical pure step functions for classification models.

The reference repeats this logic in every train.py (forward → CE → backward →
step → metrics; ref: ResNet/pytorch/train.py:438-485 and validate :488-520).
Here it is written once, as pure functions suitable for
``core.step.compile_train_step``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from deepvision_tpu.core.precision import precision_metrics
from deepvision_tpu.ops.normalize import maybe_normalize
from deepvision_tpu.losses.classification import (
    softmax_cross_entropy,
    softmax_cross_entropy_per_sample,
    topk_accuracy,
    topk_correct,
)
from deepvision_tpu.train.state import TrainState


def classification_train_step(
    state: TrainState, batch: dict, key: jax.Array,
    normalize_kind: str = "imagenet",
) -> tuple[TrainState, dict]:
    """One SGD step on {'image','label'}; returns (new_state, metrics).

    ``normalize_kind`` must match the host pipeline's uint8 wire contract:
    "imagenet" (TF-lineage mean subtraction) or "torch" (PT-lineage
    mean/std — configs with ``augment: "pt"``); bind it with
    ``functools.partial`` before compiling.

    Mixup (``data/device_aug.py``, device-side): when the in-step
    augmentation mixed the images it adds ``label_b`` (the partner
    permutation's labels) and ``lam`` to the batch, and the loss becomes
    the standard convex pair ``lam*CE(y) + (1-lam)*CE(y_b)`` (Zhang et
    al. 2018); top-k accuracy stays against the primary labels. The
    keys are present-or-absent per CONFIG (never per batch), so there is
    no retrace churn."""
    images = maybe_normalize(batch["image"], normalize_kind)
    labels = batch["label"]
    labels_b, lam = batch.get("label_b"), batch.get("lam")

    def mixed_ce(logits):
        loss = softmax_cross_entropy(logits, labels)
        if labels_b is None:
            return loss
        return lam * loss + (1.0 - lam) * softmax_cross_entropy(
            logits, labels_b)

    def loss_fn(params):
        out, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": key},
        )
        # Inception-style aux heads return (main, aux...) tuples; weight the
        # aux losses 0.3 as the paper/reference do
        # (ref: Inception/pytorch/train.py aux handling, models/inception_v1.py:92-113).
        if isinstance(out, (tuple, list)):
            main, *aux = out
            loss = mixed_ce(main)
            for a in aux:
                loss = loss + 0.3 * mixed_ce(a)
            logits = main
        else:
            logits = out
            loss = mixed_ce(logits)
        # backward runs on the (possibly loss-scaled) value; the RAW
        # loss rides the aux so metrics never report the scaled number
        return state.scale_loss(loss), (
            loss, logits, mutated.get("batch_stats", state.batch_stats))

    (_, (loss, logits, new_bs)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    new_state = state.apply_gradients(grads, batch_stats=new_bs)
    metrics = {"loss": loss, **topk_accuracy(logits, labels),
               **precision_metrics(new_state)}
    return new_state, metrics


def yolo_train_step(state: TrainState, batch: dict, key: jax.Array):
    """One detection step on {'image','boxes','label'}.

    Ground-truth grid encoding runs INSIDE the compiled step
    (ops.yolo_encode — the reference does it per-sample on the host with
    TensorArray loops, ref: YOLO/tensorflow/preprocess.py:137-269); grids
    never cross the host↔device boundary. ``boxes`` are (B, M, 4) xywh
    normalized, padded with zeros; ``label`` is (B, M) int32, -1 padding.
    """
    from deepvision_tpu.losses.yolo import yolo_loss
    from deepvision_tpu.ops.yolo_encode import encode_labels

    images = maybe_normalize(batch["image"], "tanh")
    boxes, labels = batch["boxes"], batch["label"]
    size = images.shape[1]
    grid_sizes = (size // 8, size // 16, size // 32)

    def loss_fn(params):
        preds, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        num_classes = preds[0].shape[-1] - 5
        y_true = encode_labels(
            boxes, labels, num_classes, grid_sizes=grid_sizes
        )
        parts = yolo_loss(y_true, preds, num_classes,
                          true_boxes_xywh=boxes)
        loss = jnp.mean(parts["loss"])
        return state.scale_loss(loss), (
            parts, mutated.get("batch_stats", state.batch_stats))

    (_, (parts, new_bs)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    new_state = state.apply_gradients(grads, batch_stats=new_bs)
    metrics = {k: jnp.mean(v) for k, v in parts.items()}
    metrics.update(precision_metrics(new_state))
    return new_state, metrics


def yolo_eval_step(state: TrainState, batch: dict) -> dict:
    """Mask-weighted val-loss sums (exact full-set aggregation)."""
    from deepvision_tpu.losses.yolo import yolo_loss
    from deepvision_tpu.ops.yolo_encode import encode_labels

    images = maybe_normalize(batch["image"], "tanh")
    boxes, labels = batch["boxes"], batch["label"]
    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones(images.shape[0], jnp.float32)
    size = images.shape[1]
    grid_sizes = (size // 8, size // 16, size // 32)
    variables: dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    preds = state.apply_fn(variables, images, train=False)
    num_classes = preds[0].shape[-1] - 5
    y_true = encode_labels(boxes, labels, num_classes, grid_sizes=grid_sizes)
    parts = yolo_loss(y_true, preds, num_classes, true_boxes_xywh=boxes)
    return {
        "loss_sum": jnp.sum(parts["loss"] * mask),
        "count": jnp.sum(mask),
    }


def classification_eval_step(
    state: TrainState, batch: dict, normalize_kind: str = "imagenet"
) -> dict:
    """Count-weighted sums over one batch, for exact epoch aggregation.

    ``batch["mask"]`` (optional, (B,) float 1/0) marks padding rows: the
    final partial validation batch is padded to full size and masked so the
    whole 50k-image set is evaluated with one compiled shape — the
    reference evaluates the full set too (ref: ResNet/pytorch/train.py:488-520).
    """
    images = maybe_normalize(batch["image"], normalize_kind)
    labels = batch["label"]
    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones(labels.shape[0], jnp.float32)
    variables: dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    logits = state.apply_fn(variables, images, train=False)
    if isinstance(logits, (tuple, list)):
        logits = logits[0]
    losses = softmax_cross_entropy_per_sample(logits, labels)
    correct = topk_correct(logits, labels)
    return {
        "loss_sum": jnp.sum(losses * mask),
        "count": jnp.sum(mask),
        **{k: jnp.sum(v * mask) for k, v in correct.items()},
    }


def pose_train_step(state: TrainState, batch: dict, key: jax.Array):
    """One pose step on {'image','kx','ky','v'}.

    Gaussian heatmap targets are rasterized INSIDE the compiled step
    (ops.heatmap — the reference does it per-joint on the host with
    TensorArray loops, ref: Hourglass/tensorflow/preprocess.py:91-173);
    loss is the stack-summed foreground-weighted MSE
    (ref: Hourglass/tensorflow/train.py:65-76).
    """
    from deepvision_tpu.losses.pose import weighted_heatmap_mse
    from deepvision_tpu.ops.heatmap import gaussian_heatmaps

    images = maybe_normalize(batch["image"], "tanh")
    grid = images.shape[1] // 4  # stem downsamples 256² -> 64²
    targets = gaussian_heatmaps(
        batch["kx"], batch["ky"], batch["v"], height=grid, width=grid
    )

    def loss_fn(params):
        outputs, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        loss = weighted_heatmap_mse(targets, outputs)
        return state.scale_loss(loss), (
            loss, mutated.get("batch_stats", state.batch_stats))

    (_, (loss, new_bs)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params
    )
    new_state = state.apply_gradients(grads, batch_stats=new_bs)
    return new_state, {"loss": loss,
                       **precision_metrics(new_state)}


def pose_eval_step(state: TrainState, batch: dict) -> dict:
    """Mask-weighted val-loss sums (exact full-set aggregation)."""
    from deepvision_tpu.losses.pose import weighted_heatmap_mse
    from deepvision_tpu.ops.heatmap import gaussian_heatmaps

    images = maybe_normalize(batch["image"], "tanh")
    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones(images.shape[0], jnp.float32)
    grid = images.shape[1] // 4
    targets = gaussian_heatmaps(
        batch["kx"], batch["ky"], batch["v"], height=grid, width=grid
    )
    variables: dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    outputs = state.apply_fn(variables, images, train=False)
    losses = weighted_heatmap_mse(targets, outputs, per_sample=True)
    return {
        "loss_sum": jnp.sum(losses * mask),
        "count": jnp.sum(mask),
    }


def centernet_train_step(state: TrainState, batch: dict, key: jax.Array):
    """One CenterNet step on the detection batch format
    {'image','boxes','label'} (shared with YOLO); targets encoded in-step
    (ops.centernet_encode), loss = focal + L1s over both stacks
    (losses.centernet — the capability the reference left unfinished,
    ref: ObjectsAsPoints/tensorflow/train.py:35,248).
    """
    from deepvision_tpu.losses.centernet import centernet_loss
    from deepvision_tpu.ops.centernet_encode import encode_centernet

    images = maybe_normalize(batch["image"], "tanh")
    boxes, labels = batch["boxes"], batch["label"]
    grid = images.shape[1] // 4  # output stride 4

    def loss_fn(params):
        outputs, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        num_classes = outputs[0][0].shape[-1]
        targets = encode_centernet(boxes, labels, num_classes, grid)
        parts = centernet_loss(targets, outputs)
        return state.scale_loss(parts["loss"]), (
            parts, mutated.get("batch_stats", state.batch_stats))

    (_, (parts, new_bs)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    new_state = state.apply_gradients(grads, batch_stats=new_bs)
    return new_state, {**parts, **precision_metrics(new_state)}


def centernet_eval_step(state: TrainState, batch: dict) -> dict:
    """Mask-weighted val-loss sums (exact full-set aggregation)."""
    from deepvision_tpu.losses.centernet import centernet_loss
    from deepvision_tpu.ops.centernet_encode import encode_centernet

    images = maybe_normalize(batch["image"], "tanh")
    boxes, labels = batch["boxes"], batch["label"]
    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones(images.shape[0], jnp.float32)
    grid = images.shape[1] // 4
    variables: dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    outputs = state.apply_fn(variables, images, train=False)
    num_classes = outputs[0][0].shape[-1]
    targets = encode_centernet(boxes, labels, num_classes, grid)
    parts = centernet_loss(targets, outputs, per_sample=True)
    return {
        "loss_sum": jnp.sum(parts["loss"] * mask),
        "count": jnp.sum(mask),
    }


def aggregate_eval_parts(parts) -> tuple[dict, float]:
    """Sum an iterable of eval-step outputs (count-weighted sums + a
    'count' key) into ``(val_* means, total count)`` — the one masked
    exact-aggregation impl shared by Trainer.validate and evaluate.py.
    '<k>_sum' and bare keys both become ``val_<k>`` means."""
    totals = None
    for part in parts:
        part = {k: float(v) for k, v in part.items()}
        if totals is None:
            totals = part
        else:
            totals = {k: totals[k] + part[k] for k in totals}
    if not totals:
        return {}, 0.0
    n = totals.pop("count")
    return {
        f"val_{k[:-4] if k.endswith('_sum') else k}": v / n
        for k, v in totals.items()
    }, n
