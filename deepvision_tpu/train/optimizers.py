"""Optimizer factories: reference ``training_config`` semantics → optax.

torch-SGD weight decay is L2-added-to-grad BEFORE momentum accumulation, so
the optax chain is ``add_decayed_weights → sgd(momentum)``; torch RMSprop's
``alpha``/``eps`` map to optax ``decay``/``eps``
(ref configs: ResNet/pytorch/train.py:26-215).

Plateau-scheduled configs wrap the whole chain in
``optax.inject_hyperparams`` over a ``lr_scale`` factor so the host-side
PlateauController can rescale the LR without recompiling the step.
"""

from __future__ import annotations

from typing import Any

import optax

from deepvision_tpu.train import schedules


def _base_tx(opt: str, lr, params: dict[str, Any]) -> optax.GradientTransformation:
    wd = params.get("weight_decay", 0.0)
    parts = []
    if opt == "sgd":
        if wd:
            parts.append(optax.add_decayed_weights(wd))
        parts.append(optax.sgd(lr, momentum=params.get("momentum", 0.0)))
    elif opt == "rmsprop":
        if wd:
            parts.append(optax.add_decayed_weights(wd))
        parts.append(optax.rmsprop(lr, decay=params.get("alpha", 0.9),
                                   eps=params.get("eps", 1e-8)))
    elif opt == "adam":
        parts.append(optax.adam(lr, b1=params.get("beta1", 0.9),
                                b2=params.get("beta2", 0.999),
                                eps=params.get("eps", 1e-8)))
    else:
        raise ValueError(f"unknown optimizer {opt!r}")
    return optax.chain(*parts)


def make_optimizer(cfg: dict, steps_per_epoch: int):
    """-> (tx, plateau_controller | None) from a training_config entry."""
    opt = cfg["optimizer"]
    p = dict(cfg.get("optimizer_params", {}))
    base_lr = p.pop("lr")
    sched_name = cfg.get("scheduler")
    sched_p = cfg.get("scheduler_params", {})

    if sched_name == "plateau":
        controller = schedules.PlateauController(
            mode=sched_p.get("mode", "max"),
            factor=sched_p.get("factor", 0.1),
            patience=sched_p.get("patience", 10),
        )

        def make(lr_scale):
            return _base_tx(opt, base_lr * lr_scale, p)

        tx = optax.inject_hyperparams(make)(lr_scale=1.0)
        return tx, controller

    if sched_name == "step":
        lr = schedules.step_decay(base_lr, steps_per_epoch,
                                  sched_p["step_size"], sched_p["gamma"])
    elif sched_name == "inception_poly":
        lr = schedules.inception_poly(base_lr, steps_per_epoch)
    elif sched_name == "linear_decay":
        lr = schedules.linear_decay(base_lr, sched_p["total_steps"],
                                    sched_p["decay_start"])
    elif sched_name in (None, "constant"):
        lr = base_lr
    else:
        raise ValueError(f"unknown scheduler {sched_name!r}")
    return _base_tx(opt, lr, p), None


def set_lr_scale(opt_state, scale: float):
    """Write the PlateauController's scale into inject_hyperparams state."""
    import jax.numpy as jnp

    hp = dict(opt_state.hyperparams)
    hp["lr_scale"] = jnp.asarray(scale, jnp.float32)
    return opt_state._replace(hyperparams=hp)
