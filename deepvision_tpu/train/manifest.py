"""Checkpoint integrity manifests: the pure-file half of PR 4's story.

A committed epoch's sidecar ``manifest-<epoch>.json`` records per-file
size + SHA-256 for everything under the step directory. This module
holds the write/verify primitives WITHOUT importing Orbax (or jax), so
two kinds of consumers can share one implementation:

- ``train/checkpoint.CheckpointManager`` (the writer, post-commit);
- the cluster supervisor (``resilience/cluster.py``), a jax-free parent
  process that must pick "the newest commonly-verified epoch" before
  relaunching a preempted multi-host job — it verifies and quarantines
  with nothing but file hashes.

Concurrency contract: ``write_manifest`` stages through a tmp file
UNIQUE to the writer (pid + monotonic counter) and commits with one
atomic ``os.replace``. Two hosts of a multi-process run racing the same
epoch's commit (a preemption barrier interrupted mid-save) therefore
leave either the old or the new COMPLETE manifest — never interleaved
or truncated bytes — and a writer killed mid-stage leaves only its own
tmp file, which verification ignores.
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
from pathlib import Path

MANIFEST_VERSION = 1

_tmp_seq = itertools.count()


def _hash_file(path: Path) -> str:
    """Streaming SHA-256 — the repo's ONE implementation (incl. the
    ``hashlib.file_digest`` fast path on 3.11+)."""
    from deepvision_tpu.convert.pretrained import file_digest

    return file_digest(path, "sha256")


def manifest_path(root: str | Path, epoch: int) -> Path:
    return Path(root) / f"manifest-{epoch}.json"


def step_dir(root: str | Path, epoch: int) -> Path:
    return Path(root) / str(epoch)


def write_manifest(root: str | Path, epoch: int,
                   extra: dict | None = None) -> None:
    """Hash the committed epoch directory into its sidecar. Atomic and
    multi-writer-safe: the tmp name is unique per (pid, call), so
    concurrent writers each stage complete bytes and the last
    ``os.replace`` wins with a valid file.

    ``extra`` merges additional audited fields into the sidecar —
    notably ``state_fingerprint`` (resilience/sentinel.py), the
    save-time random-projection fingerprint of the in-memory state:
    SHA-256 proves the bytes on disk match the bytes that were
    written; the fingerprint lets a verified restore prove those bytes
    match the state the trainer MEANT to save (corruption that
    predates serialization)."""
    root = Path(root)
    sdir = step_dir(root, epoch)
    if not sdir.exists():  # e.g. keep_best evicted it already
        return
    files = {
        str(p.relative_to(sdir)): {
            "size": p.stat().st_size,
            "sha256": _hash_file(p),
        }
        for p in sorted(sdir.rglob("*")) if p.is_file()
    }
    manifest = {"version": MANIFEST_VERSION, "epoch": int(epoch),
                "files": files, **(extra or {})}
    target = manifest_path(root, epoch)
    tmp = target.with_suffix(
        f".json.tmp.{os.getpid()}.{next(_tmp_seq)}")
    tmp.write_text(json.dumps(manifest))
    os.replace(tmp, target)


def read_manifest(root: str | Path, epoch: int) -> dict | None:
    """The committed sidecar as a dict (None when absent/unreadable) —
    how the verified restore reads the audited ``state_fingerprint``."""
    try:
        return json.loads(manifest_path(root, epoch).read_text())
    except (OSError, ValueError):
        return None


def verify_manifest(root: str | Path, epoch: int) -> tuple[bool, str]:
    """-> (ok, reason). An epoch with NO manifest verifies vacuously
    (pre-integrity checkpoints stay restorable); an unreadable or
    mismatching manifest fails it."""
    root = Path(root)
    sdir = step_dir(root, epoch)
    if not sdir.exists():
        return False, "step directory missing"
    mp = manifest_path(root, epoch)
    if not mp.exists():
        return True, "no manifest (pre-integrity checkpoint)"
    try:
        manifest = json.loads(mp.read_text())
        files = manifest["files"]
        for rel, want in files.items():
            p = sdir / rel
            if not p.is_file():
                return False, f"missing file {rel}"
            if p.stat().st_size != want["size"]:
                return False, (f"size mismatch {rel}: "
                               f"{p.stat().st_size} != {want['size']}")
            if _hash_file(p) != want["sha256"]:
                return False, f"checksum mismatch {rel}"
    except (ValueError, KeyError, TypeError, AttributeError,
            OSError) as e:
        # parses-but-wrong-schema manifests and files vanishing
        # mid-scan are corruption too — verification must FAIL
        # them, never crash on them
        return False, f"unreadable/malformed manifest: {e}"
    return True, "ok"


def fs_epochs(root: str | Path) -> list[int]:
    """Epoch dirs actually on disk, ascending."""
    root = Path(root)
    if not root.exists():
        return []
    return sorted(int(p.name) for p in root.iterdir()
                  if p.is_dir() and p.name.isdigit())


def newest_verified_epoch(root: str | Path, *, quarantine: bool = False,
                          log=print) -> int | None:
    """Newest-first scan returning the first epoch whose manifest
    verifies. With ``quarantine``, failing epochs are MOVED to
    ``quarantine/`` on the way past (evidence, not deletion) — the
    single-writer form of ``CheckpointManager.restore_verified``'s
    fallback that the cluster supervisor runs before relaunching a
    degraded job (no Orbax, no jax, no collective restore needed)."""
    root = Path(root)
    for epoch in reversed(fs_epochs(root)):
        ok, why = verify_manifest(root, epoch)
        if ok:
            return epoch
        log(f"[ckpt-integrity] epoch {epoch}: {why}"
            + ("; quarantining" if quarantine else ""), flush=True)
        if quarantine:
            qroot = root / "quarantine"
            qroot.mkdir(exist_ok=True)
            target = qroot / str(epoch)
            n = 0
            while target.exists():
                n += 1
                target = qroot / f"{epoch}.{n}"
            shutil.move(str(step_dir(root, epoch)), str(target))
            mp = manifest_path(root, epoch)
            if mp.exists():
                shutil.move(str(mp), str(target) + ".manifest.json")
    return None
