"""Classification loss + top-k metrics.

One canonical form: integer labels + logits (the PyTorch reference's
``CrossEntropyLoss`` convention — ref: ResNet/pytorch/train.py:452). The TF
reference instead bakes softmax into the model and uses
``categorical_crossentropy`` on one-hots (ref:
ResNet/tensorflow/models/resnet50.py:42, train.py:275-279); that asymmetry is
normalized away here — all models emit logits, one-hot conversion happens in
the loss.

Top-1/top-5 metrics mirror ref: ResNet/pytorch/train.py:523-538.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def softmax_cross_entropy_per_sample(
    logits, labels, *, label_smoothing: float = 0.0
):
    """Per-sample CE losses (B,). ``labels`` are int32 class ids."""
    logits = logits.astype(jnp.float32)
    if label_smoothing:
        num_classes = logits.shape[-1]
        onehot = jnp.eye(num_classes, dtype=jnp.float32)[labels]
        return optax.softmax_cross_entropy(
            logits, optax.smooth_labels(onehot, label_smoothing)
        )
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def softmax_cross_entropy(logits, labels, *, label_smoothing: float = 0.0):
    """Mean CE over the batch. ``labels`` are int32 class ids."""
    return softmax_cross_entropy_per_sample(
        logits, labels, label_smoothing=label_smoothing
    ).mean()


# Alias used throughout the trainers.
cross_entropy_loss = softmax_cross_entropy


def topk_correct(logits, labels, ks=(1, 5)):
    """dict of per-sample top-k hit indicators (B,) float32.

    ref: ResNet/pytorch/train.py:523-538 computes top-1/top-5 with
    ``torch.topk``; same semantics here via a rank comparison (the true
    class is in the top-k iff fewer than k classes score strictly higher).
    """
    logits = logits.astype(jnp.float32)
    target_scores = jnp.take_along_axis(logits, labels[:, None], axis=-1)
    rank = jnp.sum(logits > target_scores, axis=-1)
    return {f"top{k}": (rank < k).astype(jnp.float32) for k in ks}


def topk_accuracy(logits, labels, ks=(1, 5)):
    """dict of top-k accuracies (fractions in [0,1])."""
    return {k: v.mean() for k, v in topk_correct(logits, labels, ks).items()}
