from deepvision_tpu.losses.classification import (
    cross_entropy_loss,
    softmax_cross_entropy,
    topk_accuracy,
)

__all__ = ["cross_entropy_loss", "softmax_cross_entropy", "topk_accuracy"]
