"""YOLO v3 multi-scale loss with IoU ignore mask (pure jnp).

Semantics parity with ref: YOLO/tensorflow/yolov3.py:352-563, re-expressed
for XLA:

- xy/wh: L2 on cell-relative coords, masked by objectness, weighted by
  (2 - w*h) small-box boost, × λ_coord=5 (ref: :407, :516-563),
- class: elementwise BCE on sigmoid probs, object cells only (ref: :496-513),
- objectness: BCE split into obj + λ_noobj=0.5 × noobj, the noobj part
  gated by the ignore mask (best IoU vs true boxes < 0.5 keeps the
  penalty — ref: :437-493),
- ignore mask: the reference reshapes/sorts the y_true grid and caps at
  100 boxes to bound the IoU matrix (ref: :448-454); here the trainer
  passes the already-padded (B, M, 4) ground-truth boxes straight from the
  batch — same mask, no sort, fixed shapes throughout.

Every component is returned per-batch-mean so the Trainer can log the
xy/wh/class/obj split exactly like the reference (ref: train.py:91-95).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepvision_tpu.ops.iou import (
    binary_cross_entropy,
    broadcast_iou,
    xywh_to_corners,
)
from deepvision_tpu.ops.yolo_decode import decode_absolute, encode_relative
from deepvision_tpu.ops.yolo_encode import ANCHORS_WH

LAMBDA_COORD = 5.0  # ref: yolov3.py:357
LAMBDA_NOOBJ = 0.5  # ref: yolov3.py:358
IGNORE_THRESH = 0.5  # ref: yolov3.py:355


def yolo_scale_loss(y_true, y_pred, anchors_wh, num_classes: int,
                    true_boxes_xywh=None):
    """Loss for ONE scale.

    y_true: (B, S, S, 3, 5+C) grid from ops.yolo_encode.encode_labels
    y_pred: (B, S, S, 3, 5+C) raw model output
    true_boxes_xywh: (B, M, 4) padded ground-truth boxes for the ignore
        mask; padding rows must be all-zero. Falls back to extracting
        non-zero boxes from the grid when omitted.

    -> dict of per-image (B,) vectors: loss, xy, wh, class, obj.
    """
    y_pred = y_pred.astype(jnp.float32)
    y_true = y_true.astype(jnp.float32)

    pred_xy_rel = jax.nn.sigmoid(y_pred[..., 0:2])
    pred_wh_rel = y_pred[..., 2:4]
    pred_box_abs, pred_obj, pred_class = decode_absolute(
        y_pred, anchors_wh, num_classes
    )

    true_xy = y_true[..., 0:2]
    true_wh = y_true[..., 2:4]
    true_obj = y_true[..., 4]
    true_class = y_true[..., 5:]
    true_rel = encode_relative(y_true[..., 0:4], anchors_wh)

    # small-box weight (ref: :407)
    weight = 2.0 - true_wh[..., 0] * true_wh[..., 1]

    xy_loss = jnp.sum(
        jnp.square(true_rel[..., 0:2] - pred_xy_rel), axis=-1
    )
    xy_loss = LAMBDA_COORD * jnp.sum(
        true_obj * weight * xy_loss, axis=(1, 2, 3)
    )
    wh_loss = jnp.sum(
        jnp.square(true_rel[..., 2:4] - pred_wh_rel), axis=-1
    )
    wh_loss = LAMBDA_COORD * jnp.sum(
        true_obj * weight * wh_loss, axis=(1, 2, 3)
    )

    class_loss = jnp.sum(
        binary_cross_entropy(pred_class, true_class), axis=-1
    )
    class_loss = jnp.sum(true_obj * class_loss, axis=(1, 2, 3))

    # ignore mask: best IoU of every predicted box vs the ground truth set
    b = y_pred.shape[0]
    if true_boxes_xywh is None:
        true_boxes_xywh = y_true[..., 0:4].reshape(b, -1, 4)
    true_corners = xywh_to_corners(true_boxes_xywh)
    pred_corners = xywh_to_corners(pred_box_abs).reshape(b, -1, 4)
    best_iou = jnp.max(
        broadcast_iou(pred_corners, true_corners), axis=-1
    ).reshape(true_obj.shape)
    ignore = (best_iou < IGNORE_THRESH).astype(jnp.float32)

    obj_entropy = binary_cross_entropy(pred_obj[..., 0], true_obj)
    obj_part = jnp.sum(true_obj * obj_entropy, axis=(1, 2, 3))
    noobj_part = LAMBDA_NOOBJ * jnp.sum(
        (1.0 - true_obj) * obj_entropy * ignore, axis=(1, 2, 3)
    )
    obj_loss = obj_part + noobj_part

    total = xy_loss + wh_loss + class_loss + obj_loss
    # per-image sums (B,), like the reference's per-replica per-image loss
    # before the 1/global_batch scaling (ref: train.py:85-89)
    return {
        "loss": total,
        "xy": xy_loss,
        "wh": wh_loss,
        "class": class_loss,
        "obj": obj_loss,
    }


def yolo_loss(y_true_grids, y_pred_grids, num_classes: int,
              true_boxes_xywh=None):
    """Per-image (B,) loss components summed over the three scales.

    The reference computes one YoloLoss per scale with that scale's anchor
    triple and adds them (ref: train.py:81-95, anchors yolov3.py:18-20).
    Callers take the batch mean (train) or mask-weighted sums (eval).
    """
    anchor_groups = (ANCHORS_WH[0:3], ANCHORS_WH[3:6], ANCHORS_WH[6:9])
    totals = {"loss": 0.0, "xy": 0.0, "wh": 0.0, "class": 0.0, "obj": 0.0}
    for y_true, y_pred, anchors in zip(
        y_true_grids, y_pred_grids, anchor_groups
    ):
        part = yolo_scale_loss(
            y_true, y_pred, anchors, num_classes, true_boxes_xywh
        )
        totals = {k: totals[k] + part[k] for k in totals}
    return totals
