"""CenterNet losses — the part the reference never finished (its trainer
has an empty loss list and a commented-out run, ref:
ObjectsAsPoints/tensorflow/train.py:35,248). Completed per the
Objects-as-Points paper the reference implements:

- penalty-reduced pixelwise focal loss on the class center heatmaps
  (α=2, β=4), normalized by the number of objects,
- L1 on sub-cell center offsets (λ_off = 1),
- L1 on box sizes in output cells (λ_size = 0.1),

summed over both hourglass stacks (intermediate supervision).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

ALPHA = 2.0
BETA = 4.0
LAMBDA_SIZE = 0.1
LAMBDA_OFF = 1.0
EPS = 1e-6


def centernet_focal_loss(heatmap_logits, target, *, per_sample=False):
    """Penalty-reduced focal loss; target peaks (==1) are positives."""
    p = jnp.clip(jax.nn.sigmoid(heatmap_logits), EPS, 1.0 - EPS)
    pos = (target >= 1.0).astype(jnp.float32)
    neg = 1.0 - pos
    pos_term = -pos * ((1 - p) ** ALPHA) * jnp.log(p)
    neg_term = -neg * ((1 - target) ** BETA) * (p ** ALPHA) * jnp.log(1 - p)
    axes = tuple(range(1, heatmap_logits.ndim))
    n_pos = jnp.maximum(jnp.sum(pos, axis=axes), 1.0)
    loss = (jnp.sum(pos_term, axis=axes) + jnp.sum(neg_term, axis=axes)) \
        / n_pos
    return loss if per_sample else jnp.mean(loss)


def _masked_l1(pred, target, mask):
    """Mean-over-objects L1 at center cells; mask (B, G, G)."""
    axes = tuple(range(1, mask.ndim))
    n = jnp.maximum(jnp.sum(mask, axis=axes), 1.0)
    err = jnp.sum(
        jnp.abs(pred - target) * mask[..., None], axis=axes + (mask.ndim,)
    )
    return err / n


def centernet_loss(
    targets: dict,
    outputs: Sequence[tuple],
    *,
    per_sample: bool = False,
):
    """targets from ops.centernet_encode; outputs = per-stack
    (heatmap_logits, wh, offset). Returns metric parts dict."""
    total = heat_l = wh_l = off_l = 0.0
    for heat, wh, off in outputs:
        hl = centernet_focal_loss(
            heat.astype(jnp.float32), targets["heatmap"], per_sample=True
        )
        wl = _masked_l1(wh.astype(jnp.float32), targets["wh"],
                        targets["mask"])
        ol = _masked_l1(off.astype(jnp.float32), targets["offset"],
                        targets["mask"])
        heat_l = heat_l + hl
        wh_l = wh_l + wl
        off_l = off_l + ol
        total = total + hl + LAMBDA_SIZE * wl + LAMBDA_OFF * ol
    parts = {"loss": total, "heatmap_loss": heat_l, "wh_loss": wh_l,
             "offset_loss": off_l}
    if per_sample:
        return parts
    return {k: jnp.mean(v) for k, v in parts.items()}
