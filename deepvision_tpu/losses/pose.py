"""Pose heatmap losses.

Capability parity with ref: Hourglass/tensorflow/train.py:65-76 — MSE
between predicted and target heatmaps with foreground pixels weighted
×(81+1), summed over every stack's intermediate-supervision output.
The reference divides by the global batch size after a per-replica mean
(MirroredStrategy loss scaling); under jit+NamedSharding a plain global
mean has identical semantics, so no explicit scaling appears here.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

FOREGROUND_WEIGHT = 81.0  # ref: train.py:69


def weighted_heatmap_mse(
    targets: jnp.ndarray,
    outputs: Sequence[jnp.ndarray] | jnp.ndarray,
    *,
    per_sample: bool = False,
) -> jnp.ndarray:
    """Sum over stacks of foreground-weighted MSE vs one shared target.

    targets: (B, H, W, K); outputs: per-stack (B, H, W, K) predictions.
    With ``per_sample`` the per-image loss (B,) is returned (for exact
    masked validation aggregation), else the scalar mean.
    """
    if not isinstance(outputs, (tuple, list)):
        outputs = (outputs,)
    targets = targets.astype(jnp.float32)
    weights = (targets > 0).astype(jnp.float32) * FOREGROUND_WEIGHT + 1.0
    axes = (1, 2, 3)
    total = 0.0
    for out in outputs:
        sq = jnp.square(targets - out.astype(jnp.float32)) * weights
        total = total + jnp.mean(sq, axis=axes)
    return total if per_sample else jnp.mean(total)
