"""deepvision_tpu.resilience — self-healing training & serving.

The ROADMAP north star is a production-scale system; at that scale
preemption, transient I/O, bit-rot, and numeric blow-ups are routine
events to recover from, not fatal errors (PAPERS.md "Scale MLPerf-0.6
models on Google TPU-v3 Pods" treats them as the steady state). Every
failure path in the framework used to be fail-fast: the checkify
NaN/Inf tripwire killed the run, a corrupt checkpoint crashed
``Trainer.resume()``, and a dispatcher-loop crash stranded every queued
future. This package adds the recovery layer plus the deterministic
fault-injection harness needed to TEST it on CPU:

- ``faults``   : :class:`FaultInjector` — a deterministic, occurrence-
                 scheduled (or seed-scheduled probabilistic) injector of
                 NaN steps, transient data-read ``IOError``, on-disk
                 checkpoint corruption, stalled steps, and dispatcher
                 crashes. Trainer / data / checkpoint / serve layers
                 consult it through injectable hooks, so chaos tests
                 replay bit-identically.
- ``recovery`` : :class:`RecoveryPolicy` (bounded retries, exponential
                 backoff, rollback budget) + :class:`RecoveryCounters`
                 (rollbacks / ckpt_fallbacks / data_retries, surfaced
                 per epoch through ``train/loggers.Loggers``).
- ``cluster``  : preemption-tolerant MULTI-HOST training —
                 :class:`ClusterMember` (heartbeats + the coordinated
                 save-barrier protocol the Trainer speaks + the
                 cross-host state-agreement audit files),
                 :class:`HostLedger` (liveness/straggler view, obs
                 gauges), and :class:`ClusterSupervisor`
                 (``train_dist.py --supervise N``: watch, deliver/
                 absorb preemptions, relaunch on the surviving host
                 set with deterministic elastic resume — and, on an
                 SDC verdict, attribute the culprit by replay
                 bisection and quarantine it). Imported lazily by
                 consumers — it is NOT re-exported here so
                 ``import deepvision_tpu.resilience`` stays cheap for
                 the serve/data layers.
- ``sentinel`` : SILENT-failure defense — :func:`sentinel_step`
                 (in-graph numeric invariants riding the step's
                 metrics pytree), :class:`EwmaDetector` /
                 :class:`SentinelMonitor` (z-score anomaly detection
                 on the drain cadence, trips feed the rollback),
                 :func:`tree_fingerprint` (seeded random-projection
                 state fingerprint: the cross-host agreement audit
                 and the audited checkpoint manifests), and
                 :func:`apply_sdc` (the deterministic corruption
                 drills). Imported lazily for the same reason as
                 ``cluster``.

Consumers: ``train/trainer.py`` (NaN tripwire -> checkpoint rollback +
batch-window skip), ``train/checkpoint.py`` (per-save checksum
manifests, verify-quarantine-fallback resume), ``data/prefetch.py``
(bounded transient-read retries), ``serve/engine.py`` (supervised
dispatcher with crash containment + backoff restart).
"""

from deepvision_tpu.resilience.faults import (
    CLUSTER_SITES,
    FaultInjector,
    FaultSpec,
    InjectedCrash,
    InjectedIOError,
    format_spec,
    parse_schedule,
    poison_batch,
    split_schedule,
)
from deepvision_tpu.resilience.recovery import (
    NumericDivergence,
    RecoveryCounters,
    RecoveryError,
    RecoveryPolicy,
)

__all__ = [
    "CLUSTER_SITES",
    "FaultInjector",
    "FaultSpec",
    "InjectedCrash",
    "InjectedIOError",
    "format_spec",
    "parse_schedule",
    "poison_batch",
    "split_schedule",
    "NumericDivergence",
    "RecoveryCounters",
    "RecoveryError",
    "RecoveryPolicy",
]
