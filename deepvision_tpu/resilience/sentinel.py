"""Silent-failure defense: in-graph numeric sentinels, state
fingerprints, and the SDC corruption model they are drilled against.

Every failure the stack survives today announces itself — the checkify
NaN tripwire raises, a dead replica stops answering, a preempted host
gets a SIGTERM. The hazard this module closes is the host that keeps
heartbeating while computing the *wrong* answer: silent data corruption
(SDC) from a flaky HBM chip, a mis-executing core, or a poisoned decode
worker. At the pod scales the ROADMAP targets SDC is a when, not an if
(the pjit/TPU-pod playbooks in PAPERS.md run fleets where screening for
"mercurial cores" is routine ops). Three cooperating defenses:

**In-graph sentinels** (:func:`sentinel_step`)
    Cheap global invariants computed INSIDE the compiled train step —
    the L2 norm of the parameter update (the donation-safe stand-in
    for the global gradient norm: for any first-order optimizer the
    update is a per-leaf-scaled gradient, so a corrupted gradient is a
    corrupted update norm), the parameter norm, and their ratio — and
    merged into the step's existing scalar metrics pytree. They ride
    the Trainer's pending/drain fetch cadence, so they cost a few
    reductions per step and ZERO extra host syncs (a per-step
    ``float()`` consumer is exactly the JX109 stall jaxlint JX116
    exists to flag). An :class:`EwmaDetector` z-scores each series
    against its own exponentially-weighted history: a numeric blow-up
    or a large corrupted update trips within one drain cadence —
    before the corrupted state ever reaches a checkpoint — and the
    trip feeds the PR 4 ``RecoveryPolicy`` rollback.

**State fingerprints** (:func:`tree_fingerprint`)
    A seeded random-sign projection of the replicated parameter tree,
    accumulated in float64 and digested: same state + same seed is
    bit-equal, a single-ulp perturbation of any leaf flips the digest.
    Two consumers: the cross-host agreement audit (every K steps each
    host fingerprints its replica and the cluster compares —
    replicated state that disagrees across hosts IS an SDC, caught
    within K steps of the corruption; ``resilience/cluster.py`` holds
    the file protocol) and the audited checkpoint manifest (the PR 4
    sidecar gains the save-time state fingerprint, so a verified
    restore catches corruption that PREDATES serialization — SHA-256
    alone only proves the bytes on disk match bytes that were already
    wrong).

**Deterministic SDC injection** (:func:`apply_sdc`)
    The drill half: ``faults.py``'s ``sdc_grad``/``sdc_param`` sites
    fire at a deterministic RUN step (epoch-anchored, so replays from
    any resume point re-fire identically) on one targeted host, and
    this module applies the corruption — a small scale of one
    parameter leaf (a wrong gradient update; silent to the z-score at
    the default magnitude, caught by the agreement audit) or a
    single-bit mantissa flip (the classic one-ulp SDC only the
    fingerprint can see). Attribution — WHICH host computed garbage —
    is the cluster supervisor's replay bisection
    (``ClusterSupervisor``): deterministic elastic resume re-runs the
    suspect window on survivor subsets and compares fingerprints
    against the replayed ground truth.
"""

from __future__ import annotations

import hashlib
import math
import struct

import numpy as np

from deepvision_tpu.obs.metrics import default_registry
from deepvision_tpu.resilience.recovery import NumericDivergence

__all__ = [
    "AuditDivergence",
    "EwmaDetector",
    "SentinelMonitor",
    "SentinelTrip",
    "apply_sdc",
    "sentinel_step",
    "tree_fingerprint",
    "fingerprints_agree",
]

# sentinel scalar names added to the step's metrics pytree; the "sent_"
# prefix is the naming contract JX116 keys on and the detector watches
SENTINEL_KEYS = ("sent_update_norm", "sent_param_norm",
                 "sent_update_ratio")
# replay attribution is a RATIO test, not a flat tolerance: a replay
# on a different host count carries collective reduction-order (and
# bf16 rounding) noise that hits every host's comparison EQUALLY, so
# the cleanest host's deviation from the replayed truth is the noise
# floor and direct corruption shows as the host sitting this factor
# above it (measured on the 2-host lenet drill: clean-host dev ~2e-5,
# corrupted-host dev ~9e-4 — 40x). Corruptions BELOW the replay noise
# floor (a lone ulp flip) are still DETECTED by the bit-exact digest
# audit, but cross-host-count replay cannot attribute them; majority
# vote (fleets of 3+) can.
ATTRIBUTION_RATIO = 4.0
_FP_BUCKETS = 8  # projection components per fingerprint


class SentinelTrip(NumericDivergence):
    """An in-graph sentinel z-scored outside its history: the silent
    analog of the checkify tripwire. Subclasses
    :class:`NumericDivergence` so the Trainer's existing rollback loop
    (restore newest verified checkpoint, skip the batch window)
    handles it unchanged."""

    def __init__(self, epoch: int, step_in_epoch: int, key: str,
                 value: float, z: float):
        self.key = key
        self.value = float(value)
        self.z = float(z)
        super().__init__(epoch, step_in_epoch)
        # NumericDivergence's message names NaN/Inf; ours names the
        # sentinel that moved
        self.args = (
            f"sentinel {key}={value:.6g} tripped (|z|={z:.1f}) at "
            f"epoch {epoch} step {step_in_epoch}",)

    def __str__(self) -> str:
        return self.args[0]


class AuditDivergence(RuntimeError):
    """Cross-host fingerprint disagreement on replicated state — by
    construction an SDC somewhere in the fleet. Carries the audit step
    and the per-host fingerprints for the supervisor's attribution."""

    def __init__(self, step: int, fps: dict):
        self.step = int(step)
        self.fps = fps
        super().__init__(
            f"cross-host state fingerprints disagree at audit step "
            f"{step}: "
            + " ".join(f"host{h}={fp['digest']}"
                       for h, fp in sorted(fps.items())))


# --------------------------------------------------------- in-graph step


def sentinel_step(step_fn):
    """Wrap a pure ``step_fn(state, batch, key) -> (state, metrics)``
    so the compiled step ALSO emits the sentinel scalars.

    The additions are a handful of global reductions over the params
    (one extra scalar pytree output — no new HBM-resident tensors, no
    change to the donated state aliasing: the update ``new - old`` is
    computed from values the optimizer update already has live). The
    update norm is the donation-safe global-gradient-norm stand-in;
    the ratio update/param is the classic "learning-rate sanity"
    invariant (a healthy step moves parameters by a small fraction)."""
    import jax
    import jax.numpy as jnp

    def _norm_sq(tree):
        leaves = [l for l in jax.tree_util.tree_leaves(tree)
                  if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
        if not leaves:
            return jnp.float32(0.0)
        return sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                   for l in leaves)

    def wrapped(state, batch, key):
        new_state, metrics = step_fn(state, batch, key)
        delta_sq = _norm_sq(jax.tree.map(
            lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
            new_state.params, state.params))
        param_sq = _norm_sq(new_state.params)
        update_norm = jnp.sqrt(delta_sq)
        param_norm = jnp.sqrt(param_sq)
        metrics = dict(metrics)
        metrics["sent_update_norm"] = update_norm
        metrics["sent_param_norm"] = param_norm
        metrics["sent_update_ratio"] = update_norm / (param_norm + 1e-12)
        return new_state, metrics

    return wrapped


# ----------------------------------------------------------- the detector


class EwmaDetector:
    """Per-series EWMA mean/variance z-score anomaly detector.

    Adapts to benign drift (an lr-decay'd loss curve moves the EWMA
    with it) while a step-function anomaly lands many sigma outside
    the tracked band. ``warmup`` observations per key must land before
    any z-test (a cold variance estimate trips on everything);
    non-finite values trip immediately, warmup included — NaN is never
    in-band. A relative sigma floor keeps a converged, near-constant
    series from shrinking its band to machine epsilon and tripping on
    the next harmless wiggle."""

    def __init__(self, *, z_threshold: float = 8.0, warmup: int = 16,
                 alpha: float = 0.2, min_rel_sigma: float = 1e-3):
        if z_threshold <= 0:
            raise ValueError(f"z_threshold must be > 0, got {z_threshold}")
        if warmup < 2:
            raise ValueError(f"warmup must be >= 2, got {warmup}")
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.z_threshold = float(z_threshold)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.min_rel_sigma = float(min_rel_sigma)
        self._stats: dict[str, list] = {}  # key -> [count, mean, var]

    def reset(self) -> None:
        """Forget all history — called after a rollback (the restored
        state jumps every series back; re-warming beats re-tripping)."""
        self._stats.clear()

    def observe(self, key: str, value: float) -> float | None:
        """Fold one sample in; returns the |z|-score when it TRIPS
        (non-finite, or outside the band post-warmup), else None."""
        value = float(value)
        if not math.isfinite(value):
            return float("inf")
        st = self._stats.get(key)
        if st is None:
            st = self._stats[key] = [0, value, 0.0]
        count, mean, var = st
        z = None
        if count >= self.warmup:
            sigma = math.sqrt(var)
            floor = self.min_rel_sigma * max(abs(mean), 1e-12)
            sigma = max(sigma, floor)
            z = abs(value - mean) / sigma
        # EWMA update AFTER the test (the anomaly must not shift its
        # own acceptance band); variance tracks squared deviation from
        # the pre-update mean (West 1979 incremental form)
        a = self.alpha if count else 1.0
        d = value - mean
        st[0] = count + 1
        st[1] = mean + a * d
        st[2] = (1.0 - a) * (var + a * d * d) if count else 0.0
        if z is not None and z > self.z_threshold:
            return z
        return None


# ---------------------------------------------------------- fingerprints


def _host_local(x) -> np.ndarray:
    """Host view of (the local replica of) an array. Multi-process
    replicated jax.Arrays are not fully addressable, but each process's
    local shard IS the full replica — exactly the per-host value the
    agreement audit wants to compare."""
    try:
        import jax

        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            x = x.addressable_data(0)
    except ImportError:  # jax-free consumers (tests over numpy trees)
        pass
    return np.asarray(x)


def _leaves_with_paths(tree):
    """(path-string, leaf) pairs in a deterministic order, without
    requiring jax (plain dict/list trees fingerprint too)."""
    try:
        import jax

        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        return [(jax.tree_util.keystr(path), leaf)
                for path, leaf in flat]
    except ImportError:
        out = []

        def walk(node, prefix):
            if isinstance(node, dict):
                for k in sorted(node):
                    walk(node[k], f"{prefix}/{k}")
            elif isinstance(node, (list, tuple)):
                for i, v in enumerate(node):
                    walk(v, f"{prefix}/{i}")
            else:
                out.append((prefix, node))

        walk(tree, "")
        return out


def tree_fingerprint(tree, *, seed: int = 0,
                     signs_cache: dict | None = None) -> dict:
    """Seeded random-sign projection + energy fingerprint of a pytree.

    Each floating leaf is flattened and (in float64) both dotted
    against a deterministic ±1 sign vector derived from ``seed`` and
    the leaf's tree path AND summed-of-squares; leaf values accumulate
    into ``_FP_BUCKETS`` sign components followed by ``_FP_BUCKETS``
    energy components, all digested together (SHA-256 over the packed
    doubles, truncated). The energy half exists because a constant
    leaf meeting a balanced sign vector projects to ~zero — a uniform
    scale corruption of it would be invisible to the sign projection
    alone (found by the tamper test); the sum of squares sees every
    scale change, the sign projection sees permutations and sign
    flips that preserve energy. Properties the tests pin:

    - same tree + same seed -> bit-equal digest on every host (the
      sign vectors depend only on (seed, path, size); float64
      accumulation in a fixed order is deterministic);
    - a single-ulp perturbation of ANY leaf element flips the digest
      (ulp-scale deltas are far above float64 rounding at these
      magnitudes, and the energy term catches sign-cancelled cases).

    Returns ``{"digest": hex16, "proj": [float64 x 16], "seed": s}``.
    ``signs_cache`` (keyed by (seed, path, size)) amortizes the sign
    generation across repeated audits of the same tree shape.
    """
    proj = np.zeros(2 * _FP_BUCKETS, np.float64)
    for i, (path, leaf) in enumerate(_leaves_with_paths(tree)):
        arr = _host_local(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        flat = arr.astype(np.float64, copy=False).reshape(-1)
        ck = (seed, path, flat.size)
        signs = signs_cache.get(ck) if signs_cache is not None else None
        if signs is None:
            rng = np.random.default_rng(
                np.uint64(seed)
                + np.frombuffer(
                    hashlib.sha256(path.encode()).digest()[:8],
                    np.uint64)[0])
            signs = (rng.integers(0, 2, size=flat.size,
                                  dtype=np.int8) * 2 - 1
                     ).astype(np.float64)
            if signs_cache is not None:
                signs_cache[ck] = signs
        proj[i % _FP_BUCKETS] += float(np.dot(flat, signs))
        proj[_FP_BUCKETS + i % _FP_BUCKETS] += float(np.dot(flat, flat))
    digest = hashlib.sha256(
        struct.pack(f"<{len(proj)}d", *proj)).hexdigest()[:16]
    return {"digest": digest, "proj": [float(p) for p in proj],
            "seed": int(seed)}


def fingerprints_agree(a: dict, b: dict) -> bool:
    """The bit-exact digest test — peers running the SAME collective
    layout compute bit-identical replicated state, so any digest
    difference is an SDC (or a replay on different hardware/topology,
    which is :func:`fingerprint_deviation`'s territory)."""
    return a["digest"] == b["digest"]


def fingerprint_deviation(a: dict, b: dict) -> float:
    """Globally-normalized distance between two fingerprints' raw
    projections: per half (sign projections, then energies — different
    units), ``max_b |pa - pb| / max(|half|_inf, tiny)``, maxed over
    the halves. The GLOBAL (per-half) normalization matters —
    per-bucket relative deviation lets a near-zero bucket's floating
    noise dominate, hiding a real corruption delta sitting in a large
    bucket (the failure mode the first cut of replay attribution
    measured on the lenet drill)."""
    pa = np.asarray(a["proj"], np.float64)
    pb = np.asarray(b["proj"], np.float64)
    half = len(pa) // 2 or 1
    dev = 0.0
    for sl in (slice(0, half), slice(half, None)):
        ha, hb = pa[sl], pb[sl]
        if ha.size == 0:
            continue
        scale = max(float(np.max(np.abs(ha))),
                    float(np.max(np.abs(hb))), 1e-9)
        dev = max(dev, float(np.max(np.abs(ha - hb))) / scale)
    return dev


# ------------------------------------------------------------- injection

# sdc_grad: multiply one leaf by (1 + 2^-10) — a wrong-magnitude
# gradient update, deliberately SILENT to the z-score detector at the
# default so drills exercise the agreement-audit path; ``:ARG`` (a
# float) overrides the scale for loud single-host detector drills.
SDC_GRAD_SCALE = 1.0 + 2.0 ** -10


def apply_sdc(state, spec):
    """Apply one scheduled silent corruption to the LOCAL replica of
    the first floating parameter leaf (deterministic flatten order):

    - ``sdc_grad``: scale the leaf by ``spec.arg`` (default
      ``SDC_GRAD_SCALE``) — models a corrupted gradient/update;
    - ``sdc_param``: XOR the lowest mantissa bit of element 0 — the
      one-ulp bit-flip only the fingerprint audit can see.

    Only this process's addressable replica is rebuilt
    (``make_array_from_single_device_arrays``), which is exactly how
    real SDC manifests: the global array's replicas silently disagree
    while every collective keeps matching."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    # deterministic target: the LARGEST floating leaf (a kernel, not a
    # 6-element bias — the corruption must actually flow through the
    # forward pass for the loud-scale detector drills to mean anything)
    idx = max(
        (i for i, l in enumerate(leaves)
         if np.issubdtype(np.dtype(l.dtype), np.floating)),
        key=lambda i: int(np.prod(leaves[i].shape)), default=None)
    if idx is None:
        return state
    leaf = leaves[idx]

    def mutate(arr: np.ndarray) -> np.ndarray:
        arr = np.array(arr)  # copy: never poison a shared buffer
        if spec.kind == "sdc_grad":
            scale = spec.arg if spec.arg is not None else SDC_GRAD_SCALE
            return (arr * arr.dtype.type(scale)).astype(arr.dtype)
        # sdc_param: single-bit flip (f32 leaves; other dtypes fall
        # back to the smallest representable scale nudge)
        if arr.dtype == np.float32:
            flat = arr.reshape(-1).view(np.uint32)
            flat[0] ^= np.uint32(1)
        else:
            flat = arr.reshape(-1)
            flat[0] = np.nextafter(flat[0], np.inf, dtype=arr.dtype)
        return arr

    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        bufs = [jax.device_put(mutate(np.asarray(s.data)), s.device)
                for s in leaf.addressable_shards]
        new_leaf = jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, bufs)
    else:
        new_leaf = jax.device_put(mutate(_host_local(leaf)),
                                  getattr(leaf, "sharding", None))
    leaves[idx] = new_leaf
    return state.replace(
        params=jax.tree_util.tree_unflatten(treedef, leaves))


# -------------------------------------------------------------- monitor


class SentinelMonitor:
    """The Trainer's sentinel bundle: the detector over the drained
    ``loss``/``sent_*`` series, the audit cadence, the fingerprint
    (with its sign cache), and the obs counters
    (``sentinel_trips`` / ``sentinel_audits``).

    ``audit_every`` is in RUN steps (epoch * steps_per_epoch +
    step-in-epoch — the epoch-anchored counter that makes a resumed or
    replayed window audit at the SAME points as the uninterrupted
    run). ``replay_until`` puts the Trainer in replay-bisection mode:
    train deterministically to that run step (auditing on the way),
    then stop WITHOUT saving — the supervisor reads the audit files as
    the replay's verdict."""

    WATCH_KEYS = ("loss",) + SENTINEL_KEYS

    def __init__(self, *, z_threshold: float = 8.0, warmup: int = 16,
                 audit_every: int = 16, fingerprint_seed: int = 0,
                 replay_until: int | None = None, registry=None):
        if audit_every < 1:
            raise ValueError(
                f"audit_every must be >= 1, got {audit_every}")
        self.detector = EwmaDetector(z_threshold=z_threshold,
                                     warmup=warmup)
        self.audit_every = int(audit_every)
        self.fingerprint_seed = int(fingerprint_seed)
        self.replay_until = (int(replay_until)
                             if replay_until is not None else None)
        self._signs_cache: dict = {}
        reg = registry if registry is not None else default_registry()
        self.trips = reg.counter("sentinel_trips")
        self.audits = reg.counter("sentinel_audits")
        self.scale_backoffs = reg.counter("sentinel_scale_backoffs")

    def observe(self, epoch: int, step_in_epoch: int,
                metrics: dict) -> None:
        """Fold one drained step's metrics in; raises
        :class:`SentinelTrip` on the first watched series that
        z-scores out of band.

        Mixed-precision composition (core/precision.py): a step whose
        ``mp_grads_finite`` is 0 was a dynamic-loss-scale BACKOFF — the
        overflow was caught in-graph, the update skipped and the scale
        halved, so the step's metrics are deliberately untrustworthy
        and the detector must neither trip on them nor fold them into
        its history. Counted separately (``sentinel_scale_backoffs``);
        a trip stays what it always was: an anomaly nothing handled."""
        if metrics.get("mp_grads_finite", 1.0) < 0.5:
            self.scale_backoffs.inc()
            return
        for key in self.WATCH_KEYS:
            if key not in metrics:
                continue
            z = self.detector.observe(key, metrics[key])
            if z is not None:
                self.trips.inc()
                raise SentinelTrip(epoch, step_in_epoch, key,
                                   metrics[key], z)

    def reset(self) -> None:
        self.detector.reset()

    def audit_due(self, run_step: int) -> bool:
        return run_step > 0 and run_step % self.audit_every == 0

    def fingerprint_state(self, state) -> dict:
        """Fingerprint the replicated model state (params +
        batch_stats — the tree every data-parallel host must agree on
        bit-exactly; a ZeRO-1-sharded opt_state is legitimately
        different per host and is excluded). Used by both the
        cross-host audit (which counts it via ``audits``) and the
        checkpoint manifest (which does not — manifests are not
        agreement checks)."""
        tree = {"params": state.params}
        if getattr(state, "batch_stats", None):
            tree["batch_stats"] = state.batch_stats
        return tree_fingerprint(tree, seed=self.fingerprint_seed,
                                signs_cache=self._signs_cache)
